"""NVMe layer store for ZeRO-Inference full-offload serving.

The serving analog of the reference's parameter swapper
(ref: runtime/swap_tensor/partitioned_param_swapper.py:36
AsyncPartitionedParameterSwapper — NVMe-resident fp16 params swapped in
around each module's forward over the csrc/aio thread pool;
docs/_posts/2022-09-10-zero-inference.md:52 serves OPT-30B from NVMe at
30 tok/s). Here the unit is one PREPARED serving layer:

- staging writes each layer's leaves to one file per leaf through the
  C++ aio handle (ops/aio); host RAM holds O(1) layers at any moment,
  so the model bounds at NVMe capacity, not DRAM.
- serving reads ride an `io_callback` INSIDE the compiled step: the
  callback for layer l waits on l's prefetched reads, SUBMITS reads for
  layer l+read_ahead (the async_swapper double-buffer pattern), and
  returns the host arrays, which XLA then transfers to HBM. Ordering
  against the rest of the program comes from the same
  activations-two-back dependency the pinned-host tier uses
  (inference/engine._fetch_layer) — the callback cannot be hoisted to
  program start, which for a bigger-than-HBM model would be an OOM.

Fresh buffers are allocated per read round: the returned arrays are
handed to the runtime for the HBM transfer, and reusing them for the
next prefetch round would race that transfer.
"""

import os
import shutil
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..ops.aio import AsyncIOHandle
from ..resilience.faults import fault_point
from ..utils.logging import log_dist, logger


class HostKvSpillStore:
    """Bounded pinned-host spill tier for preempted sequences' paged KV
    (the serving analog of vLLM's swap space, wired into
    scheduler._preempt under RED pressure — docs/fault_tolerance.md
    pressure section).

    Entries are `engine.export_kv` payloads: host numpy K/V page
    stacks plus the PR-9 blake2b digest envelope, so a bit flipped
    while the payload sits in host DRAM is caught by `import_kv` at
    resume and falls back to recompute. The tier is bounded in BYTES
    (`capacity_bytes`): a put that would overflow is REJECTED (returns
    False — the caller falls back to flush-and-recompute, the
    pre-spill behavior) rather than evicting someone else's spilled
    work, because every resident entry belongs to a request the
    scheduler WILL resume; unlike a cache there are no cold entries to
    sacrifice. Chaos point 'spill.io' (ctx: op put|get, key) fires
    inside both operations so the overload lane can force the
    fallback paths deterministically.

    Lock-guarded (the R003 shared-mutable class rule): the scheduler
    is single-threaded today, but the store sits next to io_callback-
    driven machinery in this file and the accounting must never
    race."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        self._entries: Dict[Any, Dict[str, Any]] = {}
        self._bytes: Dict[Any, int] = {}
        self._lock = threading.Lock()
        self.used_bytes = 0
        self.peak_bytes = 0
        self.counters: Dict[str, int] = {
            "puts": 0, "gets": 0, "rejects": 0, "discards": 0,
        }

    @staticmethod
    def payload_nbytes(payload: Dict[str, Any]) -> int:
        return sum(int(v.nbytes) for v in payload.values()
                   if isinstance(v, np.ndarray))

    def put(self, key: Any, payload: Dict[str, Any]) -> bool:
        """Admit one spilled payload. Returns False (nothing stored)
        when the byte budget cannot take it — the caller recomputes.
        May raise an InjectedFault from the 'spill.io' chaos point."""
        fault_point("spill.io", op="put", key=key)
        nbytes = self.payload_nbytes(payload)
        with self._lock:
            if key in self._entries:
                raise ValueError(f"spill key {key!r} already stored")
            if self.used_bytes + nbytes > self.capacity_bytes:
                self.counters["rejects"] += 1
                return False
            self._entries[key] = payload
            self._bytes[key] = nbytes
            self.used_bytes += nbytes
            self.peak_bytes = max(self.peak_bytes, self.used_bytes)
            self.counters["puts"] += 1
        return True

    def get(self, key: Any):
        """Pop one spilled payload (None when absent — e.g. a 'skip'
        fault suppressed the put). May raise an InjectedFault from the
        'spill.io' chaos point; the entry is dropped first so a failed
        get never wedges the byte budget."""
        with self._lock:
            payload = self._entries.pop(key, None)
            if payload is not None:
                self.used_bytes -= self._bytes.pop(key)
                self.counters["gets"] += 1
        fault_point("spill.io", op="get", key=key)
        return payload

    def restore(self, key: Any, payload: Dict[str, Any]) -> None:
        """Re-insert a payload just popped by get() whose resume could
        not land (pool transiently full) — no fault point and no put
        accounting: the entry never logically left the tier."""
        with self._lock:
            if key in self._entries:
                raise ValueError(f"spill key {key!r} already stored")
            nbytes = self.payload_nbytes(payload)
            self._entries[key] = payload
            self._bytes[key] = nbytes
            self.used_bytes += nbytes
            self.peak_bytes = max(self.peak_bytes, self.used_bytes)

    def discard(self, key: Any) -> None:
        """Drop an entry whose request will never resume here (it
        finished, shed, or moved replicas)."""
        with self._lock:
            if self._entries.pop(key, None) is not None:
                self.used_bytes -= self._bytes.pop(key)
                self.counters["discards"] += 1

    def drain(self) -> int:
        """Discard EVERY resident entry (counted per entry) and return
        how many were dropped. The replica-retirement path
        (router._maybe_release / restore_replica): once a replica
        leaves routing, no request will ever resume from its host
        tier, so anything still resident is a leak — draining here is
        what makes the lane-end quiesce audit (zero spill bytes
        fleet-wide) provable."""
        with self._lock:
            dropped = len(self._entries)
            self.counters["discards"] += dropped
            self._entries.clear()
            self._bytes.clear()
            self.used_bytes = 0
        return dropped

    def stats(self) -> Dict[str, float]:
        with self._lock:
            s = {f"spill_{k}": float(v) for k, v in self.counters.items()}
            s["spill_entries"] = float(len(self._entries))
            s["spill_used_bytes"] = float(self.used_bytes)
            s["spill_peak_bytes"] = float(self.peak_bytes)
            s["spill_capacity_bytes"] = float(self.capacity_bytes)
        return s


class NvmeLayerStore:
    """Per-leaf NVMe files + in-flight prefetch state for one engine."""

    def __init__(self, path: str, n_layers: int, n_threads: int = 4,
                 block_size: int = 1 << 20, read_ahead: int = 2,
                 io_retries: int = 3, retry_backoff_s: float = 0.01):
        tag = f"serve-rank{jax.process_index()}-{uuid.uuid4().hex[:8]}"
        self.dir = os.path.join(path, "ds_tpu_swap", tag)
        os.makedirs(self.dir, exist_ok=True)
        self.aio = AsyncIOHandle(n_threads=n_threads, block_size=block_size)
        self.n_layers = n_layers
        self.read_ahead = max(1, read_ahead)
        # per layer: list of (flat_leaf_index, file, shape, dtype)
        self._manifest: List[Optional[List[tuple]]] = [None] * n_layers
        self._treedef = None
        self._spec_tree: List[Any] = [None] * n_layers
        # layer -> list of (ticket, buf) for in-flight prefetch reads.
        # io_callback threads arrive UNORDERED (XLA may run several
        # compiled programs' callbacks concurrently), so every
        # check-then-insert on this dict is guarded by _lock — an
        # unguarded double _submit would leak an unawaited aio ticket
        # and race two reads into one buffer.
        self._inflight: Dict[int, List[tuple]] = {}
        self._lock = threading.Lock()
        # transient NVMe/filesystem hiccups heal with a bounded retry;
        # a failure that survives the budget SURFACES (raised from the
        # serving read path, logged terminally from the close drain)
        self.io_retries = max(0, int(io_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        import atexit
        import functools

        # belt for processes that never close(); close() is the braces.
        # A per-store partial so close()'s unregister removes only THIS
        # store's hook (unregister matches by function identity).
        self._cleanup = functools.partial(shutil.rmtree, self.dir,
                                          ignore_errors=True)
        atexit.register(self._cleanup)
        self._closed = False

    def _io_retry(self, fn, what: str, terminal: str = "raise"):
        """Run one aio operation with a bounded retry + exponential
        backoff (transient NVMe/fs errors heal). After the budget:
        terminal='raise' re-raises (serving reads must surface a dead
        disk, not return garbage), terminal='log' emits one error and
        returns None (the close() drain must still release the rest)."""
        for attempt in range(self.io_retries + 1):
            try:
                fault_point("offload.io", what=what)
                return fn()
            except Exception as e:
                if attempt == self.io_retries:
                    logger.error(
                        f"NVMe store: {what} failed after "
                        f"{attempt + 1} attempts: {e!r}")
                    if terminal == "raise":
                        raise
                    return None
                delay = self.retry_backoff_s * (2 ** attempt)
                logger.warning(
                    f"NVMe store: {what} hit transient error ({e!r}); "
                    f"retry {attempt + 1}/{self.io_retries} in "
                    f"{delay:.3f}s")
                time.sleep(delay)

    def close(self) -> None:
        """Drain in-flight reads, drop the aio pool, reclaim the NVMe
        space — the engine calls this when a params refresh replaces
        the store (a long-lived server cycling models must not leak a
        model copy per refresh)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            drained = list(self._inflight.values())
            self._inflight.clear()
            aio = self.aio
        # wait OUTSIDE the lock: a concurrent read_layer may hold its
        # own popped tickets and must not deadlock against the drain.
        # terminal='log': one wedged ticket must not leak the rest of
        # the pool or the NVMe directory
        for pairs in drained:
            for t, _ in pairs:
                self._io_retry(lambda t=t: aio.wait(t),
                               f"drain of ticket {t}", terminal="log")
        self.aio = None
        shutil.rmtree(self.dir, ignore_errors=True)
        import atexit

        try:
            atexit.unregister(self._cleanup)
        except ValueError:
            pass  # already unregistered (repeat close)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- staging --------------------------------------------------------
    def stage_layer(self, l: int, lp_host: Any) -> None:
        """Write one prepared layer's leaves (host numpy/jax arrays) to
        NVMe; blocks until the writes are durable so the layer's host
        memory can be released immediately."""
        leaves, treedef = jax.tree_util.tree_flatten(lp_host)
        if self._treedef is None:
            self._treedef = treedef
        rows = []
        tickets = []
        for i, leaf in enumerate(leaves):
            arr = np.ascontiguousarray(np.asarray(leaf))
            f = os.path.join(self.dir, f"l{l}_leaf{i}.bin")
            tickets.append(self.aio.async_pwrite(arr, f))
            rows.append((i, f, arr.shape, arr.dtype))
        for t in tickets:
            self._io_retry(lambda t=t: self.aio.wait(t),
                           f"staging write of layer {l}")
        # staging is strictly single-threaded and precedes any serving
        # read (finish_staging is the barrier) — no lock needed here
        self._manifest[l] = rows  # ds-lint: ok R003 single-threaded staging phase
        self._spec_tree[l] = jax.tree_util.tree_unflatten(
            treedef,
            [jax.ShapeDtypeStruct(r[2], r[3]) for r in rows],
        )

    def finish_staging(self) -> None:
        staged = [l for l, m in enumerate(self._manifest) if m is None]
        if staged:
            raise ValueError(f"layers {staged} were never staged")
        total = sum(int(np.prod(r[2]) * np.dtype(r[3]).itemsize)
                    for m in self._manifest for r in m)
        log_dist(
            f"NVMe serving tier: {self.n_layers} layers, "
            f"{total / 2**30:.2f} GiB under {self.dir} "
            f"(read_ahead={self.read_ahead})", ranks=[0],
        )

    def layer_specs(self, l: int) -> Any:
        return self._spec_tree[l]

    # -- serving reads --------------------------------------------------
    def _submit_locked(self, l: int) -> None:
        """Caller holds _lock. Idempotent per layer: the in-flight map
        is the dedup, so two callback threads can never double-submit a
        layer (which would leak the first submission's tickets)."""
        if self._closed or l in self._inflight:
            return
        pairs = []
        for _, f, shape, dtype in self._manifest[l]:
            buf = np.empty(shape, dtype)
            pairs.append((self.aio.async_pread(buf, f), buf))
        self._inflight[l] = pairs

    def _submit(self, l: int) -> None:
        with self._lock:
            self._submit_locked(l)

    def read_layer(self, l: int) -> Any:
        """Blocking read of layer l (waits on its prefetch if in flight),
        then submits prefetch for the next read_ahead layers — called
        from the step's io_callback, so the wait overlaps the PREVIOUS
        layer's device compute. Thread-safe: unordered io_callback
        threads take the lock only for in-flight-map mutation; aio waits
        happen outside it."""
        with self._lock:
            if self._closed:
                raise RuntimeError("NvmeLayerStore is closed")
            self._submit_locked(l)
            pairs = self._inflight.pop(l)
            aio = self.aio
        for t, _ in pairs:
            # transient I/O heals here; a persistent failure raises out
            # of the serving step (a dead disk must never return a
            # zero-filled layer as weights)
            self._io_retry(lambda t=t: aio.wait(t),
                           f"read of layer {l}")
        # decode walks layers cyclically (every step re-streams the
        # model): prefetch wraps around
        with self._lock:
            if not self._closed:
                for d in range(1, self.read_ahead + 1):
                    self._submit_locked((l + d) % self.n_layers)
        return jax.tree_util.tree_unflatten(self._treedef,
                                            [b for _, b in pairs])
