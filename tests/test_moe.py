"""MoE / expert-parallelism tests.

Ref model: tests/unit/moe/test_moe.py (gating correctness, EP-size
invariance) — here layout-equivalence is trajectory equality on the
virtual 8-device mesh, and gating is unit-tested against the GShard
invariants (capacity enforcement, renormalization, aux loss at uniform).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.moe import compute_capacity, top1_gating, top2_gating

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow

VOCAB = 128


def model_cfg(**kw):
    base = dict(vocab_size=VOCAB, n_layers=2, n_heads=4, d_model=64, max_seq=32,
                variant="llama", use_flash=False, n_experts=4, moe_top_k=1,
                moe_capacity_factor=2.0)
    base.update(kw)
    return T.TransformerConfig(**base)


def ds_config(**kw):
    base = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "seed": 7,
        "steps_per_print": 1000,
    }
    base.update(kw)
    return base


def build_engine(mcfg, **cfg_kw):
    return ds.initialize(
        ds_config(**cfg_kw),
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
    )


def data(n=3, batch=16, seq=33, seed=0):
    r = np.random.default_rng(seed)
    return [{"tokens": r.integers(0, VOCAB, (batch, seq)).astype(np.int32)} for _ in range(n)]


class TestGating:
    def test_top1_capacity_enforced(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)
        combine, dispatch, _ = top1_gating(logits, capacity_factor=1.0, min_capacity=1)
        C = compute_capacity(64, 4, 1.0, 1)
        assert dispatch.shape == (64, 4, C)
        # No expert slot used twice.
        slot_use = jnp.sum(dispatch, axis=0)  # [X, C]
        assert int(slot_use.max()) <= 1
        # Per-expert token count <= capacity.
        assert int(jnp.sum(dispatch, axis=(0, 2)).max()) <= C

    def test_top1_skewed_logits_drop_tokens(self):
        # All tokens want expert 0 → only C survive, rest have zero combine.
        logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0, 0.0]]), (32, 1))
        combine, dispatch, _ = top1_gating(logits, capacity_factor=1.0, min_capacity=1)
        C = compute_capacity(32, 4, 1.0, 1)
        kept = jnp.sum(dispatch)
        assert int(kept) == C
        dropped_rows = jnp.sum(combine, axis=(1, 2)) == 0
        assert int(jnp.sum(dropped_rows)) == 32 - C

    def test_top1_aux_loss_uniform_is_one(self):
        # Uniform gates and uniform assignment → l_aux == 1.0 exactly.
        logits = jnp.zeros((32, 4), jnp.float32)
        # break argmax ties round-robin by epsilon bumps
        bump = jax.nn.one_hot(jnp.arange(32) % 4, 4) * 1e-4
        _, _, l_aux = top1_gating(logits + bump, capacity_factor=4.0)
        np.testing.assert_allclose(float(l_aux), 1.0, rtol=1e-3)

    def test_top2_combine_renormalized(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        combine, dispatch, _ = top2_gating(logits, capacity_factor=4.0)
        # With ample capacity every token keeps 2 experts, weights sum to 1.
        per_token = jnp.sum(combine, axis=(1, 2))
        np.testing.assert_allclose(np.asarray(per_token), 1.0, atol=1e-5)
        assert int(jnp.sum(dispatch, axis=(1, 2)).min()) == 2

    def test_noisy_gate_policies(self):
        logits = jnp.zeros((16, 4), jnp.float32)
        for policy in ("RSample", "Jitter"):
            c, d, a = top1_gating(
                logits, capacity_factor=4.0, rng=jax.random.PRNGKey(0),
                noisy_gate_policy=policy,
            )
            assert np.isfinite(float(a))
        with pytest.raises(ValueError):
            top1_gating(logits, rng=jax.random.PRNGKey(0), noisy_gate_policy="bogus")


class TestMoETraining:
    def test_loss_decreases(self):
        engine = build_engine(model_cfg())
        batch = data(1)[0]
        ls = [engine.train_batch(batch)["loss"] for _ in range(8)]
        assert ls[-1] < ls[0]

    def test_expert_params_sharded(self):
        engine = build_engine(model_cfg(), mesh={"data": 4, "expert": 2})
        w = engine.state.params["layers"]["w_in"]  # [L, X, E, F]
        assert "expert" in str(w.sharding.spec)

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_ep_layout_equivalence(self, top_k):
        """EP=1 vs EP=2 is a layout change only — same trajectory
        (ref: the expert group is carved out of the DP world,
        utils/groups.py:113)."""
        mcfg = model_cfg(moe_top_k=top_k)
        base = build_engine(mcfg, mesh={"data": -1}, train_batch_size=16)
        base_losses = [base.train_batch(b)["loss"] for b in data()]
        ep = build_engine(mcfg, mesh={"data": 4, "expert": 2}, train_batch_size=16)
        ep_losses = [ep.train_batch(b)["loss"] for b in data()]
        np.testing.assert_allclose(ep_losses, base_losses, rtol=2e-4)

    def test_capacity_overflow_still_trains(self):
        # Tiny capacity factor: most tokens dropped, residual carries them.
        mcfg = model_cfg(moe_capacity_factor=0.25, moe_min_capacity=1)
        engine = build_engine(mcfg)
        out = engine.train_batch(data(1)[0])
        assert np.isfinite(out["loss"])

    def test_moe_gpt2_variant(self):
        mcfg = model_cfg(variant="gpt2", moe_top_k=2)
        engine = build_engine(mcfg)
        out = engine.train_batch(data(1)[0])
        assert np.isfinite(out["loss"])

    def test_aux_loss_contributes(self):
        """moe_aux_loss_coef shifts the total loss."""
        mcfg_on = model_cfg(moe_aux_loss_coef=10.0)
        mcfg_off = model_cfg(moe_aux_loss_coef=0.0)
        b = data(1)[0]
        on = build_engine(mcfg_on).train_batch(b)["loss"]
        off = build_engine(mcfg_off).train_batch(b)["loss"]
        assert on > off


class TestPRMoE:
    """PR-MoE / residual MoE (ref: moe/layer.py:29 use_residual, arXiv
    2201.05596): moe(h)*c0 + dense(h)*c1 with a learned softmax mix."""

    def _engine(self, **kw):
        mcfg = model_cfg(moe_use_residual=True, **kw)
        return mcfg, ds.initialize(
            ds_config(mesh={"expert": 2, "data": 4}),
            loss_fn=T.make_loss_fn(mcfg, loss_chunks=1),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg))

    def test_residual_params_exist_and_train(self):
        mcfg, eng = self._engine()
        L = eng.state.params["layers"]
        for name in ("wr_in", "wr_out", "wr_gate", "w_coef", "b_coef"):
            assert name in L, name
        r = np.random.default_rng(0)
        b = {"tokens": r.integers(
            0, VOCAB, (eng.config.train_batch_size, 33)).astype(np.int32)}
        ls = [eng.train_batch(b)["loss"] for _ in range(8)]
        assert all(np.isfinite(l) for l in ls)
        assert min(ls[4:]) < ls[0]

    def test_residual_changes_forward(self):
        """With the coefficient biased toward the dense expert, the
        residual branch demonstrably participates (zeroing wr_out must
        change logits)."""
        mcfg = model_cfg(moe_use_residual=True)
        params = T.init(mcfg, jax.random.PRNGKey(0))
        toks = jnp.asarray(np.random.default_rng(0)
                           .integers(0, VOCAB, (1, 8)))
        base = T.forward(params, toks, mcfg)
        p2 = dict(params)
        p2["layers"] = dict(params["layers"])
        p2["layers"]["wr_out"] = jnp.zeros_like(params["layers"]["wr_out"])
        alt = T.forward(p2, toks, mcfg)
        assert not np.allclose(np.asarray(base), np.asarray(alt))

    def test_serving_matches_training_forward(self):
        """PR-MoE serves: engine prefill logits == T.forward next-token
        logits (capacity-free serving == training where nothing drops;
        capacity_factor is high enough here that nothing does)."""
        from deepspeed_tpu.inference import init_inference

        mcfg = model_cfg(moe_use_residual=True, moe_capacity_factor=4.0)
        params = T.init(mcfg, jax.random.PRNGKey(1))
        eng = init_inference(
            params, mcfg,
            dict(max_seq_len=32, kv_block_size=8, num_kv_blocks=16,
                 min_prefill_bucket=8, max_batch_size=4),
            dtype=jnp.float32)
        r = np.random.default_rng(0)
        prompt = r.integers(0, VOCAB, 9).astype(np.int32)
        out = eng.put([0], [prompt.copy()])
        with jax.default_matmul_precision("highest"):
            ref = np.asarray(
                T.forward(params, jnp.asarray(prompt[None]), mcfg)[0, -1])
        np.testing.assert_allclose(out[0], ref, rtol=2e-2, atol=2e-2)
