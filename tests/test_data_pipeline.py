"""Curriculum learning + random-LTD tests.

Ref model: tests/unit/runtime (curriculum scheduler math) and the
random-LTD invariant: dropped tokens bypass the LTD layers unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler,
    RandomLTDScheduler,
    truncate_to_seqlen,
)

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow

VOCAB = 128


class TestCurriculumScheduler:
    def test_fixed_linear(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(50) == 32  # 8 + 0.5*56 = 36 → floor to 8-step
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(10**6) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8, "root_degree": 2},
        })
        # sqrt schedule grows faster early than linear
        assert s.get_difficulty(25) >= 8 + (64 - 8) // 4

    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 32,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [8, 16, 32],
                                "max_step": [10, 20, 30]},
        })
        assert s.get_difficulty(5) == 8
        assert s.get_difficulty(15) == 16
        assert s.get_difficulty(99) == 32

    def test_custom(self):
        s = CurriculumScheduler({
            "min_difficulty": 1, "max_difficulty": 100,
            "schedule_type": "custom",
        })
        s.set_custom_get_difficulty(lambda step: min(step, 100))
        assert s.update_difficulty(42) == 42

    def test_state_roundtrip(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        s.update_difficulty(50)
        st = s.get_state()
        s2 = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        })
        s2.set_state(st)
        assert s2.current == s.current


class TestCurriculumEngine:
    def test_seqlen_curriculum_truncates(self):
        mcfg = T.TransformerConfig(vocab_size=VOCAB, n_layers=2, n_heads=4,
                                   d_model=64, max_seq=64, variant="llama",
                                   use_flash=False)
        engine = ds.initialize(
            {
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "curriculum_learning": {
                    "enabled": True, "curriculum_type": "seqlen",
                    "min_difficulty": 8, "max_difficulty": 32,
                    "schedule_type": "fixed_discrete",
                    "schedule_config": {"difficulty": [8, 32],
                                        "max_step": [2, 4]},
                },
                "steps_per_print": 1000,
            },
            loss_fn=T.make_loss_fn(mcfg, loss_chunks=1),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg),
        )
        r = np.random.default_rng(0)
        batch = {"tokens": r.integers(0, VOCAB, (16, 65)).astype(np.int32)}
        for _ in range(4):
            assert np.isfinite(engine.train_batch(batch)["loss"])
        # two difficulty levels → two compiled programs
        assert len(engine._train_compiled_cache) == 2


class TestRandomLTD:
    def test_dropped_tokens_bypass_ltd_layers(self):
        """With zeroed LTD-layer weights, kept tokens change only via the
        residual path; dropped tokens must be EXACTLY unchanged."""
        cfg = T.TransformerConfig(vocab_size=VOCAB, n_layers=4, n_heads=4,
                                  d_model=64, max_seq=32, variant="llama",
                                  use_flash=False,
                                  random_ltd_layer_range=(1, 3))
        params = T.init(cfg, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
        idx = jnp.stack([jnp.array([0, 2, 5, 9, 12, 15]),
                         jnp.array([1, 3, 4, 8, 10, 14])]).astype(jnp.int32)

        full = T.forward_hidden(params, toks, cfg)
        ltd = T.forward_hidden(params, toks, cfg, ltd_idx=idx)
        assert ltd.shape == full.shape
        assert not np.allclose(np.asarray(ltd), np.asarray(full))

        # zero the LTD layers' output projections → LTD segment is a no-op
        z = jax.tree.map(lambda x: x, params)
        for name in ("wo", "w_out"):
            z["layers"][name] = z["layers"][name].at[1:3].set(0.0)
        a = T.forward_hidden(z, toks, cfg, ltd_idx=idx)
        b = T.forward_hidden(z, toks, cfg)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_scheduler_and_training(self):
        cfg = T.TransformerConfig(vocab_size=VOCAB, n_layers=4, n_heads=4,
                                  d_model=64, max_seq=32, variant="llama",
                                  use_flash=False,
                                  random_ltd_layer_range=(1, 3))
        engine = ds.initialize(
            {"train_micro_batch_size_per_gpu": 2,
             "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
             "steps_per_print": 1000},
            loss_fn=T.make_loss_fn(cfg, loss_chunks=1),
            param_init_fn=lambda k: T.init(cfg, k),
            param_logical_specs=T.logical_specs(cfg),
        )
        sched = RandomLTDScheduler(min_tokens=16, max_tokens=32,
                                   total_steps=4, step_size=16)
        r = np.random.default_rng(0)
        for step in range(4):
            batch = {"tokens": r.integers(0, VOCAB, (16, 33)).astype(np.int32)}
            batch = sched.apply(batch, step)
            if step < 2:
                assert batch["random_ltd"].shape == (16, 16)
            loss = engine.train_batch(batch)["loss"]
            assert np.isfinite(loss)

    def test_truncate_to_seqlen(self):
        b = truncate_to_seqlen({"tokens": np.zeros((4, 65), np.int32)}, 16)
        assert b["tokens"].shape == (4, 17)


class TestProgressiveLayerDrop:
    """PLD (ref: runtime/progressive_layer_drop.py, arXiv 2010.13369)."""

    def _build(self, **cfg_kw):
        import deepspeed_tpu as ds
        from deepspeed_tpu.models import transformer as T

        mcfg = T.TransformerConfig(vocab_size=128, n_layers=4, n_heads=4,
                                   d_model=64, max_seq=32, variant="llama",
                                   use_flash=False)
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
               "seed": 7, "steps_per_print": 1000}
        cfg.update(cfg_kw)
        return ds.initialize(cfg, loss_fn=T.make_loss_fn(mcfg),
                             param_init_fn=lambda k: T.init(mcfg, k),
                             param_logical_specs=T.logical_specs(mcfg))

    def _data(self, n=6):
        r = np.random.default_rng(0)
        return [{"tokens": r.integers(0, 128, (16, 33)).astype(np.int32)}
                for _ in range(n)]

    def test_gamma_zero_keeps_every_layer(self):
        """Behavioral check of the engine's theta schedule: with gamma=0,
        theta(t) = (1-θ)·e^0 + θ = 1 forever — keep prob 1 for every
        layer, so the PLD engine's trajectory must EQUAL the dense
        engine's. A sign/argument regression in the schedule breaks
        this."""
        batches = self._data(4)
        dense = self._build()
        pld = self._build(progressive_layer_drop={
            "enabled": True, "theta": 0.5, "gamma": 0.0})
        ld = [dense.train_batch(b)["loss"] for b in batches]
        lp = [pld.train_batch(b)["loss"] for b in batches]
        np.testing.assert_allclose(lp, ld, rtol=1e-6)

    def test_pld_trains_and_differs_from_dense(self):
        batches = self._data()
        dense = self._build()
        pld = self._build(progressive_layer_drop={
            "enabled": True, "theta": 0.3, "gamma": 1.0})  # fast decay
        ld = [dense.train_batch(b)["loss"] for b in batches]
        lp = [pld.train_batch(b)["loss"] for b in batches]
        assert all(np.isfinite(l) for l in lp)
        assert lp[-1] < lp[0]  # still converges with dropped layers
        # after theta decays, layers ARE being dropped -> trajectories split
        assert any(abs(a - b) > 1e-6 for a, b in zip(ld[1:], lp[1:]))

    def test_eval_keeps_all_layers(self):
        """rng=None in eval disables PLD — eval losses are deterministic
        and equal a dense engine's eval at identical params."""
        pld = self._build(progressive_layer_drop={
            "enabled": True, "theta": 0.3, "gamma": 1.0})
        dense = self._build()
        b = self._data(1)[0]
        assert pld.eval_batch(b) == pld.eval_batch(b)
        np.testing.assert_allclose(pld.eval_batch(b), dense.eval_batch(b),
                                   rtol=1e-6)

    def test_pld_incompatible_paths_raise(self):
        import pytest as _pytest

        with _pytest.raises(NotImplementedError, match="progressive"):
            self._build(progressive_layer_drop={"enabled": True},
                        optimizer={"type": "OneBitAdam",
                                   "params": {"lr": 1e-3, "freeze_step": 5}})
