"""Mixture-of-Experts with expert parallelism (GShard/Switch-style).

TPU-native redesign of the reference MoE stack
(ref: deepspeed/moe/sharded_moe.py — top1gating:180, top2gating:278,
_AllToAll:95, MOELayer:421; deepspeed/moe/layer.py MoE:17; expert/data
group carving deepspeed/utils/groups.py:113).

Where the reference dispatches tokens with an explicit
torch.distributed all-to-all autograd function between einsums, here
dispatch/combine are einsums against a one-hot dispatch tensor plus a
sharding constraint putting the experts dim on the 'expert' mesh axis —
the XLA SPMD partitioner emits the all-to-all pair in forward and its
transpose in backward. The expert axis is carved out of the
data-parallel world exactly like the reference (batch shards over
data×expert; expert weights shard over 'expert'), so EP size never
changes the global math — only the layout.

All gating math runs in fp32 regardless of compute dtype (the reference
casts gate inputs to fp32 at sharded_moe.py TopKGate.forward).
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def compute_capacity(
    num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int = 4
) -> int:
    """Static per-expert token capacity
    (ref: sharded_moe.py _capacity — ceil(tokens/experts * factor))."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def _load_balance_loss(gates, mask):
    """l_aux = E * Σ_e mean_t(gate_e) · mean_t(assigned_e)  — 1.0 at uniform
    (ref: sharded_moe.py top1gating l_aux)."""
    num_experts = gates.shape[-1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask.astype(jnp.float32), axis=0)
    return num_experts * jnp.sum(me * ce)


def _apply_noise(logits, rng, policy: Optional[str]):
    """Noisy gating (ref: sharded_moe.py multiplicative_jitter / RSample
    noisy_gate_policy). No-op when rng is None (eval) or policy unset."""
    if rng is None or policy is None:
        return logits
    if policy == "RSample":
        return logits + jax.random.normal(rng, logits.shape, logits.dtype)
    if policy == "Jitter":
        eps = 1e-2
        return logits * jax.random.uniform(
            rng, logits.shape, logits.dtype, 1.0 - eps, 1.0 + eps
        )
    raise ValueError(f"unknown noisy_gate_policy {policy!r}")


def top1_gating(
    logits,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng=None,
    noisy_gate_policy: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Switch-style top-1 gating (ref: sharded_moe.py top1gating:180).

    logits: [T, X] router outputs (any float dtype; math is fp32).
    Returns (combine [T,X,C] fp32, dispatch [T,X,C] bool, l_aux scalar).
    Tokens beyond an expert's capacity are dropped (their combine row is
    zero — the residual connection around the MoE block carries them).
    """
    T, X = logits.shape
    C = compute_capacity(T, X, capacity_factor, min_capacity)
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    noisy = _apply_noise(logits, rng, noisy_gate_policy)
    index = jnp.argmax(noisy, axis=-1)  # [T]
    mask = _one_hot(index, X)  # [T, X]

    l_aux = _load_balance_loss(gates, mask)

    # Position of each token within its expert's queue; drop overflows.
    locations = jnp.cumsum(mask, axis=0) - mask  # [T, X], fp32 counts
    locations = jnp.sum(locations * mask, axis=-1).astype(jnp.int32)  # [T]
    keep = (locations < C) & (mask.sum(-1) > 0).astype(bool)
    gate_val = jnp.sum(gates * mask, axis=-1)  # [T]

    dispatch = (
        mask[:, :, None] * _one_hot(locations, C)[:, None, :]
    ) * keep[:, None, None]
    combine = dispatch * gate_val[:, None, None]
    return combine, dispatch > 0, l_aux


def top2_gating(
    logits,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng=None,
    noisy_gate_policy: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """GShard-style top-2 gating (ref: sharded_moe.py top2gating:278).

    Second choice is the argmax after masking the first; gate values of
    the two kept experts are renormalized to sum to 1.
    """
    T, X = logits.shape
    C = compute_capacity(T, X, capacity_factor * 2.0, min_capacity)
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    noisy = _apply_noise(logits, rng, noisy_gate_policy)
    index1 = jnp.argmax(noisy, axis=-1)
    mask1 = _one_hot(index1, X)
    masked = jnp.where(mask1 > 0, -jnp.inf, noisy)
    index2 = jnp.argmax(masked, axis=-1)
    mask2 = _one_hot(index2, X)

    l_aux = _load_balance_loss(gates, mask1)

    loc1 = jnp.cumsum(mask1, axis=0) - mask1
    # Second-choice tokens queue after all first-choice tokens per expert.
    loc2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    pos1 = jnp.sum(loc1 * mask1, axis=-1).astype(jnp.int32)
    pos2 = jnp.sum(loc2 * mask2, axis=-1).astype(jnp.int32)
    keep1 = pos1 < C
    keep2 = pos2 < C

    g1 = jnp.sum(gates * mask1, axis=-1) * keep1
    g2 = jnp.sum(gates * mask2, axis=-1) * keep2
    denom = jnp.maximum(g1 + g2, jnp.finfo(jnp.float32).eps)
    g1, g2 = g1 / denom, g2 / denom

    d1 = (mask1[:, :, None] * _one_hot(pos1, C)[:, None, :]) * keep1[:, None, None]
    d2 = (mask2[:, :, None] * _one_hot(pos2, C)[:, None, :]) * keep2[:, None, None]
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    dispatch = (d1 + d2) > 0
    return combine, dispatch, l_aux


def topk_gating(logits, top_k: int, **kw):
    if top_k == 1:
        return top1_gating(logits, **kw)
    if top_k == 2:
        return top2_gating(logits, **kw)
    raise ValueError(f"moe top_k must be 1 or 2, got {top_k}")


def moe_ffn(
    tokens,  # [T, E] flattened tokens, compute dtype
    router_w,  # [E, X]
    expert_fn,  # ([X, C, E] expert-major inputs) -> [X, C, E] outputs
    *,
    top_k: int = 1,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng=None,
    noisy_gate_policy: Optional[str] = None,
    shard=None,  # fn(x, *logical_spec) applying a sharding constraint
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch→expert→combine core (ref: sharded_moe.py MOELayer.forward:421).

    The einsum pair around `expert_fn` contracts the token dim (sharded
    over data×expert) into the experts dim (sharded over 'expert') and
    back — under SPMD that IS the reference's all-to-all pair
    (ref: _AllToAll:95), chosen by the XLA partitioner instead of issued
    by hand. Returns (output [T, E], l_aux).
    """
    dtype = tokens.dtype
    logits = tokens.astype(jnp.float32) @ router_w.astype(jnp.float32)  # [T, X]
    combine, dispatch, l_aux = topk_gating(
        logits,
        top_k,
        capacity_factor=capacity_factor,
        min_capacity=min_capacity,
        rng=rng,
        noisy_gate_policy=noisy_gate_policy,
    )
    x = jnp.einsum("txc,te->xce", dispatch.astype(dtype), tokens)
    if shard is not None:
        x = shard(x, "expert", None, None)
    y = expert_fn(x)  # [X, C, E]
    if shard is not None:
        y = shard(y, "expert", None, None)
    out = jnp.einsum("txc,xce->te", combine.astype(dtype), y)
    return out, l_aux
