"""Config-space autotuner.

TPU-native redesign of the reference autotuner
(ref: deepspeed/autotuning/autotuner.py Autotuner:42, tune():404 — which
launches short profiling JOBS per candidate config through the launcher,
writes per-experiment result dirs, and picks the best metric;
model-info profile run :663, micro-batch search :741-851).

On TPU a "job" collapses into an in-process build+compile+measure: each
candidate config constructs an engine over the same mesh, runs a few
timed steps (compile excluded), and is scored by throughput. What the
reference pays in process restarts we pay in recompiles — seconds, not
minutes. Memory-infeasible candidates surface as XLA RESOURCE_EXHAUSTED
and are skipped, exactly like the reference's OOM-pruned experiments.

The search space mirrors the reference's fast mode: ZeRO stages ×
micro-batch sizes (doubling from 1 until failure or the cap), GAS fixed
by the batch triangle.
"""

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger


class Autotuner:
    def __init__(
        self,
        base_config: Dict[str, Any],
        loss_fn: Callable,
        param_init_fn: Callable,
        param_logical_specs: Any = None,
        make_batch: Optional[Callable[[int], Any]] = None,
        results_dir: Optional[str] = None,
    ):
        """make_batch(global_batch_size) -> host batch pytree for one step."""
        self.base_config = dict(base_config)
        at_block = self.base_config.pop("autotuning", {}) or {}
        self.metric = at_block.get("metric", "throughput")
        self.fast = at_block.get("fast", True)
        self.results_dir = results_dir or at_block.get(
            "results_dir", "autotuning_results"
        )
        self.loss_fn = loss_fn
        self.param_init_fn = param_init_fn
        self.param_logical_specs = param_logical_specs
        self.make_batch = make_batch
        self.results: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------
    def model_info(self) -> Dict[str, Any]:
        """Param count + per-step flops of the base config (ref:
        autotuner.py model-info profile run :663 — there a whole job,
        here eval_shape + one compile's cost analysis)."""
        import jax
        import numpy as np

        rng = jax.random.PRNGKey(0)
        shapes = jax.eval_shape(self.param_init_fn, rng)
        n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
        return {"num_params": n_params}

    def _measure(self, config: Dict[str, Any], steps: int) -> Dict[str, Any]:
        import deepspeed_tpu as ds

        t_build = time.perf_counter()
        engine = ds.initialize(
            config,
            loss_fn=self.loss_fn,
            param_init_fn=self.param_init_fn,
            param_logical_specs=self.param_logical_specs,
        )
        batch = self.make_batch(engine.config.train_batch_size)
        engine.train_batch(batch)  # compile + warmup
        compile_s = time.perf_counter() - t_build
        t0 = time.perf_counter()
        for _ in range(steps):
            engine.train_batch(batch)
        dt = (time.perf_counter() - t0) / steps
        return {
            "step_time_s": dt,
            "samples_per_sec": engine.config.train_batch_size / dt,
            "compile_s": compile_s,
        }

    def tune(
        self,
        zero_stages: Sequence[int] = (0, 1, 2, 3),
        micro_batch_sizes: Optional[Sequence[int]] = None,
        steps: int = 3,
        max_micro_batch: int = 64,
    ) -> Dict[str, Any]:
        """Grid/fast search → best config dict (ref: autotuner.py tune:404).

        Results (including failures) land in <results_dir>/exps.jsonl —
        the per-experiment record the reference writes per exp dir.
        """
        if self.make_batch is None:
            raise ValueError("Autotuner needs make_batch to generate step data")
        if micro_batch_sizes is None:
            mbs: List[int] = []
            m = 1
            while m <= max_micro_batch:
                mbs.append(m)
                m *= 2
        else:
            mbs = list(micro_batch_sizes)

        best = None
        for stage in zero_stages:
            stage_failed = 0
            for mb in mbs:
                cfg = json.loads(json.dumps(self.base_config))
                cfg.setdefault("zero_optimization", {})["stage"] = stage
                cfg["train_micro_batch_size_per_gpu"] = mb
                cfg.pop("train_batch_size", None)
                exp = {"zero_stage": stage, "micro_batch_size": mb}
                try:
                    exp.update(self._measure(cfg, steps))
                    exp["ok"] = True
                except Exception as e:  # OOM / infeasible shape / bad combo
                    exp.update({"ok": False, "error": f"{type(e).__name__}: {e}"})
                    stage_failed += 1
                self.results.append(exp)
                log_dist(f"autotune exp: {exp}", ranks=[0])
                if exp.get("ok") and (
                    best is None
                    or exp["samples_per_sec"] > best["samples_per_sec"]
                ):
                    best = dict(exp)
                if self.fast and not exp.get("ok") and stage_failed >= 2:
                    break  # larger micro batches only get worse (OOM wall)

        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "exps.jsonl"), "w") as f:
            for r in self.results:
                f.write(json.dumps(r) + "\n")

        if best is None:
            raise RuntimeError(
                f"autotuning found no feasible config; see {self.results_dir}"
            )
        tuned = json.loads(json.dumps(self.base_config))
        tuned.setdefault("zero_optimization", {})["stage"] = best["zero_stage"]
        tuned["train_micro_batch_size_per_gpu"] = best["micro_batch_size"]
        tuned.pop("train_batch_size", None)
        log_dist(
            f"autotune best: stage={best['zero_stage']} micro={best['micro_batch_size']} "
            f"({best['samples_per_sec']:.1f} samples/s)",
            ranks=[0],
        )
        return tuned
