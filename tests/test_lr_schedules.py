"""LR schedule tests (ref model: tests for runtime/lr_schedules.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    build_schedule,
    one_cycle,
    warmup_cosine_lr,
    warmup_decay_lr,
    warmup_lr,
)


def f(sched, step):
    return float(sched(jnp.int32(step)))


def test_warmup_reaches_max():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1e-2, warmup_num_steps=100)
    assert f(s, 0) == pytest.approx(0.0, abs=1e-8)
    assert f(s, 100) == pytest.approx(1e-2, rel=1e-5)
    assert f(s, 1000) == pytest.approx(1e-2, rel=1e-5)


def test_warmup_linear_monotone():
    s = warmup_lr(warmup_max_lr=1e-2, warmup_num_steps=50, warmup_type="linear")
    vals = [f(s, i) for i in range(0, 60, 10)]
    assert all(b >= a for a, b in zip(vals, vals[1:]))


def test_warmup_decay_hits_zero():
    s = warmup_decay_lr(total_num_steps=200, warmup_max_lr=1e-2, warmup_num_steps=20)
    assert f(s, 200) == pytest.approx(0.0, abs=1e-6)
    assert f(s, 20) == pytest.approx(1e-2, rel=1e-4)


def test_warmup_cosine_endpoints():
    s = warmup_cosine_lr(total_num_steps=100, warmup_num_steps=10, lr=1e-2, cos_min_ratio=0.1)
    assert f(s, 10) == pytest.approx(1e-2, rel=1e-3)
    assert f(s, 100) == pytest.approx(1e-3, rel=1e-2)


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=1e-4, cycle_max_lr=1e-2, cycle_first_step_size=10)
    assert f(s, 0) == pytest.approx(1e-4, rel=1e-4)
    assert f(s, 10) == pytest.approx(1e-2, rel=1e-4)
    assert f(s, 20) == pytest.approx(1e-4, rel=1e-2)


def test_build_schedule_reference_names():
    s = build_schedule("WarmupLR", {"warmup_max_lr": 1e-3, "warmup_num_steps": 5})
    assert f(s, 5) == pytest.approx(1e-3, rel=1e-4)


def test_warmup_cosine_uses_optimizer_lr():
    # reference semantics: WarmupCosineLR scales the optimizer lr
    s = build_schedule(
        "WarmupCosineLR", {"total_num_steps": 100, "warmup_num_steps": 10}, base_lr=6e-4
    )
    assert f(s, 10) == pytest.approx(6e-4, rel=1e-3)


def test_build_schedule_none_is_constant():
    s = build_schedule(None, base_lr=3e-4)
    assert f(s, 0) == f(s, 1000) == pytest.approx(3e-4, rel=1e-6)


def test_build_schedule_unknown():
    with pytest.raises(ValueError):
        build_schedule("NoSuchLR", {})


def test_warmup_decay_respects_min_lr():
    """ADVICE r1: decay must end at warmup_min_lr, not 0 (reference
    WarmupDecayLR returns min + (max-min)*gamma)."""
    s = warmup_decay_lr(total_num_steps=100, warmup_min_lr=1e-4,
                        warmup_max_lr=1e-2, warmup_num_steps=10)
    assert f(s, 100) == pytest.approx(1e-4, rel=1e-4)
    assert f(s, 55) == pytest.approx(1e-4 + (1e-2 - 1e-4) * 0.5, rel=1e-4)
