#!/usr/bin/env python
"""Inference benchmark: decode throughput + prefill latency (TTFT) for
the flagship 350M Llama-class model on one chip.

The FastGen-class serving numbers (BASELINE.md rows 6-8) are for 70B on
4xA100; this records the single-v5e-chip equivalent for OUR flagship so
rounds can track regressions. Times the compiled decode/prefill steps
device-side (through the axon tunnel, engine-level put() timing is
dominated by the ~90ms host-readback round trip of the logits, which
real deployments don't pay per token). Prints one JSON line."""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference import model as M
    from deepspeed_tpu.inference import init_inference
    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.platform.accelerator import bench_device_guard

    # backend-init timeouts are flaky infra (BENCH_r04/r05): retry with
    # backoff, then emit an infra_flake-marked line instead of hanging
    rc = bench_device_guard("llama_350m_decode_tokens_per_sec")
    if rc is not None:
        return rc

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        mcfg = T.TransformerConfig(
            vocab_size=32000, n_layers=24, n_heads=8, d_model=1024,
            max_seq=2048, variant="llama", use_flash=True,
        )
        batch, ctx_len, steps, blocks = 64, 512, 50, 1024
    else:
        mcfg = T.TransformerConfig(
            vocab_size=512, n_layers=2, n_heads=4, d_model=128,
            max_seq=256, variant="llama", use_flash=False,
        )
        batch, ctx_len, steps, blocks = 4, 32, 4, 64

    params = jax.jit(
        lambda k: jax.tree.map(lambda x: x.astype(jnp.bfloat16), T.init(mcfg, k))
    )(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    NB = 2048 // 128

    def readback(x):
        return np.asarray(jax.tree.leaves(x)[0].ravel()[:1])

    # device-side decode step
    cache = M.init_cache(mcfg, blocks, 128, jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, blocks, (batch, NB)).astype(np.int32))
    toks = jnp.asarray(rng.integers(0, mcfg.vocab_size, batch).astype(np.int32))
    ctx = jnp.full((batch,), ctx_len, jnp.int32)
    step = jax.jit(
        lambda p, c, t, tb, cx: M.decode_step(p, c, t, tb, cx, mcfg, on_tpu),
        donate_argnums=(1,),
    )
    logits, cache = step(params, cache, toks, tables, ctx)
    readback(logits)
    t0 = time.perf_counter()
    for _ in range(steps):
        logits, cache = step(params, cache, toks, tables, ctx)
    readback(logits)
    dt = (time.perf_counter() - t0) / steps
    tok_s = batch / dt

    # device-side prefill (TTFT component)
    pre = jax.jit(
        lambda p, c, t, n, tb: M.prefill_step(p, c, t, n, tb, mcfg, on_tpu),
        donate_argnums=(1,),
    )
    ptoks = jnp.asarray(rng.integers(0, mcfg.vocab_size, ctx_len).astype(np.int32))
    table1 = jnp.arange(NB, dtype=jnp.int32)
    lg, cache = pre(params, cache, ptoks, jnp.int32(ctx_len), table1)
    readback(lg)
    t0 = time.perf_counter()
    for _ in range(max(steps // 5, 2)):
        lg, cache = pre(params, cache, ptoks, jnp.int32(ctx_len), table1)
    readback(lg)
    ttft = (time.perf_counter() - t0) / max(steps // 5, 2)

    # engine-level sanity: a real put() round trip (includes host sync);
    # free the direct-bench cache first — two arenas don't fit in HBM
    del cache, logits, lg
    eng = init_inference(
        params, mcfg,
        {"max_batch_size": batch, "max_seq_len": 2048, "kv_block_size": 128,
         "num_kv_blocks": blocks, "max_tracked_sequences": batch + 1},
    )
    eng.put([0], [rng.integers(0, mcfg.vocab_size, ctx_len).astype(np.int32)])
    eng.put([0], [np.asarray([1])])  # compile the decode bucket
    t0 = time.perf_counter()
    eng.put([0], [np.asarray([2])])
    put_ms = (time.perf_counter() - t0) * 1e3

    print(json.dumps({
        "metric": "llama_350m_decode_tokens_per_sec",
        "value": round(tok_s, 1), "unit": "tokens/s",
        "batch": batch, "ctx": ctx_len,
        "decode_step_ms": round(dt * 1e3, 2),
        "prefill_ms": round(ttft * 1e3, 1),
        "engine_put_roundtrip_ms": round(put_ms, 1),
    }))


if __name__ == "__main__":
    sys.exit(main())
