"""Preemption-tolerant elastic training loop: peer-redundant shards +
checkpoint-free resharding (docs/elasticity.md, docs/fault_tolerance.md).

`run_elastic` (agent.py) already restarts a world that lost a host —
but its workers resume from the last committed DISK checkpoint, paying
a full restore plus every step since the last save. This module is the
Bamboo/Gemini upgrade for the in-process half of that journey: the
trainer mirrors each rank's ZeRO shard slice to a neighbor every K
steps (resilience/redundancy.py), and when a preemption kills <= R
ranks it

  1. reconstructs the lost shards from surviving peers (host memory,
     no disk),
  2. rolls the world back to the last mirror boundary (<= K-1 steps),
  3. rebuilds the engine at an elastic-compatible surviving world size
     and lays the assembled state onto the new mesh
     (`reshard_state(old_mesh -> new_mesh)`),
  4. restores the dataloader position carried in the same snapshot, so
     the replay consumes exactly the samples the dead world would have
     — the committed (step -> sample ids) ledger is byte-identical to
     an uninterrupted run (no loss, no duplication).

`resize()` is the regrow half: when preempted capacity returns, the
live state reshards onto the bigger mesh with no rollback at all.
Model RNG needs no carrying — the engine derives every step's stream
from fold_in(seed, step).

The same trainer drives the deterministic training chaos lane
(`bench.py --train-chaos`, gated by scripts/ds_elastic.py): a FaultPlan
preempts a rank mid-run via the 'engine.step' fault point and the gate
asserts peer recovery with zero disk restores and a loss trajectory
matching the uninterrupted run.
"""

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..resilience.faults import (
    InjectedIOError,
    RankPreemptedError,
    fault_point,
)
from ..resilience.integrity import (
    AnomalyDetector,
    PersistentAnomalyError,
)
from ..resilience.redundancy import (
    PeerRedundantStore,
    UnrecoverableWorldError,
    assemble_state,
    export_rank_payloads,
    reshard_state,
    stage_payload_bytes,
)
from ..utils.logging import log_dist
from .agent import WorldDegradedError
from .elasticity import compute_elastic_config

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    """Drive a DeepSpeedTPUEngine through preemptions without disk.

    make_engine(world) must return a FRESH engine whose data-parallel
    world equals `world` (an elastic-batch config re-derives the same
    global batch at every compatible size, so the trajectory is
    comparable across resizes). `loader` needs the stateful-loader
    contract (runtime/dataloader.py): iteration, state_dict /
    load_state_dict, and last_batch_indices for the exactly-once
    ledger.

    elastic_block: the config's "elasticity" dict — consulted on
    shrink so the trainer lands on a world size every worker would
    accept instead of burning a generation discovering it.
    """

    def __init__(
        self,
        make_engine: Callable[[int], Any],
        world: int,
        loader,
        every_k_steps: int = 1,
        spare: int = 1,
        min_world: int = 1,
        elastic_block: Optional[Dict[str, Any]] = None,
        checkpoint_dir: Optional[str] = None,
        straggler_factor: float = 3.0,
        clock=time.perf_counter,
        guardian=None,
    ):
        self.make_engine = make_engine
        self.loader = loader
        self.every_k = int(every_k_steps)
        self.spare = int(spare)
        self.min_world = int(min_world)
        self.elastic_block = elastic_block
        self.checkpoint_dir = checkpoint_dir
        self.straggler_factor = float(straggler_factor)
        self.clock = clock

        self.world = int(world)
        self.generation = 0
        self.engine = self._launch(self.world)
        # pipeline-parallel engines mirror a GRID of logical ranks:
        # stage-major rank r = s*dp + d owns stage s's slice of ZeRO
        # shard d, so a preempted stage HOST recovers from peer mirrors
        # exactly like a ZeRO rank (docs/pipeline.md). The pipe degree
        # is a property of the model config — make_engine(world) keeps
        # it fixed while the dp world resizes.
        self.pipe_world = int(self.engine.mesh.shape.get("pipe", 1))
        self._past_mirror_integrity = 0  # failures of replaced stores
        self.stage_mirror_bytes = 0
        store_world = self.world * self.pipe_world
        self.store = PeerRedundantStore(
            store_world, spare=min(self.spare, store_world - 1))

        # -- SDC guardian (docs/fault_tolerance.md SDC section) --------
        # guardian: an AnomalyDetector, a dict of its kwargs (plus
        # 'persistent_trips'), True for defaults, or None to follow the
        # engine config's integrity block. A trip means the step's
        # loss/grad-norm readout is not to be trusted: the step is NOT
        # committed and the world rolls back to the last digest-
        # verified peer mirror.
        icfg = getattr(self.engine.config, "integrity", None)
        self.persistent_trips = int(
            getattr(icfg, "persistent_trips", 2) or 2)
        if guardian is None and icfg is not None and icfg.enabled:
            guardian = {"zscore": icfg.zscore, "window": icfg.window,
                        "warmup": icfg.warmup_steps,
                        "rel_floor": icfg.rel_floor,
                        "persistent_trips": icfg.persistent_trips}
        if guardian is True:
            guardian = {}
        if isinstance(guardian, dict):
            kw = dict(guardian)
            self.persistent_trips = int(
                kw.pop("persistent_trips", self.persistent_trips))
            guardian = AnomalyDetector(**kw)
        self.guardian: Optional[AnomalyDetector] = guardian or None
        self.anomalies_detected = 0
        self.integrity_rollbacks = 0
        self.skipped_steps = 0
        # rollbacks already spent answering an anomaly AT a given step
        # number — when the same step trips again after a verified
        # rollback + replay, the corruption is persistent (the mirror
        # itself is suspect) and the guardian escalates to disk
        self._anomaly_rollbacks_at: Dict[int, int] = {}

        # committed trajectory: step -> loss / (epoch, sample ids).
        # A rollback TRUNCATES these — what remains is exactly the
        # trajectory an uninterrupted run commits.
        self.history: Dict[int, float] = {}
        self.ledger: Dict[int, Tuple[int, Tuple[int, ...]]] = {}

        self.reconstructions = 0
        self.disk_restores = 0
        self.last_rollback_steps = 0
        self.last_reconstruction_s = 0.0
        self.straggler_steps = 0
        self.straggler_ranks: Dict[int, int] = {}
        self._step_times: List[float] = []
        self._compile_steps = 1  # steps to exempt from straggler stats
        self._data_iter = iter(loader)

        self.mirror()  # step-0 snapshot: recoverable from the first step

    def _replace_store(self, world: int) -> None:
        """Fresh PeerRedundantStore for a new world, carrying the old
        store's digest-mismatch count into the trainer-lifetime
        `mirror_integrity_failures` metric."""
        self._past_mirror_integrity += self.store.integrity_failures
        store_world = world * self.pipe_world
        self.store = PeerRedundantStore(
            store_world, spare=min(self.spare, store_world - 1))

    @property
    def mirror_integrity_failures(self) -> int:
        """Digest mismatches seen across every reconstruct this
        trainer ever ran (monitor.training_resilience_events)."""
        return self._past_mirror_integrity + self.store.integrity_failures

    # -- generation machinery -------------------------------------------
    def _launch(self, world: int):
        fault_point("elastic.generation", generation=self.generation,
                    world=world)
        engine = self.make_engine(world)
        if int(engine.dp_world_size) != world:
            raise ValueError(
                f"make_engine({world}) built a dp world of "
                f"{engine.dp_world_size}")
        return engine

    def mirror(self) -> None:
        """One redundancy round: slice the live state per rank, mirror
        to neighbors, and carry the dataloader position + slice dims so
        a recovery is self-describing (the dead engine's spec objects
        are not needed to reassemble)."""
        payloads, dims = export_rank_payloads(self.engine)
        shared = {"loader": self.loader.state_dict(), "dims": dims}
        self.store.snapshot(self.engine.global_steps, payloads, shared)
        if self.pipe_world > 1:
            self.stage_mirror_bytes += stage_payload_bytes(payloads, dims)
        from .. import comm

        # mirrors must be exchanged before the next step may commit —
        # rides the guarded control-plane barrier (comm.collective
        # fault point; single-process worlds no-op)
        comm.barrier("post-mirror")

    def _compatible_world(self, after_loss: int) -> int:
        """Largest elastic-compatible world <= after_loss (>= min_world)."""
        valid = None
        if self.elastic_block is not None:
            _, valid = compute_elastic_config(
                {"elasticity": self.elastic_block})
        w = after_loss
        while w >= self.min_world:
            if valid is None or w in valid:
                return w
            w -= 1
        raise UnrecoverableWorldError(
            [f"no elastic-compatible world in [{self.min_world}, "
             f"{after_loss}]"])

    def recover(self, lost_ranks: List[int]) -> None:
        """The preemption path: lose the ranks, reconstruct their
        shards from peers, reshard onto the surviving world, rewind the
        loader — all in host memory. Falls back to the newest verified
        disk checkpoint ONLY when more ranks died than the redundancy
        degree covers (counted in disk_restores; the chaos gate asserts
        the counter stays 0)."""
        t0 = self.clock()
        before = self.engine.global_steps
        self.store.lose(lost_ranks)
        # lost ranks are LOGICAL grid ranks (stage-major s*dp + d under
        # pipeline parallelism; plain ZeRO ranks otherwise). The dp
        # world shrinks by the number of distinct shard COLUMNS that
        # lost a host — the pipe degree is fixed by the model config,
        # so a dead stage host retires its whole dp column's capacity
        # while every surviving (stage, shard) slice still feeds the
        # reconstruction.
        dp_lost = {int(r) % self.world for r in set(lost_ranks)}
        new_world = self._compatible_world(self.world - len(dp_lost))
        try:
            step, payloads, shared = self.store.reconstruct()
        except UnrecoverableWorldError:
            if self.checkpoint_dir is None:
                raise
            self._disk_fallback(new_world)
            return
        full = assemble_state(payloads, shared["dims"])
        self.generation += 1
        self.world = new_world
        self.engine = self._launch(new_world)
        self._compile_steps = 1
        reshard_state(self.engine, full, global_steps=step)
        self.loader.load_state_dict(shared["loader"])
        self._data_iter = iter(self.loader)
        # truncate the committed trajectory to the mirror boundary —
        # the replayed steps recommit with identical sample order
        self.history = {s: v for s, v in self.history.items() if s <= step}
        self.ledger = {s: v for s, v in self.ledger.items() if s <= step}
        self._replace_store(new_world)
        self.mirror()
        self.reconstructions += 1
        self.last_rollback_steps = before - step
        self.last_reconstruction_s = self.clock() - t0
        log_dist(
            f"elastic-trainer: ranks {sorted(set(lost_ranks))} preempted "
            f"at step {before}; peer-reconstructed step {step} onto "
            f"world {new_world} (generation {self.generation}) in "
            f"{self.last_reconstruction_s * 1e3:.1f}ms, no disk restore",
            ranks=[0])

    def _disk_fallback(self, new_world: int) -> None:
        """Too many ranks died: the classic resume (load the newest
        verified tag) — the expensive path peer redundancy avoids."""
        self.generation += 1
        self.world = new_world
        self.engine = self._launch(new_world)
        self._compile_steps = 1
        self.engine.load_checkpoint(self.checkpoint_dir)
        self.disk_restores += 1
        self.engine.disk_restores = 0  # counted above; the metrics sum both
        step = self.engine.global_steps
        self.history = {s: v for s, v in self.history.items() if s <= step}
        self.ledger = {s: v for s, v in self.ledger.items() if s <= step}
        self._replace_store(new_world)
        self.mirror()

    def resize(self, new_world: int) -> None:
        """Live reshard (regrow when capacity returns, or a graceful
        shrink ahead of a planned preemption): current state, no
        rollback, no disk."""
        import jax

        if new_world == self.world:
            return
        host = {"params": jax.device_get(self.engine.state.params)}
        if self.engine.state.master is not None:
            host["master"] = jax.device_get(self.engine.state.master)
        if self.engine.state.opt is not None:
            host["opt"] = jax.device_get(self.engine.state.opt)
        step = self.engine.global_steps
        self.generation += 1
        self.world = int(new_world)
        self.engine = self._launch(self.world)
        self._compile_steps = 1
        reshard_state(self.engine, host, global_steps=step)
        self._replace_store(self.world)
        self.mirror()
        log_dist(
            f"elastic-trainer: resharded step {step} onto world "
            f"{self.world} (generation {self.generation})", ranks=[0])

    # -- the step loop ---------------------------------------------------
    def _fetch_batch(self, retries: int = 2):
        """Next batch with bounded retry on transient I/O (the
        dataloader.fetch fault point raises BEFORE the loader position
        advances, so a retry re-fetches the same batch)."""
        for attempt in range(retries + 1):
            try:
                batch = next(self._data_iter)
                return batch, (self.loader.last_batch_epoch,
                               tuple(self.loader.last_batch_indices))
            except (InjectedIOError, OSError):
                if attempt == retries:
                    raise
                # the raise closed the generator; re-enter at the (still
                # unadvanced) persisted position
                self._data_iter = iter(self.loader)
        raise AssertionError("unreachable")

    def step(self) -> Optional[Dict[str, float]]:
        """One committed global step, or None when nothing was
        committed: a preemption was absorbed (recover() rolled back),
        the compiled step skipped itself on a non-finite gradient
        (fp16 overflow / the integrity non-finite guard), or the SDC
        guardian vetoed the step (anomaly -> verified-mirror
        rollback). In every None case the caller just keeps
        stepping."""
        batch, sample_meta = self._fetch_batch()
        t0 = self.clock()
        try:
            metrics = self.engine.train_batch(batch)
        except RankPreemptedError as e:
            spec = getattr(e, "spec", None)
            lost = int(spec.value) if spec is not None else 0
            self.recover([lost])
            return None
        except WorldDegradedError as e:
            self.recover(list(e.failed_ranks))
            return None
        wall = (self.clock() - t0) + self.engine.drain_fault_delay()
        if metrics.get("skipped", 0):
            # the compiled step found a non-finite gradient and skipped
            # the update in-graph: device state (and state.step) are
            # untouched — re-sync the host counter so the next clean
            # step commits under the SAME step number, keeping the
            # (step -> sample ids) ledger gap-free. The batch is
            # consumed (reference overflow semantics); nothing is
            # committed, and the anomaly window never sees the
            # non-finite readout.
            self.engine.global_steps -= 1
            self.skipped_steps += 1
            if self.guardian is not None:
                self.guardian.note_skip()
            return None
        if self.guardian is not None:
            verdict = self.guardian.observe(
                {"loss": float(metrics["loss"]),
                 "grad_norm": float(metrics["grad_norm"])})
            if verdict != "ok":
                self.anomalies_detected += 1
                self._integrity_rollback(verdict)
                return None
        self._note_step_time(wall)
        step_no = self.engine.global_steps
        self.history[step_no] = float(metrics["loss"])
        self.ledger[step_no] = sample_meta
        if step_no % self.every_k == 0:
            self.mirror()
        return metrics

    def _integrity_rollback(self, verdict: str) -> None:
        """Answer a guardian trip: the just-run (uncommitted) step's
        readout or update is suspect. Roll the live state back to the
        last digest-VERIFIED peer mirror (a corrupted holder copy falls
        over to the next holder — resilience/redundancy.py), rewind the
        loader to the mirror boundary and replay; nothing the trip
        tainted ever reaches the history/ledger or a mirror round. A
        step that trips again after a verified rollback + replay is a
        persistent corruption (the snapshot itself, or a deterministic
        flip): escalate to the newest verified disk checkpoint, or
        raise PersistentAnomalyError without one."""
        before = self.engine.global_steps  # the vetoed step's number
        spent = self._anomaly_rollbacks_at.get(before, 0)
        if spent >= self.persistent_trips:
            if self.checkpoint_dir is None:
                raise PersistentAnomalyError(
                    f"step {before} anomalous ({verdict}) after {spent} "
                    "verified-mirror rollbacks and no checkpoint_dir to "
                    "escalate to")
            log_dist(
                f"sdc-guardian: step {before} still anomalous after "
                f"{spent} verified rollbacks; escalating to disk",
                ranks=[0])
            self._disk_fallback(self.world)
            return
        self._anomaly_rollbacks_at[before] = spent + 1
        try:
            step, payloads, shared = self.store.reconstruct()
        except UnrecoverableWorldError:
            if self.checkpoint_dir is None:
                raise
            self._disk_fallback(self.world)
            return
        full = assemble_state(payloads, shared["dims"])
        # same world, same mesh: lay the verified state straight onto
        # the live engine (no rebuild, no recompile) and rewind
        reshard_state(self.engine, full, global_steps=step)
        self.loader.load_state_dict(shared["loader"])
        self._data_iter = iter(self.loader)
        self.history = {s: v for s, v in self.history.items() if s <= step}
        self.ledger = {s: v for s, v in self.ledger.items() if s <= step}
        self.integrity_rollbacks += 1
        self.last_rollback_steps = before - step
        log_dist(
            f"sdc-guardian: {verdict} at step {before} "
            f"(loss/grad_norm={self.guardian.last_trip}); rolled back "
            f"to verified mirror at step {step} and replaying "
            f"({before - step} steps)", ranks=[0])

    def run(self, total_steps: int, regrow_at: Optional[int] = None,
            regrow_to: Optional[int] = None) -> Dict[int, float]:
        """Step until `total_steps` are committed. regrow_at/regrow_to
        model preempted capacity returning at a known step (the chaos
        lane's world-restore half)."""
        while self.engine.global_steps < total_steps:
            if (regrow_at is not None
                    and self.engine.global_steps >= regrow_at
                    and self.world < (regrow_to or self.world)):
                self.resize(regrow_to)
            self.step()
        return dict(self.history)

    # -- observability ---------------------------------------------------
    def _note_step_time(self, wall: float) -> None:
        """Straggler detection on THIS controller's step time (each
        controller of a multi-host world flags its own rank; the
        monitor aggregates the fleet view). The first step after every
        generation launch pays a compile — exempt, not a straggler."""
        import jax
        import numpy as np

        if self._compile_steps > 0:
            self._compile_steps -= 1
            return
        self._step_times.append(wall)
        prior = self._step_times[:-1]
        if len(prior) >= 3 and wall > self.straggler_factor * float(
                np.median(prior)):
            self.straggler_steps += 1
            rank = int(jax.process_index())
            self.straggler_ranks[rank] = self.straggler_ranks.get(rank, 0) + 1

    def resilience_metrics(self) -> Dict[str, float]:
        """Flat float metrics for the monitor feed
        (monitor.training_resilience_events)."""
        import numpy as np

        st = self._step_times
        out = {
            "generation": float(self.generation),
            "world": float(self.world),
            "redundancy_staleness_steps": float(
                self.store.staleness(self.engine.global_steps)),
            "mirrors_taken": float(self.store.mirrors_taken),
            "bytes_mirrored": float(self.store.bytes_mirrored),
            "reconstructions": float(self.reconstructions),
            "last_reconstruction_ms": round(
                self.last_reconstruction_s * 1e3, 3),
            "last_rollback_steps": float(self.last_rollback_steps),
            "disk_restores": float(
                self.disk_restores + self.engine.disk_restores),
            # SDC guardian feed (docs/fault_tolerance.md SDC section)
            "anomalies_detected": float(self.anomalies_detected),
            "integrity_rollbacks": float(self.integrity_rollbacks),
            "skipped_steps": float(self.skipped_steps),
            "mirror_integrity_failures": float(
                self.mirror_integrity_failures),
            "straggler_steps": float(self.straggler_steps),
            "step_time_p50_ms": round(
                float(np.median(st)) * 1e3, 3) if st else 0.0,
            "step_time_max_ms": round(max(st) * 1e3, 3) if st else 0.0,
        }
        for r, n in sorted(self.straggler_ranks.items()):
            out[f"rank{r}/straggler_flags"] = float(n)
        if self.pipe_world > 1:
            # pipeline feed: the stage-mirror byte counter plus the
            # grid geometry (the bubble/skew half of the pipeline feed
            # lives in monitor.training_events, which reads the engine)
            out["pipe_world"] = float(self.pipe_world)
            out["stage_mirror_bytes"] = float(self.stage_mirror_bytes)
        return out
