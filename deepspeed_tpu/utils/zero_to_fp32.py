"""Offline consolidation of a checkpoint into a single fp32 state dict.

TPU-native analog of the reference tool (ref: deepspeed/utils/
zero_to_fp32.py — _get_fp32_state_dict_from_zero3_checkpoint:451 merges
per-rank ZeRO shard files; convert_zero_checkpoint_to_fp32_state_dict
:524 writes a consolidated torch state_dict). Orbax checkpoints store
logical/global arrays, so there are no rank shards to merge — this tool
restores the tree host-side WITHOUT an engine or mesh, picks the fp32
master (falling back to stored params), and flattens to plain
numpy — loadable anywhere ("reload in plain JAX/numpy" contract).

Usage (mirrors `python zero_to_fp32.py checkpoint_dir output_file`):
    python -m deepspeed_tpu.utils.zero_to_fp32 <ckpt_dir> <out.npz> [--tag TAG]
"""

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def _resolve_tag(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no 'latest' file in {ckpt_dir}; pass tag explicitly"
            )
        with open(latest) as f:
            tag = f.read().strip()
    return tag


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def get_fp32_state_dict_from_checkpoint(
    ckpt_dir: str, tag: Optional[str] = None
) -> Dict[str, Any]:
    """Checkpoint dir → nested dict of fp32 numpy parameter arrays.

    (ref: zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint —
    the returned tree is the model's parameter pytree, master-precision.)
    """
    import orbax.checkpoint as ocp

    ckpt_dir = os.path.abspath(ckpt_dir)
    tag = _resolve_tag(ckpt_dir, tag)
    state_path = os.path.join(ckpt_dir, tag, "state")
    meta_path = os.path.join(ckpt_dir, tag, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)

    raw = ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).restore(state_path)
    has_master = meta.get("has_master", raw.get("master") is not None)
    src = raw["master"] if has_master and raw.get("master") is not None else raw["params"]
    import jax

    return jax.tree.map(lambda x: np.asarray(x, dtype=np.float32), src)


def convert_checkpoint_to_fp32_state_dict(
    ckpt_dir: str, output_file: str, tag: Optional[str] = None
) -> Dict[str, np.ndarray]:
    """Write a consolidated .npz of fp32 params (flat dot-joined keys).

    (ref: zero_to_fp32.py convert_zero_checkpoint_to_fp32_state_dict:524)
    """
    tree = get_fp32_state_dict_from_checkpoint(ckpt_dir, tag)
    flat = _flatten(tree)
    np.savez(output_file, **flat)
    return flat


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    flat = convert_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, args.tag
    )
    total = sum(v.size for v in flat.values())
    print(f"wrote {len(flat)} tensors / {total:,} fp32 params to {args.output_file}")


if __name__ == "__main__":
    main()
