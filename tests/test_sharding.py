"""Sharding-rule + ZeRO spec derivation tests (ref model:
tests/unit/runtime/zero partitioning checks — here specs are the whole
mechanism, so the tests assert the derived PartitionSpecs directly)."""

from jax.sharding import PartitionSpec as P

from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.parallel.sharding import (
    logical_to_mesh_spec,
    make_rules,
    tree_logical_to_mesh,
)
from deepspeed_tpu.platform.mesh import build_mesh
from deepspeed_tpu.runtime.zero import (
    derive_optimizer_specs,
    derive_param_storage_specs,
    zero_shard_spec,
)


def mesh_dp8():
    return build_mesh({"data": 8})


def mesh_dp4_tp2():
    return build_mesh({"data": 4, "model": 2})


class TestLogicalRules:
    def test_basic_mapping(self):
        rules = make_rules()
        spec = logical_to_mesh_spec(("embed", "mlp"), rules, mesh_dp4_tp2())
        assert spec == P(None, "model")

    def test_size1_axis_dropped(self):
        rules = make_rules()
        spec = logical_to_mesh_spec(("embed", "mlp"), rules, mesh_dp8())
        assert spec == P()  # model axis is size 1 → replicated

    def test_no_duplicate_axis(self):
        rules = make_rules()
        # heads and mlp both map to model; a spec using both must not
        # produce a duplicate mesh axis
        spec = logical_to_mesh_spec(("heads", "mlp"), rules, mesh_dp4_tp2())
        used = [s for s in spec if s is not None]
        assert len(used) == 1

    def test_override(self):
        rules = make_rules({"mlp": None})
        spec = logical_to_mesh_spec(("embed", "mlp"), rules, mesh_dp4_tp2())
        assert spec == P()

    def test_tree(self):
        rules = make_rules()
        tree = {"a": ("embed", "mlp"), "b": ("vocab", "embed")}
        out = tree_logical_to_mesh(tree, rules, mesh_dp4_tp2())
        assert out["a"] == P(None, "model")
        assert out["b"] == P("model")


class TestZeroShardSpec:
    def test_picks_largest_divisible_dim(self):
        spec = zero_shard_spec(P(), (4, 256), mesh_dp8())
        assert spec == P(None, "data")

    def test_respects_existing_tp(self):
        # dim1 sharded by model(2): local 256/2=128 divisible by 8 → still
        # largest; gets ('model','data')
        spec = zero_shard_spec(P(None, "model"), (64, 256), mesh_dp4_tp2(), axes=("data",))
        assert spec == P(None, ("model", "data"))

    def test_small_leaf_stays_replicated(self):
        spec = zero_shard_spec(P(), (4,), mesh_dp8(), min_size=100)
        assert spec == P()

    def test_indivisible_stays_replicated(self):
        spec = zero_shard_spec(P(), (3, 5), mesh_dp8())
        assert spec == P()

    def test_noop_on_size1_axis(self):
        mesh = build_mesh({"data": 1, "model": 8})
        assert zero_shard_spec(P(), (256, 256), mesh) == P()


class TestStageDerivation:
    def shapes(self):
        return {"w": (128, 256), "b": (7,)}

    def specs(self):
        return {"w": P(), "b": P()}

    def test_stage0_keeps_specs(self):
        z = ZeroConfig(stage=0)
        out = derive_optimizer_specs(self.specs(), self.shapes(), mesh_dp8(), z)
        assert out == self.specs()

    def test_stage1_shards_opt_only(self):
        z = ZeroConfig(stage=1)
        opt = derive_optimizer_specs(self.specs(), self.shapes(), mesh_dp8(), z)
        par = derive_param_storage_specs(self.specs(), self.shapes(), mesh_dp8(), z)
        assert opt["w"] == P(None, "data")
        assert opt["b"] == P()  # 7 elements, indivisible → replicated
        assert par["w"] == P()

    def test_stage3_shards_params(self):
        z = ZeroConfig(stage=3, param_persistence_threshold=1000)
        par = derive_param_storage_specs(self.specs(), self.shapes(), mesh_dp8(), z)
        assert par["w"] == P(None, "data")
        assert par["b"] == P()  # below persistence threshold
