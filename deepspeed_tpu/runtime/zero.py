"""ZeRO stages as sharding derivation.

TPU-native redesign of the reference ZeRO machinery
(ref: runtime/zero/stage_1_and_2.py DeepSpeedZeroOptimizer:97,
runtime/zero/stage3.py DeepSpeedZeroOptimizer_Stage3:75,
runtime/zero/partition_parameters.py zero.Init:780). Per SURVEY §7, the
~6k LoC of hook/bucket/coordinator machinery collapses on TPU into
*where each array lives on the mesh*:

  stage 1 — optimizer state (fp32 master + moments) carries an extra
            'data'-axis sharding; params stay replicated over 'data'.
            XLA emits the reduce-scatter/all-gather pair around the
            sharded update that the reference does by hand
            (stage_1_and_2.py:1811 step / all_gather_into_tensor).
  stage 2 — gradients are additionally *constrained* to the sharded
            layout at the accumulation boundary, so XLA reduce-scatters
            grads instead of all-reducing them
            (ref: stage_1_and_2.py:923 IPG bucketing → one annotation).
  stage 3 — parameters themselves are *stored* sharded over 'data';
            XLA's SPMD partitioner inserts the per-use all-gathers that
            the reference's prefetch coordinator
            (partitioned_param_coordinator.py:261 fetch_sub_module)
            schedules manually. Small params stay replicated below
            `param_persistence_threshold`
            (ref: parameter_offload.py:242 persistent params).

MiCS / ZeRO++ hpZ sub-grouping (ref: zero/mics.py:64, config.py:264) is
the 'zero' mesh sub-axis: when the data dimension is factored data×zero
(engine does this from zero_hpz_partition_size, or the user sets
mesh.zero directly — the MiCS_Init analog), ZeRO state shards over
'zero' ONLY and replicates across 'data' groups. XLA then emits
intra-group all-gathers for params plus a cross-group grad all-reduce —
the MiCS hierarchical comm pattern (mics.py allgather within shard
group, allreduce across replica groups) derived from layout. Offload
tiering and quantized collectives live in their own modules.
"""

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..config.config import ZeroConfig


def zero_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes ZeRO state shards over: the 'zero' sub-group when
    factored in (MiCS/hpZ), else the whole 'data' axis. The expert axis
    already shards expert params; MoE expert leaves get these added on
    top of their 'expert' dim."""
    if mesh.shape.get("zero", 1) > 1:
        return ("zero",)
    return ("data",)


def _spec_dims(spec: P, rank: int):
    dims = list(spec) + [None] * (rank - len(spec))
    return dims[:rank]


def _axes_of(entry):
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def zero_shard_spec(
    spec: P,
    shape,
    mesh: Mesh,
    min_size: int = 0,
    axes: Optional[Tuple[str, ...]] = None,
) -> P:
    """Add the ZeRO axes to the best dimension of one leaf's PartitionSpec.

    Picks the largest dim that (a) is not already sharded, (b) is
    divisible by the axes' total size after accounting for existing
    sharding. Leaves smaller than `min_size` elements stay untouched (the
    persistence-threshold analog). Returns the original spec when no dim
    qualifies — those leaves stay replicated over the data axes, which is
    exactly the reference's persistent-param behavior.
    """
    if axes is None:
        axes = zero_axes(mesh)
    live = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not live:
        return spec
    axis_n = int(np.prod([mesh.shape[a] for a in live]))
    size = int(np.prod(shape)) if len(shape) else 1
    if size < max(min_size, axis_n) or len(shape) == 0:
        return spec
    dims = _spec_dims(spec, len(shape))
    if any(set(live) & set(_axes_of(d)) for d in dims):
        return spec  # already zero-sharded
    best, best_len = None, 0
    for i, d in enumerate(shape):
        existing = int(np.prod([mesh.shape[a] for a in _axes_of(dims[i])])) if dims[i] else 1
        local = d // existing
        if local % axis_n != 0:
            continue
        if local > best_len:
            best, best_len = i, local
    if best is None:
        return spec
    cur = _axes_of(dims[best])
    dims[best] = cur + live
    if len(dims[best]) == 1:
        dims[best] = dims[best][0]
    while dims and dims[-1] is None:
        dims.pop()
    return P(*dims)


def derive_param_storage_specs(param_specs, shapes, mesh: Mesh, zero_config: ZeroConfig):
    """Specs for how parameters are *stored* between steps.

    stage < 3: TP spec as-is (replicated over 'data').
    stage 3:   + 'data' sharding on leaves above the persistence threshold.
    """
    if zero_config.stage < 3:
        return param_specs
    return jax.tree.map(
        lambda spec, shp: zero_shard_spec(
            spec, shp, mesh, min_size=zero_config.param_persistence_threshold
        ),
        param_specs,
        shapes,
        is_leaf=lambda x: isinstance(x, P),
    )


def derive_optimizer_specs(param_specs, shapes, mesh: Mesh, zero_config: ZeroConfig):
    """Specs for optimizer state (fp32 master + moments).

    stage >= 1: sharded over 'data' (the ZeRO-1 partition,
    ref: stage_1_and_2.py flattened param-group partitioning). No
    persistence threshold — the reference partitions *all* optimizer
    state; tiny leaves that don't divide simply stay replicated.
    """
    if zero_config.stage < 1:
        return param_specs
    return jax.tree.map(
        lambda spec, shp: zero_shard_spec(spec, shp, mesh, min_size=0),
        param_specs,
        shapes,
        is_leaf=lambda x: isinstance(x, P),
    )


def derive_grad_specs(param_specs, opt_specs, zero_config: ZeroConfig):
    """Specs gradients are constrained to at the accumulation boundary.

    stage >= 2: the sharded (optimizer) layout → XLA reduce-scatters
    (ref: stage_1_and_2.py average_tensor:1033 reduce-scatter path).
    stage < 2:  the param layout → plain all-reduce semantics.
    """
    return opt_specs if zero_config.stage >= 2 else param_specs


def _zero_sharded_dim(store_spec: P, gathered_spec: P, rank: int, mesh: Mesh):
    """The dim whose spec gains ZeRO axes in storage (None if the leaf is
    not zero-sharded)."""
    s_dims = _spec_dims(store_spec, rank)
    g_dims = _spec_dims(gathered_spec, rank)
    zaxes = set(zero_axes(mesh))
    for i in range(rank):
        if (set(_axes_of(s_dims[i])) - set(_axes_of(g_dims[i]))) & zaxes:
            return i
    return None


def zero_sharded_dims(store_specs, gathered_specs, shapes, mesh: Mesh):
    """Pytree of per-leaf ZeRO-sharded dim indices (-1 = the leaf is
    replicated over the zero axes; -1 rather than None because None is
    an empty subtree to jax pytrees). The shard-slicing contract of the
    peer-redundancy layer (resilience/redundancy.py): rank r of a world
    of W owns elements [r*d/W, (r+1)*d/W) along this dim."""

    def dim_of(s, g, shp):
        d = _zero_sharded_dim(s, g, len(shp), mesh)
        return -1 if d is None else d

    return jax.tree.map(
        dim_of, store_specs, gathered_specs, shapes,
        is_leaf=lambda x: isinstance(x, P),
    )


def axis_sharded_dims(specs, shapes, mesh: Mesh, axis: str = "pipe"):
    """Pytree of per-leaf dim indices whose spec entry is LED by `axis`
    (-1 = the leaf is not sharded over it). The stage-slicing contract
    of the pipeline peer-redundancy path (resilience/redundancy.py):
    stage s of a pipe world of P owns [s*d/P, (s+1)*d/P) along this dim
    — exactly the XLA shard geometry of a leading-'pipe' PartitionSpec
    entry ([P, L/P, ...] plain stacks: dim 0; [v, P, lc, ...] circular
    stacks: dim 1). Dims where `axis` is a trailing co-axis (e.g. vocab
    over ('model', 'pipe')) are NOT stage-sliced: the slice order would
    interleave with the major axis, so those leaves stay whole in every
    payload — conservative, always reassemblable."""
    if mesh.shape.get(axis, 1) <= 1:
        return jax.tree.map(
            lambda s, shp: -1, specs, shapes,
            is_leaf=lambda x: isinstance(x, P))

    def dim_of(spec, shp):
        dims = _spec_dims(spec, len(shp))
        for i, d in enumerate(dims):
            ax = _axes_of(d)
            if ax and ax[0] == axis:
                return i
        return -1

    return jax.tree.map(
        dim_of, specs, shapes, is_leaf=lambda x: isinstance(x, P))


def make_qwz_gather(store_specs, gathered_specs, shapes, mesh: Mesh):
    """ZeRO++ qwZ: int8-quantized weight all-gather.

    (ref: runtime/zero/partition_parameters.py:725 CUDAQuantizer +
    all_gather_coalesced quantized path; docs/_tutorials/zeropp.md qwZ —
    halves all-gather volume vs fp16/bf16.)

    Returns f(params_tree) that, for every zero-sharded leaf, quantizes
    the local shard to int8 with one scale per slice of the sharded dim
    (shard-local by construction), constrains codes+scales to the
    GATHERED layout — so XLA's all-gather moves int8, not bf16 — and
    dequantizes locally. Backward passes gradients straight through to
    the sharded layout (the reduce-scatter stays full precision; qgZ
    handles gradient compression separately).
    """
    from ..ops.quantization import dequantize_per_axis, quantize_per_axis

    def leaf_fn(store_spec, gathered_spec, shape):
        k = _zero_sharded_dim(store_spec, gathered_spec, len(shape), mesh)
        if k is None:
            return lambda w: w  # not zero-sharded: plain (already-local) use
        g_dims = _spec_dims(gathered_spec, len(shape))
        scale_spec = P(g_dims[k]) if g_dims[k] is not None else P()

        @jax.custom_vjp
        def gather(w):
            w = jax.lax.with_sharding_constraint(
                w, jax.sharding.NamedSharding(mesh, store_spec)
            )
            q, s = quantize_per_axis(w, k)
            q = jax.lax.with_sharding_constraint(
                q, jax.sharding.NamedSharding(mesh, gathered_spec)
            )
            s = jax.lax.with_sharding_constraint(
                s, jax.sharding.NamedSharding(mesh, scale_spec)
            )
            return dequantize_per_axis(q, s, k, w.dtype)

        def fwd(w):
            return gather(w), None

        def bwd(_, g):
            return (
                jax.lax.with_sharding_constraint(
                    g, jax.sharding.NamedSharding(mesh, store_spec)
                ),
            )

        gather.defvjp(fwd, bwd)
        return gather

    fns = jax.tree.map(
        leaf_fn, store_specs, gathered_specs, shapes,
        is_leaf=lambda x: isinstance(x, P),
    )

    def apply(params):
        return jax.tree.map(lambda fn, p: fn(p), fns, params)

    return apply


def validate_no_conflicts(specs) -> None:
    """Debug-mode check: no spec uses one mesh axis twice (the sharding
    analog of the reference's safe_mode re-derivation,
    ref: stage3.py:1249 __reduce_and_partition_ipg_grads(safe_mode))."""

    def check(spec):
        seen = []
        for entry in spec:
            for ax in _axes_of(entry):
                if ax in seen:
                    raise ValueError(f"mesh axis {ax} used twice in {spec}")
                seen.append(ax)
        return spec

    jax.tree.map(check, specs, is_leaf=lambda x: isinstance(x, P))
