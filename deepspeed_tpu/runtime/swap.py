"""NVMe optimizer-state tier (ZeRO-Infinity).

TPU-native redesign of the reference's swap machinery
(ref: runtime/swap_tensor/partitioned_optimizer_swapper.py:219,
async_swapper.py AsyncTensorSwapper, optimizer_utils.py — optimizer
state lives in NVMe files, swapped in around each sub-group's update
with double buffering over the csrc/aio thread pool).

Layout: one file per parameter leaf holding fp32 [master | moment_0 |
moment_1 | ...] concatenated. Each step walks the leaves in order with
one-leaf read-ahead: while leaf i's host update runs, leaf i+1's read is
in flight on the aio thread pool, and leaf i-1's write-back drains —
the async_swapper double-buffering pattern. The per-leaf update is a
jitted XLA:CPU program (the cpu_adam SIMD analog).

Peak host memory is O(2 leaves), not O(model): the point of the tier.
"""

import os
import uuid
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.aio import AsyncIOHandle
from .offload import host_device
from .precision import clip_grads_by_global_norm


class NVMeOptimizerSwapper:
    def __init__(self, optimizer, lr_schedule, clip: float, compute_dtype,
                 nvme_path: str, n_threads: int = 4, block_size: int = 1 << 20):
        self.optimizer = optimizer
        self.lr_schedule = lr_schedule
        self.clip = float(clip)
        self.compute_dtype = compute_dtype
        # Namespace per process AND engine instance so concurrent engines /
        # restarted runs sharing one NVMe mount never cross-write live swap
        # files (ref: swap_tensor paths are rank-namespaced).
        tag = f"rank{jax.process_index()}-{uuid.uuid4().hex[:8]}"
        self.dir = os.path.join(nvme_path, "ds_tpu_swap", tag)
        os.makedirs(self.dir, exist_ok=True)
        # Swap files are run-scratch (checkpoints gather durable state via
        # export_state) — reclaim the NVMe space when the engine dies.
        import atexit
        import shutil

        self._cleanup = atexit.register(
            lambda d=self.dir: shutil.rmtree(d, ignore_errors=True)
        )
        self.aio = AsyncIOHandle(n_threads=n_threads, block_size=block_size)
        self._moment_keys: List[str] = []
        self._leaf_paths: List[Tuple] = []
        self._shapes: Dict[Tuple, tuple] = {}
        self._update_cache: Dict[tuple, Any] = {}
        self._host = host_device()

    def __del__(self):
        try:
            import shutil

            shutil.rmtree(self.dir, ignore_errors=True)
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _file(self, path_tuple) -> str:
        name = "__".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path_tuple)
        return os.path.join(self.dir, name + ".bin")

    def _moments_for(self, master: np.ndarray) -> List[np.ndarray]:
        """Moment buffers for one leaf. Every registry optimizer inits
        moments to zeros (verified with a probe); a nonzero-init optimizer
        falls back to actually running init."""
        if self._zero_init:
            return [np.zeros_like(master) for _ in self._moment_keys]
        st = jax.jit(self.optimizer.init)(jax.device_put(master, self._host))
        return [np.asarray(st[k], np.float32) for k in self._moment_keys]

    def init_state(self, master_host) -> None:
        """Write the exact fp32 master + init moments per leaf to NVMe
        (ref: partitioned_param_swapper initial swap-out)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(master_host)
        self._treedef = treedef
        probe = self.optimizer.init(jnp.ones((2,), jnp.float32))
        self._moment_keys = sorted(probe.keys())
        self._zero_init = all(
            not np.asarray(v).any() for v in jax.device_get(probe).values()
        )
        self._leaf_paths = [p for p, _ in flat]
        for path, leaf in flat:
            self._shapes[path] = tuple(leaf.shape)
        self.import_state(
            master_host,
            None,  # None → init moments
        )

    # --- checkpoint interop (engine save/load) -------------------------
    def export_state(self):
        """Read every leaf's master+moments from NVMe into host trees —
        the checkpoint-time gather (transient O(model) host RAM, same as
        the reference's swap-aware checkpoint save)."""
        masters, opts = [], {k: [] for k in self._moment_keys}
        bufs = []
        for path in self._leaf_paths:
            size = int(np.prod(self._shapes[path])) if self._shapes[path] else 1
            buf = np.empty(size * (1 + len(self._moment_keys)), np.float32)
            bufs.append((buf, self.aio.async_pread(buf, self._file(path))))
        for path, (buf, t) in zip(self._leaf_paths, bufs):
            self.aio.wait(t)
            shape = self._shapes[path]
            size = int(np.prod(shape)) if shape else 1
            masters.append(buf[:size].reshape(shape).copy())
            for k, key in enumerate(self._moment_keys):
                opts[key].append(buf[size * (1 + k): size * (2 + k)].reshape(shape).copy())
        unflatten = lambda leaves: jax.tree_util.tree_unflatten(self._treedef, leaves)
        return unflatten(masters), {k: unflatten(v) for k, v in opts.items()}

    def import_state(self, master_tree, opt_tree) -> None:
        """Write master (+ moments; None → freshly-initialized) to NVMe."""
        flat_master = jax.tree.leaves(master_tree)
        flat_moments = (
            [jax.tree.leaves(opt_tree[k]) for k in self._moment_keys]
            if opt_tree is not None
            else None
        )
        for i, path in enumerate(self._leaf_paths):
            master = np.asarray(jax.device_get(flat_master[i]), np.float32)
            if flat_moments is None:
                moments = self._moments_for(master)
            else:
                moments = [
                    np.asarray(jax.device_get(m[i]), np.float32)
                    for m in flat_moments
                ]
            buf = np.concatenate([master.ravel()] + [m.ravel() for m in moments])
            self.aio.async_pwrite(buf, self._file(path))
        self.aio.drain()

    # ------------------------------------------------------------------
    def _leaf_update(self, shape):
        """Per-leaf jitted CPU update (cached per shape)."""
        if shape not in self._update_cache:
            clip = self.clip

            def up(master, moments, grad, grad_norm, lr, step):
                grad = clip_grads_by_global_norm(grad, clip, grad_norm)
                opt = dict(zip(self._moment_keys, moments))
                new_master, new_opt = self.optimizer.update(grad, opt, master, lr, step)
                lp = new_master.astype(self.compute_dtype)
                return new_master, [new_opt[k] for k in self._moment_keys], lp

            self._update_cache[shape] = jax.jit(up)
        return self._update_cache[shape]

    def step(self, grads_host: List[np.ndarray], grad_norm, step_idx: int):
        """One offloaded update over all leaves with read-ahead.

        grads_host: flat list of fp32 numpy grads in leaf order.
        Returns flat list of compute-dtype numpy params in leaf order.
        """
        n = len(self._leaf_paths)
        norm = jnp.float32(np.asarray(grad_norm))
        lr = jax.device_get(self.lr_schedule(jnp.int32(step_idx)))
        nm = len(self._moment_keys)

        def submit_read(i):
            path = self._leaf_paths[i]
            size = int(np.prod(self._shapes[path])) if self._shapes[path] else 1
            buf = np.empty(size * (1 + nm), np.float32)
            return buf, self.aio.async_pread(buf, self._file(path))

        params_lp: List[np.ndarray] = []
        pending = submit_read(0)
        write_tickets: List[int] = []
        for i in range(n):
            buf, ticket = pending
            self.aio.wait(ticket)
            if i + 1 < n:
                pending = submit_read(i + 1)  # read-ahead next leaf
            path = self._leaf_paths[i]
            shape = self._shapes[path]
            size = int(np.prod(shape)) if shape else 1
            master = buf[:size].reshape(shape)
            moments = [
                buf[size * (1 + k): size * (2 + k)].reshape(shape) for k in range(nm)
            ]
            dev = self._host
            new_master, new_moments, lp = self._leaf_update(shape)(
                jax.device_put(master, dev),
                [jax.device_put(m, dev) for m in moments],
                jax.device_put(grads_host[i].reshape(shape), dev),
                jax.device_put(norm, dev), jnp.float32(lr), jnp.int32(step_idx + 1),
            )
            out = np.concatenate(
                [np.asarray(new_master, np.float32).ravel()]
                + [np.asarray(m, np.float32).ravel() for m in new_moments]
            )
            write_tickets.append(self.aio.async_pwrite(out, self._file(path)))
            params_lp.append(np.asarray(lp))
        for t in write_tickets:
            self.aio.wait(t)
        return params_lp, lr

    def read_lp_params(self, read_ahead: int = 4) -> List[np.ndarray]:
        """Read ONLY the master section of every leaf and cast to the
        compute dtype — the offload_param=nvme re-materialization (params
        are resident nowhere between steps; ref: partitioned_param_swapper
        swap-in of fp16 partitions).

        A window of `read_ahead` preads is kept in flight so the aio
        thread pool overlaps disk latency with the per-leaf reshape/cast;
        host peak stays O(read_ahead leaves), not O(model)."""
        n = len(self._leaf_paths)

        def submit_read(i):
            path = self._leaf_paths[i]
            shape = self._shapes[path]
            size = int(np.prod(shape)) if shape else 1
            buf = np.empty(size, np.float32)  # master is the file prefix
            return buf, self.aio.async_pread(buf, self._file(path))

        out: List[np.ndarray] = []
        window = max(1, int(read_ahead))
        pending = {i: submit_read(i) for i in range(min(window, n))}
        for i in range(n):
            buf, ticket = pending.pop(i)
            if i + window < n:
                pending[i + window] = submit_read(i + window)
            self.aio.wait(ticket)
            shape = self._shapes[path := self._leaf_paths[i]]
            out.append(
                buf.reshape(shape).astype(
                    np.dtype(jnp.dtype(self.compute_dtype).name)
                )
            )
        return out

    def unflatten(self, leaves):
        return jax.tree_util.tree_unflatten(self._treedef, leaves)
