"""The one named host-sync choke point.

Benchmarks and profiling scripts must synchronize with the device at
end-of-run/per-trial boundaries; hot paths must not. ds-lint rule R002
flags raw `jax.block_until_ready`/`jax.device_get` in the engine
step/decode paths — deliberate measurement syncs route through
`host_sync` instead, so every blocking point in the tree is greppable by
one name and auditable in one place.
"""

from typing import Any

import jax
import numpy as np

__all__ = ["host_sync", "host_readback", "serving_readback"]


def host_sync(tree: Any) -> Any:
    """Block until every leaf of `tree` has materialized on device, then
    return it. The allowlisted R002 helper: use at trial/run boundaries
    (comm/bench.py, scripts/profile_*.py), never inside a step loop."""
    return jax.block_until_ready(tree)  # ds-lint: ok R002 the choke point


def serving_readback(x: Any) -> np.ndarray:
    """The serving scheduler's ONE per-iteration host readback: sampled
    token ids ([bucket] or [chunk, bucket] int32) of an in-flight
    dispatch (inference/scheduler.py). R002-allowlisted because the
    loop is double-buffered: the readback of step N is issued AFTER
    step N+1's dispatch whenever composition allows, so the device
    pipeline never idles on it — and what crosses the link is token
    ids, never [batch, vocab] logits."""
    return np.asarray(jax.device_get(x))  # ds-lint: ok R002 the serving choke point


def host_readback(tree: Any) -> np.ndarray:
    """One-element host readback of the first leaf — the sync that works
    THROUGH the axon TPU tunnel, where block_until_ready does not
    synchronize (measured; see scripts/tpu_timing.py). Same contract as
    host_sync: end-of-run/per-trial boundaries only."""
    leaf = jax.tree_util.tree_leaves(tree)[0]
    return np.asarray(leaf.ravel()[:1])
