"""Pallas flash attention (TPU), forward + backward kernels.

TPU-native replacement for the reference's fused attention CUDA kernels
(ref: csrc/transformer/ softmax_kernels.cu + strided_batch_gemm for
training). Flash-attention-2-style online softmax, with:

- **bf16 MXU inputs everywhere**: all matmuls feed the MXU in the input
  dtype with f32 accumulation (`preferred_element_type`) — never
  pre-cast to f32 (f32 matmul runs at 1/4 rate on v5e).
- **GQA via BlockSpec index maps**: q is [B*H, S, D], kv stays
  [B*KV, S, D]; the kv block index map folds the q-head → kv-head
  mapping (h // group) so repeated KV heads are never materialized in
  HBM (fixes VERDICT W4's n_rep× HBM traffic multiplier).
- **Pallas backward**: two kernels (dq; dk/dv) recomputing probabilities
  from the saved logsumexp — replaces round 1's XLA lax.scan backward
  that materialized [BH, S, block_k] probability tiles.
- causal masking prunes fully-masked blocks with @pl.when; the diagonal
  band applies an iota mask.

grid layout: the innermost grid dims are sequential on TPU, so running
accumulators live in VMEM scratch across those steps and outputs are
written on the last step (out index maps that ignore the inner dims keep
the block resident until then).

Numerics are validated against the pure-jnp oracle in
tests/test_flash_attention.py exactly as the reference validates CUDA
kernels against torch (ref: tests/unit/ops).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    """Run kernels through the Pallas interpreter off-TPU so the CPU test
    lane exercises the real kernel math (ref: tests/unit/ops runs CUDA
    kernels only on GPU; the interpreter removes that gap here)."""
    return jax.default_backend() != "tpu"


def _dot(a, b, trans_a=False, trans_b=False):
    """MXU matmul with f32 accumulation, keeping input dtype (bf16 ok)."""
    ca = 0 if trans_a else 1
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())), preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
    *, scale: float, block_q: int, block_k: int, seq_len: int, causal: bool,
):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (sequential)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_start = i * block_q
    k_start = j * block_k
    needed = True
    if causal:
        needed = k_start < q_start + block_q

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, trans_b=True) * scale  # (bq, bk) f32

        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = cols < seq_len  # k padding
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:]  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # (bq, bk) f32
        corr = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_sc[:] = l_sc[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]
        pv = _dot(p.astype(v.dtype), v)
        acc_sc[:] = acc_sc[:] * corr + pv
        m_sc[:] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:] + jnp.log(l_safe)).reshape(1, block_q).astype(jnp.float32)


def _pad_to(x, size, axis):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _kv_index(b, H: int, KV: int, G: int):
    """q-head-major grid index b (over B*H) → kv index (over B*KV).

    q head h attends kv head h // G (heads grouped contiguously)."""
    return (b // H) * KV + (b % H) // G


def _clamp_j(j, i, bq: int, bk: int, causal: bool):
    """Causal DMA pruning for the k-sequential kernels (fwd, dq): blocks
    strictly above the diagonal are skipped by @pl.when, but Pallas would
    still stream their tiles. Clamping the index map to the last needed
    k block makes pruned steps revisit a resident block — no transfer."""
    if not causal:
        return j
    jmax = ((i + 1) * bq - 1) // bk
    return jnp.minimum(j, jmax)


def _clamp_i(i, j, bq: int, bk: int, causal: bool):
    """Same DMA pruning for the q-sequential dk/dv kernel: q blocks
    strictly above the diagonal map to the first needed q block."""
    if not causal:
        return i
    imin = (j * bk) // bq
    return jnp.maximum(i, imin)


def _flash_fwd(q, k, v, causal, block_q, block_k, H, KV):
    """q: [B*H, S, D]; k,v: [B*KV, S, D] → (o [B*H,S,D], lse [B*H,S])."""
    BH, S, D = q.shape
    G = H // KV
    scale = 1.0 / (D**0.5)
    bq, bk = block_q, block_k
    Sp = pl.cdiv(S, bq) * bq
    Sk = pl.cdiv(S, bk) * bk
    qp = _pad_to(q, Sp, 1)
    kp = _pad_to(k, Sk, 1)
    vp = _pad_to(v, Sk, 1)
    nq, nk = Sp // bq, Sk // bk

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=bq, block_k=bk, seq_len=S, causal=causal
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec(
                (1, bk, D),
                lambda b, i, j: (_kv_index(b, H, KV, G), _clamp_j(j, i, bq, bk, causal), 0),
            ),
            pl.BlockSpec(
                (1, bk, D),
                lambda b, i, j: (_kv_index(b, H, KV, G), _clamp_j(j, i, bq, bk, causal), 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            # lse carries a singleton middle dim so the block's trailing two
            # dims (1, bq) satisfy the TPU (8,128) tiling rule via equality
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sp, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Sp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    return o[:, :S], lse[:, 0, :S]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc,
    *, scale: float, block_q: int, block_k: int, seq_len: int, causal: bool,
):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (sequential)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_start = i * block_q
    k_start = j * block_k
    needed = True
    if causal:
        needed = k_start < q_start + block_q

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, trans_b=True) * scale  # (bq, bk) f32

        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = cols < seq_len
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)

        lse = lse_ref[0].reshape(block_q, 1)  # (bq, 1)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # (bq, bk) f32
        do = do_ref[0]
        dp = _dot(do, v_ref[0], trans_b=True)  # (bq, bk) f32
        delta = delta_ref[0].reshape(block_q, 1)
        ds = p * (dp - delta) * scale  # (bq, bk) f32
        dq_sc[:] = dq_sc[:] + _dot(ds.astype(k.dtype), k)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_sc, dv_sc,
    *, scale: float, block_q: int, block_k: int, seq_len: int, causal: bool,
    n_group: int,
):
    j = pl.program_id(1)   # k block
    g = pl.program_id(2)   # q-head within the kv group (sequential)
    i = pl.program_id(3)   # q block (sequential)
    nq = pl.num_programs(3)

    @pl.when(jnp.logical_and(g == 0, i == 0))
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_start = i * block_q
    k_start = j * block_k
    needed = True
    if causal:
        needed = k_start < q_start + block_q

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        # transposed orientation (bk, bq): no in-kernel transposes needed
        s_t = _dot(k, q, trans_b=True) * scale

        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        mask = cols < seq_len
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)

        lse = lse_ref[0]  # (1, bq) broadcasts over bk rows
        p_t = jnp.where(mask, jnp.exp(s_t - lse), 0.0)  # (bk, bq) f32
        do = do_ref[0]
        dv_sc[:] = dv_sc[:] + _dot(p_t.astype(do.dtype), do)
        dp_t = _dot(v_ref[0], do, trans_b=True)  # (bk, bq) f32
        delta = delta_ref[0]  # (1, bq)
        ds_t = p_t * (dp_t - delta) * scale
        dk_sc[:] = dk_sc[:] + _dot(ds_t.astype(q.dtype), q)

    @pl.when(jnp.logical_and(g == n_group - 1, i == nq - 1))
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, H, KV):
    BH, S, D = q.shape
    BKV = k.shape[0]
    G = H // KV
    scale = 1.0 / (D**0.5)
    bq, bk = block_q, block_k
    Sp = pl.cdiv(S, bq) * bq
    Sk = pl.cdiv(S, bk) * bk
    nq, nk = Sp // bq, Sk // bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [BH,S]
    qp = _pad_to(q, Sp, 1)
    dop = _pad_to(do, Sp, 1)
    lsep = _pad_to(lse, Sp, 1).reshape(BH, 1, Sp)
    deltap = _pad_to(delta, Sp, 1).reshape(BH, 1, Sp)
    kp = _pad_to(k, Sk, 1)
    vp = _pad_to(v, Sk, 1)

    kwargs = dict(scale=scale, block_q=bq, block_k=bk, seq_len=S, causal=causal)
    kv_ix = lambda b: _kv_index(b, H, KV, G)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kwargs),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (kv_ix(b), _clamp_j(j, i, bq, bk, causal), 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (kv_ix(b), _clamp_j(j, i, bq, bk, causal), 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    # q-head index for the dk/dv grid: (b_kv, g) → q head row in [B*H)
    q_ix = lambda b, g: (b // KV) * H + (b % KV) * G + g

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, n_group=G, **kwargs),
        grid=(BKV, nk, G, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, g, i: (q_ix(b, g), _clamp_i(i, j, bq, bk, causal), 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, g, i: (q_ix(b, g), _clamp_i(i, j, bq, bk, causal), 0)),
            pl.BlockSpec((1, 1, bq), lambda b, j, g, i: (q_ix(b, g), 0, _clamp_i(i, j, bq, bk, causal))),
            pl.BlockSpec((1, 1, bq), lambda b, j, g, i: (q_ix(b, g), 0, _clamp_i(i, j, bq, bk, causal))),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, g, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BKV, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BKV, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    return dq[:, :S], dk[:, :S], dv[:, :S]


# ---------------------------------------------------------------------------
# custom VJP + public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, H, KV):
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, H, KV)
    return o


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, H, KV):
    o, lse = _flash_fwd(q, k, v, causal, block_q, block_k, H, KV)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, block_q, block_k, H, KV, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, H, KV)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q, k, v, causal: bool = True, block_q: int = 512, block_k: int = 1024
):
    """[B,S,H,D] x [B,S,KV,D] x [B,S,KV,D] → [B,S,H,D] flash attention.

    GQA (KV < H) is handled inside the kernels via index maps — callers
    must NOT pre-repeat KV heads."""
    B, S, H, D = q.shape
    KV = k.shape[2]
    assert H % KV == 0, f"n_heads {H} not a multiple of kv_heads {KV}"
    bq = min(block_q, S)
    bk = min(block_k, S)

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, S, D)

    o = _flash(to_bh(q), to_bh(k), to_bh(v), causal, bq, bk, H, KV)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
