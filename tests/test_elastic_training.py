"""Preemption-tolerant elastic training: peer-redundant ZeRO shards,
checkpoint-free resharding, the guarded control-plane collectives, and
the training fault points (docs/fault_tolerance.md training section,
docs/elasticity.md).

The full journey — injected mid-run rank kill + world shrink + regrow
with a byte-exact data-order ledger — is additionally gated end-to-end
by `bench.py --train-chaos` / scripts/ds_elastic.py (tier-1 pre-test
gate); here the pieces are proven fast and in isolation, plus one
compact in-process journey.
"""

import json
import os
import sys

import numpy as np
import pytest

import deepspeed_tpu.comm as comm
from deepspeed_tpu.resilience import (
    FaultPlan,
    InjectedIOError,
    PeerRedundantStore,
    RankPreemptedError,
    RedundancyError,
    UnrecoverableWorldError,
    armed,
)
from deepspeed_tpu.resilience.redundancy import (
    assemble_tree,
    slice_tree,
)


# ---------------------------------------------------------------------------
# PeerRedundantStore: the storage-honesty state machine
# ---------------------------------------------------------------------------

def _payloads(world, step=0):
    return {r: {"w": np.full((4,), 100 * step + r, np.float32)}
            for r in range(world)}


class TestPeerRedundantStore:
    def test_snapshot_reconstruct_after_single_loss(self):
        st = PeerRedundantStore(world=4, spare=1)
        st.snapshot(6, _payloads(4, step=6), shared={"k": 1})
        st.lose([2])
        ok, missing = st.recoverable()
        assert ok and missing == []
        step, payloads, shared = st.reconstruct()
        assert step == 6 and shared == {"k": 1}
        # rank 2's slice came from its mirror on rank 3
        np.testing.assert_array_equal(payloads[2]["w"],
                                      np.full((4,), 602, np.float32))

    def test_losing_rank_and_its_holder_is_unrecoverable(self):
        st = PeerRedundantStore(world=4, spare=1)
        st.snapshot(1, _payloads(4))
        st.lose([2, 3])  # rank 2's only mirror lived on rank 3
        ok, missing = st.recoverable()
        assert not ok and missing == [2]
        with pytest.raises(UnrecoverableWorldError) as ei:
            st.reconstruct()
        assert ei.value.missing_ranks == [2]

    def test_spare_two_survives_double_loss(self):
        st = PeerRedundantStore(world=4, spare=2)
        st.snapshot(1, _payloads(4))
        st.lose([2, 3])
        ok, _ = st.recoverable()
        assert ok  # rank 2 also mirrors to rank 0, rank 3 to ranks 0+1
        _, payloads, _ = st.reconstruct()
        assert sorted(payloads) == [0, 1, 2, 3]

    def test_new_snapshot_clears_losses_and_staleness(self):
        st = PeerRedundantStore(world=2, spare=1)
        st.snapshot(4, _payloads(2, step=4))
        st.lose([1])
        st.snapshot(6, _payloads(2, step=6))  # the next mirror round
        assert st.lost == set()
        assert st.staleness(current_step=7) == 1
        assert st.staleness(current_step=6) == 0

    def test_world_one_is_local_only(self):
        st = PeerRedundantStore(world=1, spare=0)
        st.snapshot(1, _payloads(1))
        assert st.reconstruct()[0] == 1
        st.lose([0])
        assert not st.recoverable()[0]

    def test_bad_geometry_rejected(self):
        with pytest.raises(RedundancyError):
            PeerRedundantStore(world=2, spare=2)
        st = PeerRedundantStore(world=2, spare=1)
        with pytest.raises(RedundancyError):
            st.snapshot(1, {0: {}})  # incomplete rank set


class TestSliceAssemble:
    def test_round_trip_mixed_dims(self):
        tree = {"a": np.arange(8, dtype=np.float32),
                "b": np.arange(12, dtype=np.float32).reshape(3, 4),
                "c": np.float32(7.0).reshape(())}
        dims = {"a": 0, "b": 1, "c": -1}
        world = 4
        payloads = {r: slice_tree(tree, dims, r, world)
                    for r in range(world)}
        assert payloads[1]["a"].shape == (2,)
        assert payloads[1]["b"].shape == (3, 1)
        full = assemble_tree(payloads, dims)
        np.testing.assert_array_equal(full["a"], tree["a"])
        np.testing.assert_array_equal(full["b"], tree["b"])
        np.testing.assert_array_equal(full["c"], tree["c"])

    def test_indivisible_dim_rejected(self):
        with pytest.raises(RedundancyError):
            slice_tree({"a": np.arange(6)}, {"a": 0}, 0, 4)


# ---------------------------------------------------------------------------
# guarded control-plane collectives (comm/comm.py)
# ---------------------------------------------------------------------------

class TestCollectiveGuard:
    def test_transient_fault_heals_within_retries(self):
        plan = FaultPlan([{"point": "comm.collective", "kind": "raise",
                           "error": "io", "at": 1, "times": 2}])
        with armed(plan) as p:
            comm.barrier("t-heal")  # two failures, third attempt lands
        assert len(p.fired) == 2

    def test_retries_exhausted_surfaces(self):
        plan = FaultPlan([{"point": "comm.collective", "kind": "raise",
                           "error": "io", "times": -1}])
        with armed(plan):
            with pytest.raises(InjectedIOError):
                comm.barrier("t-dead", retries=1)

    def test_timeout_is_typed_with_op_and_group(self):
        # injected delay >= the deadline: a deterministic timeout
        # verdict with NO real hang (the guard never sleeps it)
        plan = FaultPlan([{"point": "comm.collective", "kind": "delay",
                           "value": 60.0}])
        with armed(plan):
            with pytest.raises(comm.CollectiveTimeoutError) as ei:
                comm.barrier("t-hang", timeout_s=2.0)
        assert ei.value.op == "barrier[t-hang]"
        assert ei.value.replica_group == "world"
        assert "t-hang" in str(ei.value)

    def test_short_delay_is_slow_but_alive(self):
        plan = FaultPlan([{"point": "comm.collective", "kind": "delay",
                           "value": 0.01}])
        with armed(plan):
            comm.barrier("t-slow", timeout_s=5.0)  # completes

    def test_broadcast_host_guarded_and_identity_single_process(self):
        plan = FaultPlan([{"point": "comm.collective", "kind": "raise",
                           "error": "io",
                           "where": {"op": "broadcast_host"}, "times": 1}])
        with armed(plan) as p:
            assert comm.broadcast_host({"a": 1}) == {"a": 1}
        assert p.fired  # fired once, healed by the retry

    def test_timeout_env_knob(self, monkeypatch):
        monkeypatch.setenv("DS_COMM_TIMEOUT_S", "12.5")
        assert comm.collective_timeout_from_env() == 12.5
        monkeypatch.setenv("DS_COMM_TIMEOUT_S", "junk")
        assert comm.collective_timeout_from_env(3.0) == 3.0


# ---------------------------------------------------------------------------
# dataloader fault point (state stays clean across an injected failure)
# ---------------------------------------------------------------------------

class _Toy:
    def __init__(self, n=16):
        self.items = [{"tokens": np.full((4,), i, np.int32)}
                      for i in range(n)]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]


class TestDataloaderFaultPoint:
    def test_injected_fetch_error_leaves_position_clean(self):
        from deepspeed_tpu.runtime.dataloader import (
            DeepSpeedTPUDataLoader,
            RepeatingLoader,
        )

        dl = DeepSpeedTPUDataLoader(_Toy(), batch_size=4, shuffle=True,
                                    seed=3)
        rl = RepeatingLoader(dl)
        first = next(rl)
        plan = FaultPlan([{"point": "dataloader.fetch", "kind": "raise",
                           "error": "io", "at": 1, "times": 1}])
        with armed(plan):
            state_before = rl.state_dict()
            with pytest.raises(InjectedIOError):
                next(rl)
            # the raise fired BEFORE the position advanced
            assert rl.state_dict() == state_before
            retry = next(rl)  # RepeatingLoader re-enters at the position
        ids = dl.last_batch_indices
        rl.load_state_dict(state_before)
        again = next(rl)
        assert dl.last_batch_indices == ids
        np.testing.assert_array_equal(retry["tokens"], again["tokens"])
        assert not np.array_equal(first["tokens"], retry["tokens"])


# ---------------------------------------------------------------------------
# elastic.launch fault point: a failed relaunch burns a generation
# ---------------------------------------------------------------------------

class TestLaunchFaultPoint:
    def test_failed_launch_shrinks_and_retries(self, tmp_path, capsys):
        from deepspeed_tpu.elasticity import run_elastic

        ok = tmp_path / "ok.py"
        ok.write_text("import sys; sys.exit(0)\n")
        plan = FaultPlan([{"point": "elastic.launch", "kind": "raise",
                           "error": "io", "where": {"generation": 0}}])
        with armed(plan):
            rc = run_elastic(
                [sys.executable, str(ok)], num_procs=3,
                heartbeat_dir=str(tmp_path / "hb"),
                resume_dir=str(tmp_path),
                first_beat_timeout_s=0, max_restarts=2, min_procs=1)
        err = capsys.readouterr().err
        assert rc == 0
        assert "launch failed" in err
        assert "restarting at world=2" in err


# ---------------------------------------------------------------------------
# elastic.generation fault point: a generation launch failure is LOUD
# ---------------------------------------------------------------------------

class TestGenerationFaultPoint:
    def test_failed_generation_launch_propagates(self):
        """elastic.generation fires inside ElasticTrainer._launch
        BEFORE make_engine runs, so an injected launch failure must
        surface to the caller untouched — never be absorbed into a
        half-built trainer (the lifecycle L003 coverage lane for this
        point)."""
        from deepspeed_tpu.elasticity import ElasticTrainer

        calls = []
        plan = FaultPlan([{"point": "elastic.generation",
                           "kind": "raise", "error": "io",
                           "where": {"generation": 0}, "times": 1}])
        with armed(plan) as p:
            with pytest.raises(InjectedIOError):
                ElasticTrainer(
                    lambda w: calls.append(w), 2, _make_loader(),
                    elastic_block=dict(ELASTIC))
        assert p.fired == ["elastic.generation#1:raise:io"]
        # the fault raised at the generation boundary: no engine was
        # ever built for the doomed generation
        assert calls == []


# ---------------------------------------------------------------------------
# the compact in-process journey: kill -> peer reshard -> regrow
# ---------------------------------------------------------------------------

ELASTIC = {"enabled": True, "max_train_batch_size": 8,
           "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8}


def _make_engine(world):
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.platform.mesh import build_mesh

    mcfg = T.TransformerConfig(vocab_size=64, n_layers=1, n_heads=2,
                               d_model=32, max_seq=16, variant="llama",
                               use_flash=False)
    mesh = build_mesh({"data": world}, devices=jax.devices()[:world])
    return ds.initialize(
        {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
         "elasticity": dict(ELASTIC),
         "zero_optimization": {"stage": 1},
         "seed": 3, "steps_per_print": 10**9},
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
        mesh=mesh)


def _make_loader():
    from deepspeed_tpu.runtime.dataloader import (
        DeepSpeedTPUDataLoader,
        RepeatingLoader,
    )

    class Tok:
        def __init__(self, n=24):
            r = np.random.default_rng(9)
            self.items = [
                {"tokens": r.integers(0, 64, (17,)).astype(np.int32)}
                for _ in range(n)]

        def __len__(self):
            return len(self.items)

        def __getitem__(self, i):
            return self.items[i]

    return RepeatingLoader(DeepSpeedTPUDataLoader(
        Tok(), batch_size=8, shuffle=True, seed=5))


class TestElasticTrainerJourney:
    def test_preempt_reshard_regrow_exactly_once(self):
        from deepspeed_tpu.elasticity import ElasticTrainer
        from deepspeed_tpu.monitor.monitor import (
            training_resilience_events,
        )

        T_STEPS = 6
        clean = ElasticTrainer(_make_engine, 2, _make_loader(),
                               every_k_steps=2,
                               elastic_block=dict(ELASTIC))
        clean_hist = clean.run(T_STEPS)

        # rank 1 preempted at the dispatch of step 4 (state at 3,
        # mirror at 2 -> rollback 1 step); regrow 1 -> 2 at step 5
        plan = FaultPlan([
            {"point": "engine.step", "kind": "raise",
             "error": "preempted", "value": 1, "where": {"step": 4},
             "times": 1},
        ])
        chaos = ElasticTrainer(_make_engine, 2, _make_loader(),
                               every_k_steps=2,
                               elastic_block=dict(ELASTIC))
        with armed(plan) as p:
            chaos_hist = chaos.run(T_STEPS, regrow_at=5, regrow_to=2)
        assert p.fired == ["engine.step#1:raise:preempted"]

        # exactly-once committed trajectory + byte-exact sample ledger
        assert sorted(clean_hist) == list(range(1, T_STEPS + 1))
        assert sorted(chaos_hist) == list(range(1, T_STEPS + 1))
        assert json.dumps(sorted(clean.ledger.items())) \
            == json.dumps(sorted(chaos.ledger.items()))
        # bitwise before the kill; reassociation-only drift after
        assert all(clean_hist[s] == chaos_hist[s] for s in (1, 2, 3))
        for s in range(4, T_STEPS + 1):
            assert abs(clean_hist[s] - chaos_hist[s]) \
                <= 1e-3 * abs(clean_hist[s])

        # the recovery was peer-shard, not disk
        m = chaos.resilience_metrics()
        assert chaos.reconstructions == 1
        assert m["disk_restores"] == 0
        assert chaos.last_rollback_steps == 1  # step 3 -> mirror at 2
        assert chaos.world == 2 and chaos.generation == 2

        # monitor feed contract: (name, float, step) with the prefix
        events = training_resilience_events(chaos, step=T_STEPS)
        names = {n for n, _, _ in events}
        assert {"train/resilience/generation",
                "train/resilience/redundancy_staleness_steps",
                "train/resilience/disk_restores"} <= names
        assert all(s == T_STEPS and isinstance(v, float)
                   for _, v, s in events)

    def test_payload_slices_match_device_shards(self):
        """The honesty check: an exported rank payload is byte-identical
        to the rank's actual addressable ZeRO shard on the mesh."""
        from deepspeed_tpu.resilience.redundancy import (
            engine_shard_dims,
            export_rank_payloads,
        )

        eng = _make_engine(2)
        payloads, dims = export_rank_payloads(eng)
        # find a genuinely sharded opt leaf and compare with the
        # device's own addressable shard
        import jax

        leaf = eng.state.opt["mu"]["embed"]
        dim = dims["opt"]["mu"]["embed"]
        assert dim >= 0  # embed (64, 32) shards over data=2
        for shard in leaf.addressable_shards:
            r = shard.index[dim].start or 0
            rank = r // (leaf.shape[dim] // 2)
            np.testing.assert_array_equal(
                np.asarray(shard.data),
                payloads[rank]["opt"]["mu"]["embed"])
        assert engine_shard_dims(eng).keys() == dims.keys()

    def test_unrecoverable_without_checkpoint_dir_raises(self):
        from deepspeed_tpu.elasticity import ElasticTrainer

        tr = ElasticTrainer(_make_engine, 2, _make_loader(),
                            every_k_steps=1,
                            elastic_block=dict(ELASTIC))
        tr.store.lose([0, 1])  # both hosts gone: nothing survives
        with pytest.raises(UnrecoverableWorldError):
            tr.recover([0, 1])


# ---------------------------------------------------------------------------
# RandomLTD RNG-stream state round trip (data_pipeline satellite)
# ---------------------------------------------------------------------------

class TestRandomLTDState:
    def test_rng_stream_round_trip(self):
        from deepspeed_tpu.runtime.data_pipeline import RandomLTDScheduler

        a = RandomLTDScheduler(min_tokens=8, max_tokens=32,
                               total_steps=100, step_size=8, seed=7)
        a.sample_batch_indices(2, 16, 8)  # advance the stream
        snap = a.get_state()
        want = a.sample_batch_indices(2, 16, 8)
        b = RandomLTDScheduler(min_tokens=8, max_tokens=32,
                               total_steps=100, step_size=8, seed=7)
        b.set_state(snap)
        np.testing.assert_array_equal(
            b.sample_batch_indices(2, 16, 8), want)
