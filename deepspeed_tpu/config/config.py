"""Config system: one JSON/dict → typed config tree.

TPU-native analog of the reference config plumbing
(ref: runtime/config.py DeepSpeedConfig, runtime/config_utils.py
DeepSpeedConfigModel). Uses pydantic v2. Field names intentionally match
the reference JSON schema (train_micro_batch_size_per_gpu, zero_optimization,
bf16/fp16 blocks, optimizer/scheduler type+params) so configs written for
the reference parse here; batch-triangle resolution reproduces
runtime/config.py's train/micro/GAS coupling with the data-parallel world
size coming from the mesh rather than torch.distributed.
"""

import json
from enum import IntEnum
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator


class ConfigModel(BaseModel):
    """Base for all config blocks (ref: config_utils.py DeepSpeedConfigModel)."""

    model_config = ConfigDict(extra="forbid", validate_assignment=True, populate_by_name=True)


class ZeroStage(IntEnum):
    disabled = 0
    optimizer_states = 1  # shard optimizer state over 'data'
    gradients = 2  # + reduce-scatter grads
    weights = 3  # + shard parameters


class OffloadDevice:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OffloadConfig(ConfigModel):
    """ref: runtime/zero/offload_config.py"""

    device: str = OffloadDevice.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    pin_memory: bool = False


class ZeroConfig(ConfigModel):
    """ref: runtime/zero/config.py DeepSpeedZeroConfig:83"""

    stage: int = 0
    # ZeRO-3 persistence threshold: params smaller than this stay replicated
    # (ref: stage3 param_persistence_threshold / parameter_offload.py:242).
    param_persistence_threshold: int = 10_000
    # Sub-mesh ("MiCS"/hpZ-style) sharding: shard params over groups of this
    # size and replicate across groups (ref: runtime/zero/mics.py:64,
    # zero_hpz_partition_size config.py:264).
    zero_hpz_partition_size: int = 0  # 0 = full data-axis sharding
    # ZeRO++ quantized collectives (ref: zero/config.py:268/:280).
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # ref: zero/config.py zero_quantized_nontrainable_weights — resident
    # int8 storage for frozen weights. Not implemented (all engine params
    # are trainable here; serve frozen models via inference PTQ instead) —
    # parses when false so stock ZeRO++ configs load, raises when true.
    zero_quantized_nontrainable_weights: bool = False
    offload_optimizer: OffloadConfig = Field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = Field(default_factory=OffloadConfig)
    # Comm/compute overlap master switch (runtime/overlap.py,
    # docs/overlap.md): scan-carried ZeRO-3 parameter prefetch, bucketed
    # gradient reduce-scatter launches, pipeline permute overlap, and the
    # schedule analyzer's latency-hiding credit. false = the serialized
    # twin ds_schedule commits (every collective modeled fully exposed,
    # no prefetch/bucket restructure) — the reference's overlap_comm
    # semantics (ref: stage_1_and_2.py overlap_comm reduction during bwd).
    overlap_comm: bool = True
    # How many layers ahead the scanned stack's gathered-weights buffer
    # runs (ref: partitioned_param_coordinator.py fetch_sub_module +
    # stage3_prefetch_bucket_size's look-ahead role). 0 disables the
    # prefetch restructure (per-use gathers at the consumer); >=1 carries
    # that many gathered layer buffers through the scan. tune_aot
    # searches this axis.
    prefetch_depth: int = 1
    # Gradient reduce-scatter launch-group size in MiB (ref:
    # stage_1_and_2.py reduce_bucket_size IPG buckets). 0 = one
    # serialized constraint wall at the accumulation boundary; >0 =
    # software-pipelined bucket launches (runtime/overlap.bucketed_apply).
    # tune_aot searches this axis.
    bucket_mb: float = 32.0
    # Accepted no-op on TPU: buffers are always contiguous under XLA.
    contiguous_gradients: bool = True


class BF16Config(ConfigModel):
    """ref: runtime/config.py bf16 block"""

    enabled: bool = False
    # Keep a fp32 master copy partitioned ZeRO-1 style (ref: bf16_optimizer.py:30).
    master_weights: bool = True


class FP16Config(ConfigModel):
    """ref: runtime/fp16/loss_scaler.py DynamicLossScaler + config keys"""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


class DataTypesConfig(ConfigModel):
    """ref: runtime/config.py data_types block. `grad_accum_dtype`
    declares the gradient-accumulation/reduction precision the compiled
    step must honor (None = fp32, the engine's construction); the
    numerics sanitizer (analysis/numerics.py N001) verifies the HLO
    against it."""

    grad_accum_dtype: Optional[str] = None  # None -> fp32

    @model_validator(mode="after")
    def _check_dtype(self):
        if self.grad_accum_dtype is not None and \
                self.grad_accum_dtype.lower() not in (
                    "fp32", "float32", "f32", "bf16", "bfloat16",
                    "fp16", "float16", "f16"):
            raise ValueError(
                f"data_types.grad_accum_dtype={self.grad_accum_dtype!r}; "
                "expected fp32/bf16/fp16")
        return self


class IntegrityConfig(ConfigModel):
    """Silent-data-corruption guardian (resilience/integrity.py,
    docs/fault_tolerance.md SDC section). `enabled` turns on (a) the
    in-graph non-finite gradient guard in the compiled train step —
    `precision.found_inf_in_grads` over the grad pytree, skipping the
    optimizer update exactly like the fp16 overflow path (fp16 keeps
    its own loss-scale-coupled check either way) — and (b) the default
    EMA z-score anomaly detector the ElasticTrainer builds when no
    explicit guardian is passed. Off by default: the guard adds
    branchless selects to the compiled step, and the committed
    MEMBUDGET/NUMERICS baselines pin the un-guarded canonical
    programs.

    zscore/window/warmup_steps/rel_floor parameterize the detector
    (see AnomalyDetector); persistent_trips bounds how many times the
    guardian may answer the SAME step's anomaly with a verified-mirror
    rollback before escalating to the disk checkpoint (or raising
    PersistentAnomalyError without one)."""

    enabled: bool = False
    zscore: float = 8.0
    window: int = 16
    warmup_steps: int = 4
    rel_floor: float = 0.02
    persistent_trips: int = 2

    @model_validator(mode="after")
    def _check(self):
        if self.zscore <= 0 or self.window < 1 or self.warmup_steps < 1:
            raise ValueError(
                "integrity needs zscore > 0, window >= 1, "
                "warmup_steps >= 1")
        if self.persistent_trips < 1:
            raise ValueError("integrity.persistent_trips must be >= 1")
        return self


class OptimizerConfig(ConfigModel):
    """ref: runtime/config.py optimizer block → ops/adam etc."""

    type: str = "adamw"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(ConfigModel):
    """ref: runtime/lr_schedules.py"""

    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class MeshConfig(ConfigModel):
    """Parallel topology — the analog of PipeModelDataParallelTopology
    (ref: runtime/pipe/topology.py:244) expressed as mesh axis sizes.
    -1 on exactly one axis means "all remaining devices"."""

    pipe: int = 1
    data: int = -1
    # ZeRO sub-group axis (MiCS): set directly, or derived from
    # zero_optimization.zero_hpz_partition_size by the engine.
    zero: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {"pipe": self.pipe, "data": self.data, "zero": self.zero,
                "expert": self.expert, "seq": self.seq, "model": self.model}


class ActivationCheckpointingConfig(ConfigModel):
    """ref: runtime/activation_checkpointing/config.py:94

    `policy` drives jax.checkpoint around each micro-step's loss in the
    compiled train step (the engine-level analog of the reference's
    configure()+checkpoint() pair):
      'none'          — no rematerialization (save everything)
      'full'          — recompute everything in backward
      'dots'          — save MXU dot/matmul outputs only
      'dots_no_batch' — save dot outputs without batch dims
    Models may additionally carry their own finer-grained remat (e.g.
    per-scanned-layer); the engine wrap composes around it.

    `cpu_checkpointing` (with policy='dots_no_batch') offloads the saved
    dot outputs to host DRAM instead of keeping them in HBM
    (jax.checkpoint_policies.offload_dot_with_no_batch_dims — ref:
    checkpointing.py:989 cpu_checkpointing).

    `partition_activations` is an accepted no-op BY DESIGN: under XLA
    SPMD the saved residuals are computed and kept in their sharded
    layout (the model's TP/Ulysses activation constraints), so saved
    activations are never replicated across model ranks — which is the
    entire job of the reference's partition_activations
    (checkpointing.py partition_activations + gather on backward).
    tests/test_engine.py asserts the per-device remat footprint shrinks
    with the model axis."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    policy: str = "none"

    @model_validator(mode="after")
    def _check_policy(self):
        if self.policy not in ("none", "full", "dots", "dots_no_batch"):
            raise ValueError(
                f"unknown activation_checkpointing.policy '{self.policy}' "
                "(expected none|full|dots|dots_no_batch)"
            )
        return self


class AioConfig(ConfigModel):
    """ref: csrc/aio handle knobs (deepspeed_py_aio_handle.h:15-39, config
    'aio' block). Drives the native I/O library (csrc/aio/ds_aio.cpp)
    behind NVMe offload: block_size chunks each request across the pool,
    thread_count sizes the pool. queue_depth/single_submit/overlap_events
    are libaio submission details the thread pool subsumes — accepted for
    config compatibility, no separate effect."""

    block_size: int = 1 << 20
    queue_depth: int = 8
    thread_count: int = 4
    single_submit: bool = False
    overlap_events: bool = True


class CommsLoggerConfig(ConfigModel):
    """ref: deepspeed/utils/comms_logging.py + comm config"""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False


class FlopsProfilerConfig(ConfigModel):
    """ref: deepspeed/profiling/config.py"""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class MonitorConfig(ConfigModel):
    """ref: deepspeed/monitor/config.py"""

    enabled: bool = False
    tensorboard: Dict[str, Any] = Field(default_factory=dict)
    csv_monitor: Dict[str, Any] = Field(default_factory=dict)
    wandb: Dict[str, Any] = Field(default_factory=dict)


class PrefixCacheConfig(ConfigModel):
    """Automatic prefix caching for the ragged inference engine
    (inference/ragged.py): content-addressed reuse of full KV blocks
    across sequences sharing a prompt prefix, vLLM-PagedAttention style.

    pool_blocks caps the LRU pool of retired-but-cached blocks
    (refcount 0, contents kept for future hits): -1 keeps every retired
    cached block until allocation pressure evicts it; 0 disables
    parking (blocks shared only while a live sequence holds them)."""

    enabled: bool = True
    pool_blocks: int = -1


class PressureConfig(ConfigModel):
    """Memory-pressure governor for the serving scheduler
    (inference/pressure.py PressureGovernor; docs/fault_tolerance.md
    pressure section). Off by default: the committed serving baselines
    (MEMBUDGET / serving-sim / chaos lanes) pin the un-governed control
    plane, and flush-and-recompute preemption stays the legacy
    behavior until a deployment opts in.

    Watermarks are LIVE block-pool occupancy fractions (parked
    prefix-cache blocks are evictable headroom, not pressure), scaled
    down when the S004 warmup footprint crowds the HBM budget past
    `static_headroom` (see PressureGovernor.watermark_scale):

      occupancy >= yellow    evict up to yellow_trim_blocks LRU-parked
                             prefix-cache blocks per iteration
      occupancy >= red       preemption victims spill their paged KV to
                             the bounded pinned-host tier (spill_host_mb;
                             resume = import_kv, recompute on any
                             failure) instead of discarding it
      occupancy >= brownout  speculative mode degrades to plain decode,
                             the prefill chunk shrinks by
                             brownout_chunk_div, admission caps at
                             brownout_admit requests per iteration, and
                             the router engages fleet-wide fair shed

    hysteresis: the margin occupancy must clear a level's entry
    watermark by before the governor relaxes one level (per update)."""

    enabled: bool = False
    yellow: float = 0.65
    red: float = 0.85
    brownout: float = 0.95
    hysteresis: float = 0.05
    static_headroom: float = 0.8
    yellow_trim_blocks: int = 4
    spill_enabled: bool = True
    spill_host_mb: float = 256.0
    brownout_chunk_div: int = 4
    brownout_admit: int = 1

    @model_validator(mode="after")
    def _check(self):
        if not (0.0 < self.yellow <= self.red <= self.brownout <= 1.0):
            raise ValueError(
                "pressure watermarks need 0 < yellow <= red <= "
                "brownout <= 1")
        if self.hysteresis < 0 or self.hysteresis >= self.yellow:
            raise ValueError(
                "pressure.hysteresis must be in [0, yellow)")
        if self.static_headroom <= 0 or self.static_headroom > 1:
            raise ValueError("pressure.static_headroom must be in (0, 1]")
        if self.yellow_trim_blocks < 0 or self.spill_host_mb < 0:
            raise ValueError(
                "yellow_trim_blocks and spill_host_mb must be >= 0")
        if self.brownout_chunk_div < 1 or self.brownout_admit < 0:
            raise ValueError(
                "brownout_chunk_div must be >= 1, brownout_admit >= 0")
        return self


class ServingSchedulerConfig(ConfigModel):
    """Continuous-batching serving scheduler (inference/scheduler.py
    ServingScheduler) — the request-level control plane over the paged
    KV substrate.

    max_num_batched_tokens: per-iteration token budget (Sarathi-Serve's
    chunked-prefill knob): decode rows spend 1 token each, prefill
    chunks fill the remainder — so a long prompt never stalls decode.
    prefill_chunk: max prompt tokens one sequence feeds per iteration.
    decode_chunk: steady-state fused decode depth — when every active
    sequence is decoding (no prefill in flight), the scheduler
    dispatches ONE compiled multi-step program covering decode_chunk
    tokens (tokens stay device-resident between steps).
    admission: 'fcfs' stops at the first waiting request that does not
    fit the KV pool (strict arrival order); 'skip' keeps scanning the
    queue for later requests that do fit (no head-of-line blocking on
    capacity, mild reordering).
    prefill_mode: 'chunked' feeds prompts through the decode path in
    prefill_chunk pieces piggybacked on decode iterations (serving
    default); 'wave' prefills whole prompts through the compiled
    cross-prompt prefill waves (the generate() parity path).
    warmup: AOT-precompile the (bucket width x chunk) decode/sample
    grid at scheduler construction so steady-state serving triggers
    zero recompiles (engine.warmup).
    hbm_budget_gb: per-device HBM budget the warmup-measured bucket
    footprints are validated against at admit-config time (analysis/
    costmodel S004); 0 = auto from the running chip
    (platform/accelerator.py hbm_per_device).
    max_preemptions: preemption-starvation bound — a request preempted
    this many times becomes PROTECTED (never selected as a victim
    again; the requester yields instead), so every admitted request
    makes forward progress under sustained pressure. 0 disables the
    bound (the legacy youngest-first-always policy, which can ping-pong
    two similar-age requests forever).
    slo_classes: named SLO classes mapped to TTFT deadlines in modeled
    seconds (inference/pressure.py cost model) — submit(slo_class=...)
    resolves a deadline through this table; submit(deadline_s=...)
    passes one directly. A request whose admission-time TTFT estimate
    exceeds its deadline is rejected with finish_reason='deadline'
    BEFORE any KV block is touched.
    pressure: the memory-pressure governor block (PressureConfig)."""

    max_num_batched_tokens: int = 256
    prefill_chunk: int = 32
    decode_chunk: int = 1
    admission: str = "fcfs"
    prefill_mode: str = "chunked"
    warmup: bool = True
    hbm_budget_gb: float = 0.0
    max_preemptions: int = 8
    slo_classes: Dict[str, float] = Field(default_factory=dict)
    pressure: PressureConfig = Field(default_factory=PressureConfig)

    @model_validator(mode="after")
    def _check(self):
        if self.max_preemptions < 0:
            raise ValueError("max_preemptions must be >= 0 (0 = off)")
        for name, dl in self.slo_classes.items():
            if dl <= 0:
                raise ValueError(
                    f"slo_classes[{name!r}] deadline must be > 0 s")
        if self.admission not in ("fcfs", "skip"):
            raise ValueError(
                f"unknown admission policy '{self.admission}' "
                "(expected fcfs|skip)")
        if self.prefill_mode not in ("chunked", "wave"):
            raise ValueError(
                f"unknown prefill_mode '{self.prefill_mode}' "
                "(expected chunked|wave)")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        if self.max_num_batched_tokens < 1:
            raise ValueError("max_num_batched_tokens must be >= 1")
        if self.hbm_budget_gb < 0:
            raise ValueError("hbm_budget_gb must be >= 0 (0 = auto)")
        return self


class AutoscalerConfig(ConfigModel):
    """SLO-class autoscaler policy loop (inference/autoscaler.py
    Autoscaler; docs/autoscaling.md). Off by default — a fleet stays
    at its constructed size until a deployment opts in.

    Replica-count bounds: min_replicas / max_replicas clamp every
    decision (the policy never drains below min or spins past max).

    Scale-up signals, evaluated every evaluation_interval_s on the
    injectable clock (virtual-time sim and wall clock share one path):
    any replica's pressure level >= scale_up_pressure
    (inference/pressure.py: 1 yellow / 2 red / 3 brownout), fleet
    queue depth per live replica > scale_up_queue_per_replica, or a
    shed/deadline-rejection delta since the last evaluation. A signal
    must hold for up_hysteresis CONSECUTIVE evaluations before the
    fleet grows (occupancy noise at a watermark must not flap the
    fleet size), except when the delta includes a class named in
    premium_classes — a premium-impact event is already an SLO breach,
    so it bypasses hysteresis (cooldown still applies).

    Scale-down: pressure GREEN everywhere, queue depth per replica <
    scale_down_queue_per_replica, and no shed/rejection activity, held
    for down_hysteresis consecutive evaluations. Cooldowns are
    asymmetric (scale_up_cooldown_s < scale_down_cooldown_s: growing
    is cheap and urgent, shrinking wrong costs a spin-up later), and
    any scale action resets both.

    Spin-up failure policy: a failed add_replica (the chaos point
    'replica.spinup' models a replica killed mid-scale-up) burns the
    attempt and retries after spinup_retry_backoff_s, doubling up to
    spinup_max_retries attempts before the policy loop re-arms on the
    next scale-up signal."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    evaluation_interval_s: float = 1.0
    scale_up_pressure: int = 2
    scale_up_queue_per_replica: float = 4.0
    scale_down_queue_per_replica: float = 1.0
    up_hysteresis: int = 2
    down_hysteresis: int = 4
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 30.0
    spinup_retry_backoff_s: float = 1.0
    spinup_max_retries: int = 3
    premium_classes: List[str] = Field(default_factory=list)

    @model_validator(mode="after")
    def _check(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.evaluation_interval_s <= 0:
            raise ValueError("evaluation_interval_s must be > 0")
        if not (0 <= self.scale_up_pressure <= 3):
            raise ValueError(
                "scale_up_pressure must be a pressure level in [0, 3]")
        if self.scale_up_queue_per_replica < 0 \
                or self.scale_down_queue_per_replica < 0:
            raise ValueError("queue watermarks must be >= 0")
        if self.scale_down_queue_per_replica \
                > self.scale_up_queue_per_replica:
            raise ValueError(
                "scale_down_queue_per_replica must be <= "
                "scale_up_queue_per_replica (the dead band must exist)")
        if self.up_hysteresis < 1 or self.down_hysteresis < 1:
            raise ValueError("hysteresis counts must be >= 1")
        if self.scale_up_cooldown_s < 0 or self.scale_down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.spinup_retry_backoff_s <= 0 or self.spinup_max_retries < 0:
            raise ValueError(
                "spinup_retry_backoff_s must be > 0, "
                "spinup_max_retries >= 0")
        return self


class ServingRouterConfig(ConfigModel):
    """Multi-replica serving front door (inference/router.py
    ServingRouter) — the fleet layer over N ServingScheduler-backed
    engine replicas.

    replicas: fleet size (informational when engines are passed
    explicitly; a mismatch with the engine list raises).
    policy: 'prefix_aware' scores each replica by
    ``load/max_batch - cache_weight * cached_prefix_fraction`` using
    the blake2b hash-chain prefix index as the locality signal;
    'round_robin' ignores locality (the comparison baseline).
    cache_weight: how many normalized-load units a fully-cached prompt
    is worth — 0 reduces prefix_aware to pure least-loaded.
    session_affinity: pin multi-turn sessions to their replica (turn
    N+1 extends turn N's cached prefix); a pin breaks when the pinned
    replica's backlog exceeds the least-loaded replica's by
    affinity_evict_margin requests.
    mode: 'colocated' replicas each run prefill AND decode;
    'disaggregated' dedicates the first prefill_replicas replicas to
    chunked prefill and hands finished sequences' paged KV blocks to
    the decode replicas (DistServe/Splitwise) — fleets too small to
    split fall back to colocated with a log line.
    speculative_replicas: run the LAST K decode replicas' schedulers
    in speculative mode (prompt-lookup self-drafting, greedy-only) —
    the per-replica mode flag the router reports through metrics().
    scheduler: the per-replica ServingSchedulerConfig.

    Self-healing (deepspeed_tpu/resilience, docs/fault_tolerance.md):
    health_enabled turns on the per-replica circuit breaker — a
    replica whose dispatch raises (or, with dispatch_deadline_s > 0,
    overruns the deadline) failure_threshold times in a row is failed
    over AUTOMATICALLY (the fail_replica requeue machinery, no manual
    call), then probed after an exponential backoff
    (breaker_backoff_s doubling by breaker_backoff_mult up to
    breaker_backoff_max_s) and restored when the probe succeeds.
    handoff_timeout_s > 0 bounds each KV export+import; a timed-out or
    failed transfer falls back to the token-identical
    requeue-for-recompute path. max_fleet_queue > 0 bounds the fleet's
    total waiting queue; over it, submissions shed per shed_policy:
    'fair' sheds the queue-heaviest session's newest waiting request
    (the submitting session itself when it is the heaviest),
    'reject' always sheds the new request.

    Pressure integration (inference/pressure.py; active only when the
    per-replica scheduler's pressure governor is enabled):
    pressure_routing_weight folds each replica's pressure level into
    its routing score (normalized level x weight in load units — a RED
    replica must be much cheaper on every other axis to win a pick,
    and BROWNOUT replicas are skipped entirely while a calmer replica
    exists). max_handoff_backlog > 0 bounds each prefill replica's
    handoff_ready backlog: pump() stops moving sequences to decode
    replicas that are saturated (batch-full or pressure >= RED),
    leaving them parked instead of force-recomputing, and routing
    stops picking prefill replicas already at the backlog bound
    (counters handoff_backpressure / prefill_backpressure in
    router.metrics()). brownout_shed engages the fair-shed machinery
    fleet-wide while EVERY live replica sits at BROWNOUT, even when
    max_fleet_queue is unbounded (the effective bound becomes the
    fleet's live batch capacity)."""

    # -- replica lifecycle (docs/autoscaling.md) ------------------------
    # warm_prefix_limit: how many of the donor's hottest parked prefix
    # chains a joining replica imports at spin-up (add_replica warm
    # boot; 0 = always join cache-cold). autoscaler: the SLO-class
    # autoscaler policy block (inference/autoscaler.py; disabled by
    # default — construction-time fleet size is final until enabled).
    warm_prefix_limit: int = 8
    autoscaler: AutoscalerConfig = Field(default_factory=AutoscalerConfig)

    replicas: int = 1
    policy: str = "prefix_aware"
    cache_weight: float = 2.0
    session_affinity: bool = True
    affinity_evict_margin: int = 4
    mode: str = "colocated"
    prefill_replicas: int = 1
    speculative_replicas: int = 0
    health_enabled: bool = True
    failure_threshold: int = 3
    dispatch_deadline_s: float = 0.0
    breaker_backoff_s: float = 1.0
    breaker_backoff_mult: float = 2.0
    breaker_backoff_max_s: float = 30.0
    handoff_timeout_s: float = 0.0
    max_fleet_queue: int = 0
    shed_policy: str = "fair"
    pressure_routing_weight: float = 1.0
    max_handoff_backlog: int = 0
    brownout_shed: bool = True
    scheduler: ServingSchedulerConfig = Field(
        default_factory=ServingSchedulerConfig)

    @model_validator(mode="after")
    def _check(self):
        if self.warm_prefix_limit < 0:
            raise ValueError("warm_prefix_limit must be >= 0 (0 = cold)")
        if self.pressure_routing_weight < 0:
            raise ValueError("pressure_routing_weight must be >= 0")
        if self.max_handoff_backlog < 0:
            raise ValueError(
                "max_handoff_backlog must be >= 0 (0 = unbounded)")
        if self.policy not in ("prefix_aware", "round_robin"):
            raise ValueError(
                f"unknown routing policy '{self.policy}' "
                "(expected prefix_aware|round_robin)")
        if self.mode not in ("colocated", "disaggregated"):
            raise ValueError(
                f"unknown router mode '{self.mode}' "
                "(expected colocated|disaggregated)")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.prefill_replicas < 1:
            raise ValueError("prefill_replicas must be >= 1")
        if self.speculative_replicas < 0:
            raise ValueError("speculative_replicas must be >= 0")
        if self.cache_weight < 0:
            raise ValueError("cache_weight must be >= 0")
        if self.affinity_evict_margin < 0:
            raise ValueError("affinity_evict_margin must be >= 0")
        if self.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if self.dispatch_deadline_s < 0 or self.handoff_timeout_s < 0:
            raise ValueError("deadlines/timeouts must be >= 0 (0 = off)")
        if self.breaker_backoff_s <= 0 or self.breaker_backoff_mult < 1 \
                or self.breaker_backoff_max_s < self.breaker_backoff_s:
            raise ValueError(
                "breaker backoff needs backoff_s > 0, mult >= 1, "
                "max >= backoff_s")
        if self.max_fleet_queue < 0:
            raise ValueError("max_fleet_queue must be >= 0 (0 = unbounded)")
        if self.shed_policy not in ("fair", "reject"):
            raise ValueError(
                f"unknown shed_policy '{self.shed_policy}' "
                "(expected fair|reject)")
        return self


class CurriculumConfig(ConfigModel):
    """ref: runtime/data_pipeline/curriculum_scheduler.py config (the
    legacy 'curriculum_learning' block). Consumed by the engine: with
    curriculum_type='seqlen' every train batch is truncated to the
    scheduled difficulty (each difficulty level costs one recompile)."""

    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)


class ElasticityConfig(ConfigModel):
    """ref: deepspeed/elasticity/config.py ElasticityConfig — consumed by
    deepspeed_tpu.elasticity.compute_elastic_config and the engine (which
    derives the batch triangle from the current device count)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    # scheduler-level knobs, accepted for config compatibility
    min_time: int = 0
    version: float = 0.1
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1


class AutotuningConfig(ConfigModel):
    """ref: deepspeed/autotuning/config.py — consumed by
    deepspeed_tpu.autotuning.Autotuner (the engine itself ignores it,
    matching the reference where the launcher drives tuning)."""

    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    metric: str = "throughput"


class CheckpointConfig(ConfigModel):
    """ref: runtime/checkpoint_engine + engine save/load knobs"""

    use_node_local_storage: bool = False
    load_universal: bool = False
    async_save: bool = False


class ProgressiveLayerDropConfig(ConfigModel):
    """ref: runtime/progressive_layer_drop.py ProgressiveLayerDrop:10 +
    constants PLD_THETA/PLD_GAMMA. theta(t) = (1-θ)·exp(-γt) + θ decays
    from 1 (keep everything) toward θ; the engine injects it into each
    micro-batch and the model drops layer l with prob
    (l+1)/L · (1-theta) via lax.cond (compute actually skipped)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class DataEfficiencyConfig(ConfigModel):
    """ref: runtime/data_pipeline/config.py get_data_efficiency_config +
    constants.py field names. `data_sampling.curriculum_learning` is
    consumed by runtime/data_analyzer.py build_curriculum_sampler (the
    DeepSpeedDataSampler analog); the analyzer artifacts it reads come
    from runtime/data_analyzer.py DataAnalyzer."""

    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)

    @model_validator(mode="after")
    def _check_routing(self):
        routing = dict(self.data_routing or {})
        if (self.enabled and routing.get("enabled")
                and routing.get("random_ltd", {}).get("enabled")):
            # random-LTD is a model-graph transform here, not a dataloader
            # one — refuse the dataloader-side knob rather than no-op it
            raise NotImplementedError(
                "data_routing.random_ltd is configured on the model in "
                "deepspeed_tpu (TransformerConfig random_ltd_* fields drive "
                "the in-graph token-drop layers); the dataloader-side block "
                "has no consumer"
            )
        return self


class NebulaConfig(ConfigModel):
    """Tiered checkpoint service knobs (ref: nebula/config.py
    DeepSpeedNebulaConfig + nebula/constants.py defaults). Consumed by
    runtime/checkpoint.py TieredCheckpointEngine: fast node-local tier
    with version retention + interval-persisted durable tier."""

    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: float = 100.0
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True
    load_path: Optional[str] = None


class DeepSpeedTPUConfig(ConfigModel):
    """The full config tree (ref: runtime/config.py DeepSpeedConfig)."""

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    seed: int = 1234
    # ref: runtime/config.py communication_data_type — the dtype
    # gradient-reduction collectives are DECLARED to carry (None = the
    # compute dtype, the reference default for fp16/bf16 training).
    # Verified against the compiled HLO by analysis/numerics.py N001.
    communication_data_type: Optional[str] = None

    optimizer: OptimizerConfig = Field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = Field(default_factory=SchedulerConfig)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    bf16: BF16Config = Field(default_factory=BF16Config)
    fp16: FP16Config = Field(default_factory=FP16Config)
    data_types: DataTypesConfig = Field(default_factory=DataTypesConfig)
    integrity: IntegrityConfig = Field(default_factory=IntegrityConfig)
    mesh: MeshConfig = Field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig
    )
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    monitor: MonitorConfig = Field(default_factory=MonitorConfig)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    nebula: NebulaConfig = Field(default_factory=NebulaConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = Field(default_factory=AutotuningConfig)
    curriculum_learning: CurriculumConfig = Field(default_factory=CurriculumConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = Field(
        default_factory=ProgressiveLayerDropConfig
    )
    # compression training (ref: compression/config.py — deep free-form
    # schema validated by compression.init_compression at engine build)
    compression_training: Optional[Dict[str, Any]] = None

    @model_validator(mode="after")
    def _check_precision(self):
        if self.bf16.enabled and self.fp16.enabled:
            raise ValueError("bf16 and fp16 cannot both be enabled")
        return self

    @model_validator(mode="after")
    def _check_implemented(self):
        """Unimplemented knobs raise instead of silently doing nothing
        (VERDICT r1 W2: 'dead config knobs are silent lies')."""
        z = self.zero_optimization
        unimpl = []
        if z.zero_quantized_nontrainable_weights:
            unimpl.append(
                "zero_optimization.zero_quantized_nontrainable_weights "
                "(serve frozen models via inference PTQ: init_inference "
                "quantization={'bits': 8})"
            )
        if z.offload_param.device != OffloadDevice.none:
            # ZeRO-Infinity param tier is a stage-3 feature, matching the
            # reference's assertion (zero/config.py offload_param is
            # consumed only by stage3.py / parameter_offload.py). The nvme
            # tier additionally requires offload_optimizer=nvme (engine
            # check — params re-materialize from the optimizer swap files).
            if z.stage != 3:
                raise ValueError(
                    "zero_optimization.offload_param requires zero stage 3"
                )
        if (
            self.activation_checkpointing.cpu_checkpointing
            and self.activation_checkpointing.policy != "dots_no_batch"
        ):
            # the host tier offloads the saved dot outputs — there must BE a
            # saveable-dots policy to offload (ref: checkpointing.py:989
            # cpu_checkpointing moves the checkpointed activations to CPU)
            raise ValueError(
                "activation_checkpointing.cpu_checkpointing requires "
                "policy='dots_no_batch' (the saved dot outputs are what "
                "moves to host DRAM)"
            )
        if self.checkpoint.use_node_local_storage:
            unimpl.append(
                "checkpoint.use_node_local_storage (use the nebula block: "
                "fast node-local tier + durable persistent_storage_path)"
            )
        if self.prescale_gradients:
            unimpl.append("prescale_gradients")
        if unimpl:
            raise NotImplementedError(
                "config enables features not yet implemented in deepspeed_tpu: "
                + "; ".join(unimpl)
            )
        return self

    # --- batch triangle (ref: runtime/config.py batch assertions) --------
    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Solve train = micro × GAS × dp_world, filling in missing values.

        Reproduces the reference's resolution order: given any two of
        (train, micro, GAS) derive the third; given one, assume the others.
        """
        train, micro, gas = (
            self.train_batch_size,
            self.train_micro_batch_size_per_gpu,
            self.gradient_accumulation_steps,
        )
        if train is not None and micro is not None and gas is None:
            if train % (micro * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {train} not divisible by micro*dp = "
                    f"{micro}*{dp_world_size}"
                )
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None and micro is None:
            if train % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {train} not divisible by gas*dp = "
                    f"{gas}*{dp_world_size}"
                )
            micro = train // (gas * dp_world_size)
        elif micro is not None:
            gas = gas or 1
            train = train or micro * gas * dp_world_size
        elif train is not None:
            gas = gas or 1
            if train % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {train} not divisible by gas*dp = "
                    f"{gas}*{dp_world_size}"
                )
            micro = train // (gas * dp_world_size)
        else:
            raise ValueError(
                "config must set at least one of train_batch_size / "
                "train_micro_batch_size_per_gpu"
            )
        if train != micro * gas * dp_world_size:
            raise ValueError(
                f"batch triangle inconsistent: train={train} != micro={micro} "
                f"× gas={gas} × dp={dp_world_size}"
            )
        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    # --- convenience ----------------------------------------------------
    @property
    def zero_stage(self) -> int:
        return self.zero_optimization.stage

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32


# Reference-era keys with no TPU meaning, accepted and dropped WITH a
# warning so stock reference configs parse here (the module docstring's
# compatibility promise). Keyed by block path ("" = top level). These are
# knobs whose function is subsumed by XLA (bucket sizes, prefetch limits,
# process-level fetch machinery) or by torch-only machinery we don't port
# (SURVEY §7 "what we explicitly do NOT port").
_REFERENCE_NOOP_KEYS: Dict[str, tuple] = {
    # communication_data_type / data_types became REAL knobs in PR 5
    # (the numerics sanitizer's declared precision policy) — no longer
    # dropped here.
    "": (
        "zero_allow_untested_optimizer",
        "sparse_gradients", "amp", "dump_state", "memory_breakdown",
        "gradient_predivide_factor", "dataloader_drop_last",
        "use_data_before_expert_parallel_",
    ),
    "zero_optimization": (
        # bucketing/prefetch/fetch machinery → XLA SPMD scheduling
        "allgather_partitions", "allgather_bucket_size", "reduce_scatter",
        "reduce_bucket_size", "stage3_prefetch_bucket_size",
        "stage3_max_live_parameters", "stage3_max_reuse_distance",
        "stage3_gather_16bit_weights_on_model_save", "sub_group_size",
        "round_robin_gradients", "ignore_unused_parameters",
        "legacy_stage1", "stage3_gather_fp16_weights_on_model_save",
        "elastic_checkpoint",
    ),
    "fp16": ("auto_cast", "fp16_master_weights_and_grads"),
    "bf16": ("immediate_grad_update",),
    "activation_checkpointing": (
        "contiguous_memory_optimization", "synchronize_checkpoint_boundary",
        "profile",
    ),
    "autotuning": (
        # launcher/experiment plumbing subsumed by in-process measurement
        "exps_dir", "overwrite", "start_profile_step", "end_profile_step",
        "metric_path", "arg_mappings", "max_train_batch_size",
        "min_train_batch_size", "max_train_micro_batch_size_per_gpu",
        "min_train_micro_batch_size_per_gpu", "num_tuning_micro_batch_sizes",
        "tuner_type", "tuner_early_stopping", "tuner_num_trials",
        "model_info", "model_info_path", "mp_size", "num_nodes", "num_gpus",
    ),
}

# Renames: reference key → our key (same block).
_REFERENCE_RENAMES: Dict[str, Dict[str, str]] = {
    "zero_optimization": {"stage3_param_persistence_threshold": "param_persistence_threshold"},
}

# Whole reference config blocks naming features that do not exist yet —
# presence raises (silent acceptance would be a lie).
_UNIMPLEMENTED_BLOCKS = ()


def _compat_filter(config: Dict[str, Any]) -> Dict[str, Any]:
    from ..utils.logging import logger

    config = {k: (dict(v) if isinstance(v, dict) else v) for k, v in config.items()}

    def _enabled(block):
        # stock reference configs often carry disabled blocks
        # ({"autotuning": {"enabled": false}}) — those parse fine
        if isinstance(block, dict) and "enabled" in block:
            return bool(block["enabled"])
        return bool(block)

    if "hybrid_engine" in config and _enabled(config.get("hybrid_engine")):
        raise NotImplementedError(
            "the hybrid_engine config block has no engine-level consumer; "
            "wrap the training engine explicitly: "
            "deepspeed_tpu.runtime.hybrid_engine.HybridEngine(engine, "
            "model_config, inference_config)"
        )
    config.pop("hybrid_engine", None)
    if "sparse_attention" in config and _enabled(config.get("sparse_attention")):
        raise NotImplementedError(
            "the sparse_attention config block has no engine-level consumer "
            "(models are functional here); enable it on the model instead: "
            "TransformerConfig(attention_impl='sparse', sparse_mode=..., "
            "sparse_block=...)"
        )
    config.pop("sparse_attention", None)
    present = [b for b in _UNIMPLEMENTED_BLOCKS
               if b in config and _enabled(config.pop(b))]
    if present:
        raise NotImplementedError(
            f"config blocks not yet implemented in deepspeed_tpu: {present}"
        )
    if float(config.get("gradient_predivide_factor", 1.0) or 1.0) != 1.0:
        raise NotImplementedError(
            "gradient_predivide_factor != 1.0 is not implemented (grad "
            "reduction is a fused fp32 psum-mean on TPU)"
        )
    for path, keys in _REFERENCE_NOOP_KEYS.items():
        block = config if path == "" else config.get(path)
        if not isinstance(block, dict):
            continue
        dropped = [k for k in keys if k in block]
        for k in dropped:
            block.pop(k)
        if dropped:
            where = path or "config"
            logger.warning(
                f"{where}: ignoring reference-era keys with no TPU meaning: {dropped}"
            )
    for path, renames in _REFERENCE_RENAMES.items():
        block = config.get(path)
        if isinstance(block, dict):
            for old, new in renames.items():
                if old in block and new not in block:
                    block[new] = block.pop(old)
    return config


def parse_config(config: Union[str, Dict[str, Any], DeepSpeedTPUConfig, None]) -> DeepSpeedTPUConfig:
    """Accept a path to a JSON file, a dict, or an already-built config.

    Reference-schema compatibility: known no-op keys are dropped with a
    warning; keys/blocks naming unimplemented features raise."""
    if config is None:
        return DeepSpeedTPUConfig()
    if isinstance(config, DeepSpeedTPUConfig):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"config must be path/dict/DeepSpeedTPUConfig, got {type(config)}")
    return DeepSpeedTPUConfig(**_compat_filter(config))
