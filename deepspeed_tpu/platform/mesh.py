"""Device-mesh construction and topology introspection.

TPU-native replacement for the reference's process-group topology
machinery (ref: deepspeed/utils/groups.py, runtime/pipe/topology.py —
ProcessTopology:12, PipeModelDataParallelTopology:244). Where the
reference builds cartesian rank grids plus torch ProcessGroups, here the
whole cluster is one `jax.sharding.Mesh` with named axes; "groups" are
mesh axes and collectives ride ICI/DCN as XLA chooses.

Axis names (fixed vocabulary, any may be size 1):
  pipe    — pipeline stages           (ref: runtime/pipe/)
  data    — data parallel / ZeRO      (ref: groups.py:385)
  zero    — ZeRO sub-group (MiCS/hpZ) (ref: runtime/zero/mics.py:64,
            zero_hpz_partition_size config.py:264): when >1, the data
            dimension is factored data×zero and ZeRO state shards over
            'zero' only, replicating across 'data' groups — sharding
            collectives stay on the fast intra-group links
  expert  — expert parallel for MoE   (ref: groups.py:113-290)
  seq     — Ulysses sequence parallel (ref: deepspeed/sequence/layer.py)
  model   — tensor parallel           (ref: module_inject AutoTP)

Order is outermost→innermost: 'model' is fastest-varying so TP
collectives ride the highest-bandwidth ICI links; 'pipe' is outermost so
stage boundaries may cross DCN; 'zero' sits inside 'data' so sub-group
gathers ride shorter paths than cross-group traffic.
"""

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.logging import logger

MESH_AXES = ("pipe", "data", "zero", "expert", "seq", "model")

# Axes over which a batch is sharded (data-parallel-like axes).
BATCH_AXES = ("data", "zero", "expert")


def resolve_axis_sizes(
    axis_sizes: Dict[str, int], n_devices: Optional[int] = None
) -> Dict[str, int]:
    """Fill in a single -1 axis from the device count and validate the product."""
    if n_devices is None:
        n_devices = len(jax.devices())
    sizes = {ax: int(axis_sizes.get(ax, 1)) for ax in MESH_AXES}
    wildcard = [ax for ax, s in sizes.items() if s == -1]
    if len(wildcard) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wildcard:
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes product {fixed}"
            )
        sizes[wildcard[0]] = n_devices // fixed
        fixed = n_devices
    if fixed != n_devices:
        raise ValueError(
            f"mesh axes {sizes} multiply to {fixed} but there are {n_devices} devices"
        )
    return sizes


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global Mesh.

    On real TPU slices this uses `mesh_utils.create_device_mesh` so axis
    adjacency maps onto the physical ICI torus; on CPU/fake platforms a
    plain reshape of the device list is used.
    """
    if devices is None:
        devices = jax.devices()
    sizes = resolve_axis_sizes(axis_sizes or {}, n_devices=len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    if devices[0].platform in ("tpu",) and len(devices) > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
            return Mesh(dev_array, MESH_AXES)
        except Exception as e:  # pragma: no cover - topology-dependent
            logger.warning(f"mesh_utils.create_device_mesh failed ({e}); using reshape order")
    dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    return build_mesh({ax: 1 for ax in MESH_AXES})


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name]


def data_parallel_size(mesh: Mesh) -> int:
    """World size of the batch-sharded axes (data × expert).

    Mirrors the reference notion that the expert-parallel group is carved
    out of the data-parallel world (ref: groups.py:113
    _create_expert_and_data_parallel).
    """
    return int(np.prod([mesh.shape[ax] for ax in BATCH_AXES]))


def describe(mesh: Mesh) -> str:
    parts = [f"{ax}={mesh.shape[ax]}" for ax in mesh.axis_names if mesh.shape[ax] > 1]
    return "Mesh(" + (", ".join(parts) or "1 device") + f", {mesh.size} devices)"
