"""Engine end-to-end tests on the virtual 8-device mesh.

Ref model: tests/unit/runtime/zero/test_zero.py correctness strategy —
tiny models, loss-equality across configurations. Here the key
invariant is that every parallelism layout (ZeRO stage, TP, Ulysses,
GAS split) computes the SAME global training trajectory.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow

VOCAB = 128


def model_cfg(**kw):
    base = dict(vocab_size=VOCAB, n_layers=2, n_heads=4, d_model=64, max_seq=32,
                variant="llama", use_flash=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def ds_config(**kw):
    base = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "seed": 7,
        "steps_per_print": 1000,
    }
    base.update(kw)
    return base


def build_engine(mcfg=None, **cfg_kw):
    mcfg = mcfg or model_cfg()
    return ds.initialize(
        ds_config(**cfg_kw),
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
    )


def data(n=3, batch=16, seq=33, seed=0):
    r = np.random.default_rng(seed)
    return [{"tokens": r.integers(0, VOCAB, (batch, seq)).astype(np.int32)} for _ in range(n)]


def losses(engine, batches):
    return [engine.train_batch(b)["loss"] for b in batches]


class TestTraining:
    def test_loss_decreases(self):
        engine = build_engine()
        batch = data(1)[0]
        ls = [engine.train_batch(batch)["loss"] for _ in range(8)]
        assert ls[-1] < ls[0]

    def test_eval_batch(self):
        engine = build_engine()
        loss = engine.eval_batch(data(1, batch=8)[0])
        assert np.isfinite(loss) and loss > 0


class TestZeroEquivalence:
    """Stages 0-3 must produce identical trajectories (fp32)."""

    @pytest.fixture(scope="class")
    def baseline(self):
        engine = build_engine(zero_optimization={"stage": 0})
        return losses(engine, data())

    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_stage_matches_baseline(self, baseline, stage):
        engine = build_engine(
            zero_optimization={"stage": stage, "param_persistence_threshold": 64}
        )
        ls = losses(engine, data())
        np.testing.assert_allclose(ls, baseline, rtol=2e-4)

    def test_stage3_actually_shards_params(self):
        engine = build_engine(
            zero_optimization={"stage": 3, "param_persistence_threshold": 64}
        )
        w = engine.state.params["layers"]["w_in"]
        assert "data" in str(w.sharding.spec)


class TestParallelismEquivalence:
    """Different mesh layouts, same global batch of 16 → same trajectory."""

    @pytest.fixture(scope="class")
    def baseline(self):
        engine = build_engine(mesh={"data": -1}, train_batch_size=16)
        return losses(engine, data())

    def test_tensor_parallel(self, baseline):
        engine = build_engine(mesh={"data": 4, "model": 2}, train_batch_size=16, gradient_accumulation_steps=2)
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_ulysses_sequence_parallel(self, baseline):
        engine = build_engine(mesh={"data": 4, "seq": 2}, train_batch_size=16, gradient_accumulation_steps=2)
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_tp_and_zero3_compose(self, baseline):
        engine = build_engine(
            mesh={"data": 4, "model": 2},
            train_batch_size=16,
            gradient_accumulation_steps=2,
            zero_optimization={"stage": 3, "param_persistence_threshold": 64},
        )
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_tp_params_sharded(self):
        engine = build_engine(mesh={"data": 4, "model": 2}, train_batch_size=16, gradient_accumulation_steps=2)
        w = engine.state.params["layers"]["w_in"]  # [L, E, F] → F over model
        assert "model" in str(w.sharding.spec)


class TestBatchHandling:
    def test_rank1_batch_leaf(self):
        # a per-microbatch scalar leaf [gas] must shard/reshape cleanly
        engine = build_engine(gradient_accumulation_steps=2,
                              train_micro_batch_size_per_gpu=1)
        r = np.random.default_rng(0)
        out = engine.shard_batch(
            {"tokens": r.integers(0, VOCAB, (2, 8, 33)).astype(np.int32),
             "weight": np.ones((2,), np.float32)},
            leading_accum_dim=True,
        )
        assert out["weight"].shape == (2,)


class TestGradientAccumulation:
    def test_gas_equivalence(self):
        # same global batch, different micro/gas split → same trajectory
        e1 = build_engine(train_micro_batch_size_per_gpu=2, gradient_accumulation_steps=1)
        e2 = build_engine(train_micro_batch_size_per_gpu=1, gradient_accumulation_steps=2)
        batches = data(3)
        np.testing.assert_allclose(losses(e1, batches), losses(e2, batches), rtol=2e-4)


class TestPrecisionModes:
    def test_bf16_trains(self):
        engine = build_engine(bf16={"enabled": True}, zero_optimization={"stage": 2})
        batch = data(1)[0]
        ls = [engine.train_batch(batch)["loss"] for _ in range(6)]
        assert ls[-1] < ls[0]
        # params stored bf16, master fp32
        assert engine.state.params["embed"].dtype == jax.numpy.bfloat16
        assert engine.state.master["embed"].dtype == jax.numpy.float32

    def test_fp16_loss_scaling(self):
        engine = build_engine(
            fp16={"enabled": True, "initial_scale_power": 8}, zero_optimization={"stage": 1}
        )
        batch = data(1)[0]
        m = engine.train_batch(batch)
        assert m["loss_scale"] >= 256.0
        assert m["skipped"] in (0.0, 1.0)

    def test_gpt2_variant(self):
        mcfg = model_cfg(variant="gpt2", tie_embeddings=True)
        engine = build_engine(mcfg=mcfg)
        batch = data(1)[0]
        ls = [engine.train_batch(batch)["loss"] for _ in range(5)]
        assert ls[-1] < ls[0]


class TestRound2Fixes:
    def test_pipe_axis_raises_until_pp(self):
        """VERDICT r1 W3: a pipe axis that nothing consumes must not
        silently waste devices."""
        with pytest.raises(NotImplementedError):
            build_engine(mesh={"pipe": 2, "data": 4})

    def test_eval_has_no_dropout(self):
        """VERDICT r1 W5 / ADVICE: eval must run with dropout disabled —
        repeated eval_batch calls return the identical loss."""
        mcfg = model_cfg(dropout=0.5)
        engine = build_engine(mcfg)
        b = data(1, batch=8)[0]
        assert engine.eval_batch(b) == engine.eval_batch(b)

    def test_activation_checkpointing_policy_changes_program(self):
        """VERDICT r1 item 6: the DeepSpeed-style activation_checkpointing
        block must actually drive rematerialization (remat shows up in the
        compiled step) without changing numerics."""
        batches = data(2)
        ref = losses(build_engine(), batches)

        engine = build_engine(activation_checkpointing={"policy": "full"})
        got = losses(engine, batches)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

        jaxpr = str(jax.make_jaxpr(
            engine._build_train_step().__wrapped__
        )(engine.state, engine.shard_batch(
            engine._reshape_gas(batches[0]), leading_accum_dim=True)))
        assert "remat" in jaxpr or "checkpoint" in jaxpr


class TestActivationOffload:
    """cpu_checkpointing + partition_activations (ref: runtime/
    activation_checkpointing/checkpointing.py:989)."""

    def test_cpu_checkpointing_matches_dots_no_batch(self):
        batches = data(2)
        ref = losses(
            build_engine(activation_checkpointing={"policy": "dots_no_batch"}),
            batches,
        )
        engine = build_engine(activation_checkpointing={
            "policy": "dots_no_batch", "cpu_checkpointing": True})
        got = losses(engine, batches)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # the saved-dot host transfers are in the traced program (XLA:CPU
        # may elide the placement custom-calls in the final HLO — host and
        # device memory coincide there; on TPU they lower to D2H/H2D)
        jaxpr = str(jax.make_jaxpr(
            engine._build_train_step().__wrapped__
        )(engine.state, engine.shard_batch(
            engine._reshape_gas(batches[0]), leading_accum_dim=True)))
        assert "<host>" in jaxpr  # offloaded residuals are host-typed

    def test_cpu_checkpointing_requires_dots_no_batch(self):
        with pytest.raises(ValueError, match="dots_no_batch"):
            build_engine(activation_checkpointing={
                "policy": "full", "cpu_checkpointing": True})

    def test_partition_activations_not_replicated_over_model_axis(self):
        """partition_activations is satisfied BY DESIGN under SPMD: remat-
        saved residuals stay sharded over the model axis. Evidence: at a
        fixed global batch, the per-device temp footprint with tp=4 stays
        ~equal to pure-dp (were activations replicated across the 4 model
        ranks — what the reference flag exists to prevent — it would be
        ~4x larger)."""
        def temp_bytes(micro, **mesh):
            engine = build_engine(
                activation_checkpointing={"policy": "dots_no_batch",
                                          "partition_activations": True},
                train_micro_batch_size_per_gpu=micro,
                mesh=mesh,
            )
            losses(engine, data(1))
            return engine._train_compiled.memory_analysis().temp_size_in_bytes

        tp = temp_bytes(8, model=4, data=2)   # global batch 16 = 8 x dp2
        dp = temp_bytes(2, model=1, data=8)   # global batch 16 = 2 x dp8
        assert tp < 2.5 * dp
