"""SLO-class autoscaler: the policy loop that makes the serving fleet
ELASTIC (docs/autoscaling.md).

The resilience arc gave the fleet failover (PR 7), overload governance
(PR 10), and a replica LIFECYCLE (inference/router.py add_replica /
drain_replica) — but something still has to decide WHEN the fleet
grows and shrinks. Static provisioning is the alternative, and it is
wrong twice a day: sized for the diurnal peak it burns replica-hours
all night; sized for the valley it sheds load every evening. The
`Autoscaler` closes the loop on the PR-10 pressure/overload signals
(max pressure level, queue depth per replica, shed and
deadline-rejection rates) against per-tenant SLO classes:

- **signals, not wall clocks**: every input is a counter the router
  already maintains; evaluation runs on the injectable clock
  (resilience/health.py's convention), so the deterministic
  virtual-time diurnal sim (bench.py --autoscale-sim) and wall-clock
  serving drive ONE policy path.
- **hysteresis + asymmetric cooldowns**: a scale-up signal must hold
  for `up_hysteresis` consecutive evaluations (occupancy noise at a
  watermark must not flap the fleet), scale-down for the longer
  `down_hysteresis`; any action opens a cooldown window
  (scale_up_cooldown_s < scale_down_cooldown_s — growing is urgent,
  shrinking wrong costs a spin-up later).
- **premium bypass**: a shed or deadline rejection hitting a class in
  `premium_classes` is already an SLO breach — it bypasses hysteresis
  (cooldown still applies) so the fleet grows on the FIRST premium
  impact, not the third.
- **burned spin-ups retry with backoff**: a scale-up that raises (the
  'replica.spinup' chaos point models a replica killed mid-scale-up)
  is burned; the autoscaler retries after spinup_retry_backoff_s,
  doubling per attempt up to spinup_max_retries, then re-arms on the
  next scale-up signal.

The policy is fleet-agnostic: it talks to a duck-typed fleet object
(`live_replicas()`, `signals()`, `scale_up(now)`, `scale_down(now)`,
optional `note_time(now)`), so the macro diurnal simulator's fluid
fleet model and the real router (via `RouterFleetAdapter`) exercise
EXACTLY the same decision code — what the AUTOSCALE.json gate
measures over millions of simulated sessions is the code production
runs.
"""

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ..config.config import AutoscalerConfig
from ..utils.logging import log_dist
from .pressure import GREEN
from .router import ReplicaDrainError, ServingRouter

__all__ = ["Autoscaler", "AutoscalerConfig", "RouterFleetAdapter"]


class Autoscaler:
    """The policy loop. `fleet` is any object implementing:

      live_replicas() -> int      capacity-bearing replicas (routable
                                  + warming: capacity already paid for
                                  counts against min/max even before
                                  it joins routing)
      signals() -> dict           cumulative counters + instantaneous
                                  gauges: queue_depth,
                                  max_pressure_level, shed_requests,
                                  deadline_rejections, premium_sheds,
                                  premium_rejections
      scale_up(now)               add one replica; raises on a burned
                                  spin-up (the autoscaler retries)
      scale_down(now) -> bool     drain one replica; False when no
                                  legal victim exists
      note_time(now)              optional: advance the fleet's
                                  replica-hour integral

    Drive it by calling tick() — from a serving loop, a timer thread,
    or a virtual-clock simulator passing explicit `now` values. tick()
    is cheap when it is not an evaluation boundary (one clock read +
    one comparison), so calling it every sweep is fine."""

    def __init__(
        self,
        fleet: Any,
        config: Union[AutoscalerConfig, Dict[str, Any], None] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if isinstance(config, dict):
            config = AutoscalerConfig(**config)
        if config is None and hasattr(fleet, "router"):
            # the nested ServingRouterConfig.autoscaler block is the
            # default policy for a router-backed fleet
            config = fleet.router.cfg.autoscaler
        self.cfg = config or AutoscalerConfig()
        self.fleet = fleet
        self._clock = clock or time.monotonic
        self._last_eval: Optional[float] = None
        self._cooldown_until: Optional[float] = None
        self._up_votes = 0
        self._down_votes = 0
        self._prev: Optional[Dict[str, float]] = None
        self._retry_at: Optional[float] = None
        self._retry_attempt = 0
        self.counters: Dict[str, int] = {
            "evals": 0, "scale_ups": 0, "scale_downs": 0,
            "scale_up_denied": 0, "scale_down_denied": 0,
            "spinup_failures": 0, "spinup_retries": 0,
            "premium_bypass": 0, "cooldown_holds": 0,
        }
        # decision audit: [{"t", "action", "reason"}] — the diurnal
        # lane's scale-event trace comes straight from here
        self.log: List[Dict[str, Any]] = []

    # -- bookkeeping ------------------------------------------------------
    def _note(self, now: float, action: str, reason: str) -> None:
        self.log.append({"t": now, "action": action, "reason": reason})
        log_dist(f"autoscaler: {action} at t={now:.3f} ({reason})",
                 ranks=[0])

    def _cooling(self, now: float) -> bool:
        return self._cooldown_until is not None \
            and now < self._cooldown_until

    # -- the policy loop --------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One policy pass. Returns the action taken ('scale_up',
        'scale_down', 'spinup_failed') or None. Between evaluation
        boundaries only a pending spin-up retry can act; at a
        boundary the signal deltas since the previous evaluation are
        computed and voted."""
        if not self.cfg.enabled:
            return None
        now = self._clock() if now is None else now
        note_time = getattr(self.fleet, "note_time", None)
        if note_time is not None:
            note_time(now)
        # a scheduled spin-up retry fires as soon as its backoff
        # expires — the decision was already made, only the attempt
        # was burned
        if self._retry_at is not None and now >= self._retry_at:
            if int(self.fleet.live_replicas()) >= self.cfg.max_replicas:
                self._retry_at = None
                self._retry_attempt = 0
            else:
                return self._try_scale_up(now, "spin-up retry")
        if self._last_eval is not None and \
                now - self._last_eval < self.cfg.evaluation_interval_s:
            return None
        self._last_eval = now
        self.counters["evals"] += 1
        sig = {k: float(v) for k, v in self.fleet.signals().items()}
        prev, self._prev = self._prev, sig

        def delta(key: str) -> float:
            return sig.get(key, 0.0) - (prev.get(key, 0.0) if prev
                                        else 0.0)

        live = max(1, int(self.fleet.live_replicas()))
        qpr = sig.get("queue_depth", 0.0) / live
        pressure_hot = (sig.get("max_pressure_level", 0.0)
                        >= self.cfg.scale_up_pressure)
        degraded = (delta("shed_requests") > 0
                    or delta("deadline_rejections") > 0)
        premium_hit = (delta("premium_sheds") > 0
                       or delta("premium_rejections") > 0)
        want_up = (pressure_hot or degraded
                   or qpr > self.cfg.scale_up_queue_per_replica)
        calm = (sig.get("max_pressure_level", 0.0) <= GREEN
                and qpr < self.cfg.scale_down_queue_per_replica
                and not degraded)
        if want_up:
            self._up_votes += 1
            self._down_votes = 0
        elif calm:
            self._down_votes += 1
            self._up_votes = 0
        else:
            self._up_votes = 0
            self._down_votes = 0
        if premium_hit:
            self.counters["premium_bypass"] += 1
        if want_up and (premium_hit
                        or self._up_votes >= self.cfg.up_hysteresis):
            if self._retry_at is not None:
                # a burned spin-up already owns the next attempt: the
                # eval path must not race past its backoff
                return None
            if live >= self.cfg.max_replicas:
                self.counters["scale_up_denied"] += 1
                return None
            if self._cooling(now):
                self.counters["cooldown_holds"] += 1
                return None
            reason = ("premium SLO impact" if premium_hit else
                      "pressure" if pressure_hot else
                      "degradation" if degraded else
                      f"queue {qpr:.1f}/replica")
            return self._try_scale_up(now, reason)
        if calm and self._down_votes >= self.cfg.down_hysteresis:
            if live <= self.cfg.min_replicas:
                return None
            if self._cooling(now):
                self.counters["cooldown_holds"] += 1
                return None
            if self.fleet.scale_down(now):
                self.counters["scale_downs"] += 1
                self._down_votes = 0
                self._cooldown_until = \
                    now + self.cfg.scale_down_cooldown_s
                self._note(now, "scale_down",
                           f"queue {qpr:.1f}/replica, pressure green")
                return "scale_down"
            self.counters["scale_down_denied"] += 1
        return None

    def _try_scale_up(self, now: float, reason: str) -> str:
        self._retry_at = None
        try:
            self.fleet.scale_up(now)
        except Exception as e:
            # burned spin-up (replica died mid-scale-up): retry with
            # exponential backoff; after spinup_max_retries the loop
            # re-arms on the next scale-up signal instead
            self.counters["spinup_failures"] += 1
            if self._retry_attempt < self.cfg.spinup_max_retries:
                backoff = (self.cfg.spinup_retry_backoff_s
                           * (2 ** self._retry_attempt))
                self._retry_attempt += 1
                self.counters["spinup_retries"] += 1
                self._retry_at = now + backoff
                self._note(now, "spinup_failed",
                           f"{e!r}; retry in {backoff:.3f}s")
            else:
                self._retry_attempt = 0
                self._note(now, "spinup_abandoned", repr(e))
            return "spinup_failed"
        self._retry_attempt = 0
        self.counters["scale_ups"] += 1
        self._up_votes = 0
        self._cooldown_until = now + self.cfg.scale_up_cooldown_s
        self._note(now, "scale_up", reason)
        return "scale_up"

    def metrics(self) -> Dict[str, float]:
        m = {f"autoscaler_{k}": float(v)
             for k, v in self.counters.items()}
        m["autoscaler_up_votes"] = float(self._up_votes)
        m["autoscaler_down_votes"] = float(self._down_votes)
        m["autoscaler_retry_pending"] = float(self._retry_at is not None)
        return m


class RouterFleetAdapter:
    """Binds the policy loop to a real ServingRouter: signals come
    from the router/scheduler counters the overload work already
    maintains, scale_up spins a replica from `engine_factory` through
    add_replica (cache-warm boot included), scale_down drains the
    least-loaded routable replica of the scaled pool. With
    join=False, spun-up replicas are left WARMING and their ids
    collect in `pending_join` — the virtual-clock simulator charges
    each one its modeled spin-up time, then calls
    router.join_replica(); wall-clock callers keep the default
    join=True (add_replica's warmup IS the spin-up time)."""

    def __init__(self, router: ServingRouter,
                 engine_factory: Callable[[], Any],
                 role: str = "decode",
                 premium_classes: Sequence[str] = (),
                 join: bool = True):
        self.router = router
        self.engine_factory = engine_factory
        self.role = role
        self.premium = tuple(premium_classes)
        self.join = join
        self.pending_join: List[int] = []

    def live_replicas(self) -> int:
        r = self.router
        return sum(1 for i in range(len(r.schedulers))
                   if r._routable(i) or i in r.warming)

    def signals(self) -> Dict[str, float]:
        r = self.router
        n = len(r.schedulers)
        sig = {
            "queue_depth": float(sum(
                len(r.schedulers[i].waiting) for i in range(n)
                if r._serving(i))),
            "max_pressure_level": float(max(
                (r._pressure(i) for i in range(n) if r._serving(i)),
                default=0)),
            "shed_requests": float(r.counters["shed_requests"]),
            "deadline_rejections": float(sum(
                s.counters["deadline_rejections"]
                for s in r.schedulers)),
            "premium_sheds": float(sum(
                r.shed_by_class.get(c, 0) for c in self.premium)),
            "premium_rejections": float(sum(
                s.slo_rejections.get(c, 0) for s in r.schedulers
                for c in self.premium)),
        }
        return sig

    def scale_up(self, now: float) -> int:
        rid = self.router.add_replica(
            self.engine_factory(), role=self.role, join=self.join,
            now=now)
        if not self.join:
            self.pending_join.append(rid)
        return rid

    def scale_down(self, now: float) -> bool:
        r = self.router
        pool = (r.prefill_idx if self.role == "prefill"
                else r.decode_idx)
        cands = [i for i in pool if r._routable(i)]
        if len(cands) <= 1:
            return False
        # least-loaded first; ties drain the YOUNGEST replica (the
        # most recently added host is the one to give back)
        victim = min(cands, key=lambda i: (r._load(i), -i))
        try:
            r.drain_replica(victim, now=now)
        except ReplicaDrainError:
            return False
        return True

    def note_time(self, now: float) -> None:
        self.router.observe_time(now)
