"""Checkpoint save/load of sharded state.

TPU-native analog of the reference checkpoint layer
(ref: runtime/checkpoint_engine/checkpoint_engine.py CheckpointEngine
ABC, engine.py save_checkpoint:3064 / load_checkpoint:2700, and the
Nebula async engine). Backed by orbax: every process writes only its
addressable shards, restore re-shards to whatever mesh the new run uses
— which is why the reference's "universal checkpoint" reshape tooling
(deepspeed/checkpoint/ds_to_universal.py) is mostly free here: saved
arrays are logical/global, not per-rank shards.

Layout mirrors the reference's tag scheme, hardened with a commit
protocol (docs/fault_tolerance.md) so 'latest'/meta.json can never
point at an uncommitted or corrupt tree:

  <save_dir>/<tag>/INCOMPLETE     (written FIRST; removed at commit —
                                   its presence marks a crash window)
  <save_dir>/<tag>/state/...      (orbax tree)
  <save_dir>/<tag>/meta.json
  <save_dir>/<tag>/manifest.json  (per-file size + blake2b checksum)
  <save_dir>/<tag>/COMMITTED      (written LAST; holds the manifest
                                   digest — marker + matching checksums
                                   = a verified tag)
  <save_dir>/latest               (text file holding the newest tag;
                                   only ever updated AFTER COMMITTED)

A crash anywhere before COMMITTED leaves INCOMPLETE behind and 'latest'
still pointing at the previous tag; post-commit bitrot is caught by the
checksummed manifest. `load(tag=None)` falls back to the newest
VERIFIED tag when the one 'latest' names fails verification — the
elastic agent's resume (elasticity/agent.py) rides this, so a host that
died mid-save can never wedge the restart on a half-written tree
(the Varuna/Bamboo preemption-tolerance posture)."""

import contextlib
import hashlib
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax

from ..resilience.faults import active_plan, corrupt_file, fault_point
from ..utils.logging import log_dist

_INCOMPLETE = "INCOMPLETE"
_COMMITTED = "COMMITTED"
_MANIFEST = "manifest.json"
_MARKERS = (_INCOMPLETE, _COMMITTED, _MANIFEST)


class CheckpointCorruptError(RuntimeError):
    """The requested tag failed verification (uncommitted crash residue
    or checksum mismatch) and no verified fallback exists."""


def _file_digest(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest_digest(manifest: Dict) -> str:
    return hashlib.blake2b(
        json.dumps(manifest, sort_keys=True).encode(),
        digest_size=16).hexdigest()


def build_manifest(tag_dir: str) -> Dict:
    """Checksummed inventory of everything under the tag dir except the
    protocol markers themselves."""
    files: Dict[str, Dict] = {}
    for root, _, names in os.walk(tag_dir):
        for name in sorted(names):
            rel = os.path.relpath(os.path.join(root, name), tag_dir)
            if rel in _MARKERS:
                continue
            p = os.path.join(tag_dir, rel)
            files[rel] = {"size": os.path.getsize(p),
                          "blake2b": _file_digest(p)}
    return {"files": files}


def verify_tag(load_dir: str, tag: str) -> Tuple[bool, str]:
    """Is <load_dir>/<tag> a committed, uncorrupted checkpoint?
    Returns (ok, reason). Tags written before the commit protocol
    (no markers at all) are accepted as legacy."""
    tag_dir = os.path.join(os.path.abspath(load_dir), tag)
    if not os.path.isdir(tag_dir):
        return False, "tag dir missing"
    if os.path.exists(os.path.join(tag_dir, _INCOMPLETE)):
        return False, "uncommitted (crash window residue)"
    committed = os.path.join(tag_dir, _COMMITTED)
    manifest_p = os.path.join(tag_dir, _MANIFEST)
    if not os.path.exists(committed):
        if os.path.exists(manifest_p):
            return False, "manifest without commit marker"
        return True, "legacy (pre-commit-protocol tag)"
    try:
        with open(manifest_p) as f:
            manifest = json.load(f)
        with open(committed) as f:
            want = f.read().strip()
    except (OSError, ValueError) as e:
        return False, f"unreadable markers ({e})"
    if _manifest_digest(manifest) != want:
        return False, "manifest digest mismatch"
    for rel, rec in manifest.get("files", {}).items():
        p = os.path.join(tag_dir, rel)
        if not os.path.exists(p):
            return False, f"missing file {rel}"
        if os.path.getsize(p) != rec["size"]:
            return False, f"size mismatch in {rel}"
        if _file_digest(p) != rec["blake2b"]:
            return False, f"checksum mismatch in {rel}"
    return True, "verified"


class CheckpointEngine:
    def __init__(self, async_save: bool = False, save_retries: int = 3,
                 retry_backoff_s: float = 0.05):
        self.async_save = async_save
        self.save_retries = int(save_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self._ckptr = None
        self._pending = None
        if async_save:
            # the final save of a run must still commit + publish 'latest'
            # even if the script never saves again (ref: nebula engine's
            # implicit finalization on teardown)
            import atexit

            atexit.register(self.wait)

    def _checkpointer(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            if self.async_save:
                self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            else:
                self._ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        return self._ckptr

    def save(self, save_dir: str, tag: str, state: Any, meta: Dict) -> None:
        """Crash-consistent save: INCOMPLETE marker first, orbax tree
        (with bounded retry + exponential backoff on transient I/O
        errors), then the commit sequence — meta, checksummed manifest,
        COMMITTED marker, and ONLY then the 'latest' pointer. Async
        saves defer the whole commit sequence to wait(): until the
        background orbax write lands, the tag stays marked INCOMPLETE
        and 'latest' untouched, so a crash in that window is detected
        at load instead of resuming from a half-written tree."""
        save_dir = os.path.abspath(save_dir)
        tag_dir = os.path.join(save_dir, tag)
        path = os.path.join(tag_dir, "state")
        os.makedirs(tag_dir, exist_ok=True)
        self.wait()  # one in-flight async save at a time (ref: nebula engine semantics)
        if jax.process_index() == 0:
            with open(os.path.join(tag_dir, _INCOMPLETE), "w") as f:
                f.write("commit pending")
        ckptr = self._checkpointer()
        self._save_with_retry(ckptr, path, state, tag)
        if self.async_save:
            # the tag must only become loadable once the background
            # commit finishes: defer meta/manifest/COMMITTED/'latest'
            # to wait() (pre-hardening, meta.json landed HERE — a crash
            # before the orbax commit left a tag that looked complete)
            self._pending = (ckptr, save_dir, tag, meta)
        else:
            self._commit(save_dir, tag, meta)
        log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])

    def _save_with_retry(self, ckptr, path: str, state: Any,
                         tag: str) -> None:
        """Transient storage errors (an NFS blip, a GCS 5xx) heal with
        a bounded retry; anything still failing after the budget
        surfaces. Only OSError is retried — a shape/type error from
        orbax retries into the same wall."""
        for attempt in range(self.save_retries + 1):
            try:
                fault_point("checkpoint.save", tag=tag)
                ckptr.save(path, state, force=True)
                return
            except OSError as e:
                if attempt == self.save_retries:
                    log_dist(
                        f"checkpoint save of {tag} failed after "
                        f"{attempt + 1} attempts: {e!r}", ranks=[0])
                    raise
                delay = self.retry_backoff_s * (2 ** attempt)
                log_dist(
                    f"checkpoint save of {tag} hit transient I/O error "
                    f"({e!r}); retry {attempt + 1}/{self.save_retries} "
                    f"in {delay:.2f}s", ranks=[0])
                time.sleep(delay)

    def _commit(self, save_dir: str, tag: str, meta: Dict) -> None:
        """The commit sequence: anything before COMMITTED can crash and
        the tag stays invisible (INCOMPLETE present, 'latest' old)."""
        tag_dir = os.path.join(save_dir, tag)
        fault_point("checkpoint.commit", tag=tag)  # the crash window
        if jax.process_index() == 0:
            with open(os.path.join(tag_dir, "meta.json"), "w") as f:
                json.dump(meta, f, sort_keys=True)
            manifest = build_manifest(tag_dir)
            with open(os.path.join(tag_dir, _MANIFEST), "w") as f:
                json.dump(manifest, f, sort_keys=True)
            with open(os.path.join(tag_dir, _COMMITTED), "w") as f:
                f.write(_manifest_digest(manifest))
            try:
                os.remove(os.path.join(tag_dir, _INCOMPLETE))
            except OSError:
                pass
        self._write_latest(save_dir, tag)
        act = fault_point("checkpoint.corrupt", tag=tag, dir=tag_dir)
        if act is not None and act.kind == "corrupt":
            # injected post-commit bitrot: flip bytes in the largest
            # state file — verification must catch it at load
            plan = active_plan()
            state_dir = os.path.join(tag_dir, "state")
            victims = [os.path.join(r, n)
                       for r, _, ns in os.walk(state_dir) for n in ns]
            if victims:
                victim = max(victims, key=os.path.getsize)
                corrupt_file(victim, seed=plan.seed if plan else 0)

    @staticmethod
    def _write_latest(save_dir: str, tag: str) -> None:
        if jax.process_index() == 0:
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(tag)

    def wait(self) -> None:
        if self._pending is not None:
            ckptr, save_dir, tag, meta = self._pending
            # crash semantics: a failed commit is not retried on the
            # next wait() — the tag stays INCOMPLETE and load falls
            # back to the previous verified one
            self._pending = None
            ckptr.wait_until_finished()
            self._commit(save_dir, tag, meta)

    def resolve_tag(self, load_dir: str, tag: Optional[str]) -> str:
        load_dir = os.path.abspath(load_dir)
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                raise FileNotFoundError(f"no 'latest' file in {load_dir}")
            with open(latest) as f:
                tag = f.read().strip()
        return tag

    def resolve_verified_tag(self, load_dir: str,
                             tag: Optional[str]) -> str:
        """resolve_tag + verification. An EXPLICIT tag that fails
        verification raises (the caller asked for that exact version);
        a failing 'latest' falls back to the newest verified tag in
        the directory — the crash-consistent resume path."""
        load_dir = os.path.abspath(load_dir)
        explicit = tag is not None
        resolved = self.resolve_tag(load_dir, tag)
        if explicit and not os.path.isdir(os.path.join(load_dir, resolved)):
            # absent is not corrupt: keep the miss contract (tiered
            # fast-tier sweeps, caller typos) a FileNotFoundError
            raise FileNotFoundError(
                f"checkpoint tag {resolved} not found in {load_dir}")
        ok, why = verify_tag(load_dir, resolved)
        if ok:
            return resolved
        if explicit:
            raise CheckpointCorruptError(
                f"checkpoint {resolved} in {load_dir} failed "
                f"verification: {why}")
        log_dist(
            f"checkpoint {resolved} (from 'latest') failed verification "
            f"({why}); falling back to the newest verified tag",
            ranks=[0])
        # sorted() + (mtime, name) tie-break: same-second saves (or a
        # copied tree with flattened mtimes) must resolve to the SAME
        # fallback tag on every host and every run
        candidates = [
            t for t in sorted(os.listdir(load_dir))
            if t != resolved and os.path.isdir(os.path.join(load_dir, t))]
        candidates.sort(
            key=lambda t: (os.path.getmtime(os.path.join(load_dir, t)), t),
            reverse=True)
        for cand in candidates:
            ok, cand_why = verify_tag(load_dir, cand)
            if ok:
                log_dist(
                    f"resuming from verified fallback tag {cand} "
                    f"({cand_why})", ranks=[0])
                return cand
            log_dist(f"fallback candidate {cand} rejected: {cand_why}",
                     ranks=[0])
        raise CheckpointCorruptError(
            f"no verified checkpoint in {load_dir}: latest tag "
            f"{resolved} is bad ({why}) and no fallback verifies")

    def peek_meta(self, load_dir: str, tag: Optional[str]) -> Dict:
        """Read meta.json without touching tensor data (used to reconcile
        structure differences before restore)."""
        self.wait()  # an in-flight async save must commit before any read
        load_dir = os.path.abspath(load_dir)
        tag = self.resolve_verified_tag(load_dir, tag)
        meta_path = os.path.join(load_dir, tag, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}

    def load(
        self, load_dir: str, tag: Optional[str], template_state: Any
    ) -> Tuple[Any, Dict, str]:
        import orbax.checkpoint as ocp

        self.wait()
        load_dir = os.path.abspath(load_dir)
        tag = self.resolve_verified_tag(load_dir, tag)
        path = os.path.join(load_dir, tag, "state")
        restore_args = ocp.checkpoint_utils.construct_restore_args(template_state)
        state = self._checkpointer().restore(
            path, args=ocp.args.PyTreeRestore(
                item=template_state,
                restore_args=restore_args,
            ),
        )
        meta_path = os.path.join(load_dir, tag, "meta.json")
        meta: Dict = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
        return state, meta, tag


class TieredCheckpointEngine:
    """Nebula-class tiered checkpointing (ref: runtime/checkpoint_engine/
    nebula_checkpoint_engine.py NebulaCheckpointEngine + nebula/constants.py).

    The reference offloads checkpoint I/O to the torch_nebula service:
    every save lands in a fast node-local tier (tier-1) and the service
    persists versions to durable storage (tier-3) on a time interval,
    keeping a bounded number of versions in the fast tier. Here the same
    tiering is two orbax engines and a retention sweep:

      save(dir, tag)  → fast tier = `dir` (point it at node-local SSD),
                        async; every `persistent_time_interval` seconds a
                        version is ALSO written to
                        `persistent_storage_path` (sync, durable)
      retention       → only the newest `num_of_version_in_retention`
                        tags survive in the fast tier
      load            → fast tier first, durable fallback (the reference's
                        enable_nebula_load tier3>tier1 priority inverted:
                        tier-1 is authoritative-if-present since 'latest'
                        is committed only after the async save lands)

    API-compatible with CheckpointEngine so the training engine swaps it
    in when config `nebula.enabled` is true.
    """

    def __init__(
        self,
        persistent_storage_path: str,
        persistent_time_interval: float = 100.0,
        num_of_version_in_retention: int = 2,
        load_path: Optional[str] = None,
        enable_tier_load: bool = True,
        async_save: bool = True,
        _clock=None,
    ):
        import time

        if not persistent_storage_path:
            raise ValueError("nebula.enabled requires persistent_storage_path")
        self.persistent_storage_path = os.path.abspath(persistent_storage_path)
        self.load_path = os.path.abspath(load_path or persistent_storage_path)
        # enable_nebula_load=False in the reference disables tier-routed
        # loads (plain load from the caller's path only, no durable
        # fallback)
        self.enable_tier_load = bool(enable_tier_load)
        self.persistent_time_interval = float(persistent_time_interval)
        self.retention = int(num_of_version_in_retention)
        self.fast = CheckpointEngine(async_save=async_save)
        self.durable = CheckpointEngine(async_save=False)
        self._clock = _clock or time.monotonic
        self._last_persist: Optional[float] = None

    # --- save path ----------------------------------------------------
    def save(self, save_dir: str, tag: str, state: Any, meta: Dict) -> None:
        self._tier_cache = None  # new version: re-resolve on next load
        self.fast.save(save_dir, tag, state, meta)
        now = self._clock()
        if (
            self._last_persist is None
            or now - self._last_persist >= self.persistent_time_interval
        ):
            self.durable.save(self.persistent_storage_path, tag, state, meta)
            self._last_persist = now
        self._sweep_retention(save_dir, keep_tag=tag)

    def _sweep_retention(self, save_dir: str, keep_tag: str) -> None:
        """Drop fast-tier versions beyond the retention window. Never
        swept: the version just written (its async commit may be in
        flight) and the version 'latest' currently points to (until the
        new commit republishes 'latest', that one is the only recoverable
        fast-tier checkpoint). Runs on every process — fast tiers may be
        node-local; on a shared filesystem concurrent sweeps target the
        same already-doomed dirs, which ignore_errors tolerates."""
        import shutil

        save_dir = os.path.abspath(save_dir)
        if not os.path.isdir(save_dir):
            return
        protected = {keep_tag}
        latest_file = os.path.join(save_dir, "latest")
        try:
            if os.path.exists(latest_file):
                with open(latest_file) as f:
                    protected.add(f.read().strip())
        except OSError:
            pass
        try:
            # deterministic sweep order: (mtime, name) so equal
            # timestamps cannot leave the retention victim to the
            # filesystem's enumeration order
            tags = [
                t for t in sorted(os.listdir(save_dir))
                if os.path.isdir(os.path.join(save_dir, t))
            ]
            tags.sort(key=lambda t: (
                os.path.getmtime(os.path.join(save_dir, t)), t))
        except OSError:
            return  # racing with another process's sweep
        excess = max(0, len(tags) - self.retention)
        for t in tags[:excess]:
            if t in protected:
                continue
            shutil.rmtree(os.path.join(save_dir, t), ignore_errors=True)

    # --- load path (fast tier first, durable fallback) ----------------
    @contextlib.contextmanager
    def load_fanout(self, load_dir: str, tag: Optional[str]):
        """Pin ONE (tier, version) resolution for the duration of a
        load fan-out (peek_meta → resolve_tag → load): re-resolving per
        call could route them to different tiers/versions if a
        retention sweep or an async fast-tier commit lands in between.
        The pin lives ONLY inside this scope — a standalone peek_meta
        (e.g. polling latest-tag metadata) resolves fresh every time,
        so it can never serve a stale 'latest' (r3 advisor finding)."""
        key = (os.path.abspath(load_dir), tag)
        self._tier_cache = (key, self._resolve_tier(load_dir, tag))
        try:
            yield
        finally:
            self._tier_cache = None

    def _tier_for(
        self, load_dir: str, tag: Optional[str]
    ) -> Tuple[CheckpointEngine, str, str]:
        """Inside an open load_fanout: the pinned resolution. Outside:
        resolve fresh (uncached)."""
        key = (os.path.abspath(load_dir), tag)
        cached = getattr(self, "_tier_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        return self._resolve_tier(load_dir, tag)

    def _resolve_tier(
        self, load_dir: str, tag: Optional[str]
    ) -> Tuple[CheckpointEngine, str, str]:
        self.fast.wait()
        val: Optional[Tuple[CheckpointEngine, str, str]] = None
        try:
            # verification-aware: an unverified fast-tier 'latest'
            # (crash residue, bitrot) falls back first to an older
            # verified fast-tier tag, then to the durable tier
            resolved = self.fast.resolve_verified_tag(load_dir, tag)
            if os.path.isdir(os.path.join(os.path.abspath(load_dir), resolved, "state")):
                val = (self.fast, load_dir, resolved)
        except (FileNotFoundError, CheckpointCorruptError):
            pass
        if val is None:
            if not self.enable_tier_load:
                # no durable fallback: surface the fast-tier miss directly
                val = (self.fast, load_dir,
                       tag if tag is not None else "")
            else:
                val = (self.durable, self.load_path,
                       self.durable.resolve_verified_tag(self.load_path,
                                                         tag))
        return val

    def peek_meta(self, load_dir: str, tag: Optional[str]) -> Dict:
        engine, root, resolved = self._tier_for(load_dir, tag)
        return engine.peek_meta(root, resolved or tag)

    def load(self, load_dir: str, tag: Optional[str], template_state: Any):
        engine, root, resolved = self._tier_for(load_dir, tag)
        return engine.load(root, resolved or tag, template_state)

    def resolve_tag(self, load_dir: str, tag: Optional[str]) -> str:
        engine, root, resolved = self._tier_for(load_dir, tag)
        return resolved or engine.resolve_tag(root, tag)

    def wait(self) -> None:
        self.fast.wait()
        self.durable.wait()
