"""Evoformer attention + Megatron indexed-dataset tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer_attention import evoformer_attention
from deepspeed_tpu.runtime.indexed_dataset import (

    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow


def dense_oracle(q, k, v, biases):
    D = q.shape[-1]
    qT = jnp.moveaxis(q, -2, -3)
    kT = jnp.moveaxis(k, -2, -3)
    vT = jnp.moveaxis(v, -2, -3)
    logits = jnp.einsum("...qd,...kd->...qk", qT, kT) / np.sqrt(D)
    for b in biases:
        if b is not None:
            logits = logits + b
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.moveaxis(jnp.einsum("...qk,...kd->...qd", p, vT.astype(jnp.float32)), -3, -2)


class TestEvoformerAttention:
    def test_chunked_matches_dense_with_biases(self):
        """MSA-shaped input [B, N_seq, N_res, H, D] + mask + pair bias
        (the DS4Sci_EvoformerAttention contract)."""
        B, S, N, H, D = 2, 3, 64, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(0), 5)
        q = jax.random.normal(ks[0], (B, S, N, H, D))
        k = jax.random.normal(ks[1], (B, S, N, H, D))
        v = jax.random.normal(ks[2], (B, S, N, H, D))
        mask_bias = jnp.where(
            jax.random.bernoulli(ks[3], 0.9, (B, S, 1, 1, N)), 0.0, -1e9)
        pair_bias = jax.random.normal(ks[4], (B, 1, H, N, N)) * 0.5

        want = dense_oracle(q, k, v, [mask_bias, pair_bias])
        got = evoformer_attention(q, k, v, [mask_bias, pair_bias], chunk_size=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_small_n_dense_path(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 8))
        got = evoformer_attention(q, q, q, [], chunk_size=512)
        want = dense_oracle(q, q, q, [])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_flow(self):
        q = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 2, 8))
        g = jax.grad(lambda x: evoformer_attention(x, x, x, [], chunk_size=8).sum())(q)
        assert np.isfinite(np.asarray(g)).all()


class TestIndexedDataset:
    def test_build_read_roundtrip(self, tmp_path):
        prefix = str(tmp_path / "corpus")
        b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
        docs = [np.arange(10), np.arange(5) + 100, np.arange(17) * 3]
        for d in docs:
            b.add_item(d)
            b.end_document()
        b.finalize()

        ds = MMapIndexedDataset(prefix)
        assert len(ds) == 3
        for i, d in enumerate(docs):
            np.testing.assert_array_equal(ds[i], d.astype(np.int32))
        np.testing.assert_array_equal(ds.sizes, [10, 5, 17])
        np.testing.assert_array_equal(ds.doc_idx, [0, 1, 2, 3])
        # partial reads (the sampler's window access pattern)
        np.testing.assert_array_equal(ds.get(2, offset=4, length=3),
                                      (np.arange(17) * 3)[4:7].astype(np.int32))

    def test_uint16_tokens(self, tmp_path):
        """GPT-2-vocab datasets use uint16 (the Megatron convention)."""
        prefix = str(tmp_path / "u16")
        b = MMapIndexedDatasetBuilder(prefix, dtype=np.uint16)
        b.add_item(np.array([1, 2, 50000], np.uint16))
        b.end_document()
        b.finalize()
        ds = MMapIndexedDataset(prefix)
        assert ds.dtype == np.uint16
        np.testing.assert_array_equal(ds[0], [1, 2, 50000])

    def test_bad_magic_raises(self, tmp_path):
        p = tmp_path / "bad.idx"
        p.write_bytes(b"NOTMAGIC0" + b"\x00" * 64)
        (tmp_path / "bad.bin").write_bytes(b"")
        with pytest.raises(ValueError, match="magic"):
            MMapIndexedDataset(str(tmp_path / "bad"))


class TestEvoformerPallasKernel:
    """Fused Pallas forward for the DS4Sci contract (ref: csrc/
    deepspeed4science/evoformer_attn CUTLASS kernels) vs the chunked
    oracle; gradients route through the exact chunked vjp."""

    def _inputs(self, rng, B=1, S=2, N=128, H=2, D=32):
        q = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        mask = jnp.asarray(
            np.where(rng.random((B, S, 1, 1, N)) < 0.2, -1e9, 0.0),
            jnp.float32)
        pair = jnp.asarray(rng.normal(size=(B, 1, H, N, N)), jnp.float32)
        return q, k, v, mask, pair

    def test_forward_matches_chunked(self, rng):
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, mask, pair = self._inputs(rng)
        with jax.default_matmul_precision("highest"):
            got = ds4sci_evoformer_attention(q, k, v, [mask, pair])
            want = evoformer_attention(q, k, v, [mask, pair],
                                       chunk_size=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_forward_no_bias_and_single_bias(self, rng):
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, mask, _ = self._inputs(rng)
        with jax.default_matmul_precision("highest"):
            for biases in ([], [mask]):
                got = ds4sci_evoformer_attention(q, k, v, biases)
                want = evoformer_attention(q, k, v, biases, chunk_size=64)
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=2e-4,
                    atol=2e-4)

    def test_gradients_match_chunked(self, rng):
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, mask, pair = self._inputs(rng, N=128)

        def loss_k(q, pair):
            return ds4sci_evoformer_attention(
                q, k, v, [mask, pair]).astype(jnp.float32).sum()

        def loss_c(q, pair):
            return evoformer_attention(
                q, k, v, [mask, pair], chunk_size=64
            ).astype(jnp.float32).sum()

        with jax.default_matmul_precision("highest"):
            gq_k, gp_k = jax.grad(loss_k, argnums=(0, 1))(q, pair)
            gq_c, gp_c = jax.grad(loss_c, argnums=(0, 1))(q, pair)
        np.testing.assert_allclose(np.asarray(gq_k), np.asarray(gq_c),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(gp_k), np.asarray(gp_c),
                                   rtol=2e-4, atol=2e-4)

    def test_off_contract_falls_back(self, rng):
        """N not tile-aligned: silently uses the chunked path."""
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, mask, pair = self._inputs(rng, N=48)
        got = ds4sci_evoformer_attention(q, k, v, [mask, pair],
                                         chunk_size=48)
        want = evoformer_attention(q, k, v, [mask, pair], chunk_size=48)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestEvoformerPallasBackward:
    """Round-5 handwritten backward kernels (ref: csrc/deepspeed4science/
    evoformer_attn/attention_back.cu) vs jax.grad of the chunked oracle
    — dq/dk/dv plus BOTH bias grads (dbias1 via the dkv row-sums,
    dbias2 via the N_seq-innermost accumulation kernel)."""

    def _inputs(self, rng, B=1, S=2, N=128, H=2, D=32):
        q = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, N, H, D)), jnp.float32)
        mask = jnp.asarray(
            np.where(rng.random((B, S, 1, 1, N)) < 0.2, -1e9, 0.0),
            jnp.float32)
        pair = jnp.asarray(rng.normal(size=(B, 1, H, N, N)), jnp.float32)
        return q, k, v, mask, pair

    @pytest.mark.parametrize("which", ["both", "pair_only", "mask_only",
                                       "none"])
    def test_grads_match_chunked_oracle(self, rng, which):
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, mask, pair = self._inputs(rng)
        biases = {"both": [mask, pair], "pair_only": [None, pair],
                  "mask_only": [mask], "none": []}[which]
        do = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

        def loss_kernel(*args):
            n = len([b for b in biases if b is not None])
            qq, kk, vv, *bs = args
            bl = list(biases)
            bi = iter(bs)
            bl = [next(bi) if b is not None else None for b in bl]
            return jnp.sum(ds4sci_evoformer_attention(qq, kk, vv, bl) * do)

        def loss_oracle(*args):
            qq, kk, vv, *bs = args
            bl = list(biases)
            bi = iter(bs)
            bl = [next(bi) if b is not None else None for b in bl]
            return jnp.sum(
                evoformer_attention(qq, kk, vv, bl, chunk_size=64) * do)

        args = [q, k, v] + [b for b in biases if b is not None]
        argnums = tuple(range(len(args)))
        with jax.default_matmul_precision("highest"):
            gk = jax.grad(loss_kernel, argnums=argnums)(*args)
            go = jax.grad(loss_oracle, argnums=argnums)(*args)
        for a, b in zip(gk, go):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-3, atol=3e-3)

    def test_multi_seq_pair_grad_accumulates(self, rng):
        """dbias2 must SUM over N_seq (the resident-tile accumulation
        the db2 kernel's grid ordering exists for): S=4 forces multiple
        s-steps per output tile."""
        from deepspeed_tpu.ops.evoformer_attention import (
            ds4sci_evoformer_attention, evoformer_attention)

        q, k, v, _, pair = self._inputs(rng, S=4)
        do = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
        with jax.default_matmul_precision("highest"):
            gk = jax.grad(lambda p: jnp.sum(
                ds4sci_evoformer_attention(q, k, v, [None, p]) * do))(pair)
            go = jax.grad(lambda p: jnp.sum(
                evoformer_attention(q, k, v, [None, p],
                                    chunk_size=64) * do))(pair)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(go),
                                   rtol=3e-3, atol=3e-3)
