"""Compression training: QAT + pruning as functional param transforms.

TPU-native redesign of the reference compression library
(ref: compression/compress.py init_compression:100 — walks the module
tree substituting LinearLayer_Compress etc. (basic_layer.py:121-611)
which quantize/prune inside forward; scheduler.py drives schedule
offsets from engine step hooks; redundancy_clean:148 bakes the masks in
for export). With functional params there is nothing to substitute:
compression is ONE pure function `apply(params, step)` composed into the
loss — XLA fuses the fake-quant/mask math into the weight loads.

Supported (reference config schema, same key names):
  weight_quantization.different_groups.<g>.params.target_bits + .modules
      — QAT fake-quant with straight-through gradients
        (ref: basic_layer.py weight quantization + fake_quantizer.cu)
  sparse_pruning {method: l1|topk, dense_ratio, schedule_offset}
      — unstructured magnitude pruning (ref: basic_layer.py SparsePruning)
  row_pruning {dense_ratio, schedule_offset, modules}
      — structured output-row pruning
  head_pruning {dense_ratio, schedule_offset, modules}
      — attention-head pruning on [H, ...] leaves
Activation quantization lives on the model
(TransformerConfig.activation_quant_bits — applied to the normed
activations feeding every projection, training and serving alike); the
config block here raises with that pointer.

`modules` patterns are fnmatch globs over the param path
("layers/w_in") — the analog of the reference's module-name matching.
"""

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


def _match(path: str, patterns) -> bool:
    return any(fnmatch.fnmatch(path, p) or p == "*" for p in patterns)


def _fake_quant(w, bits):
    """Symmetric per-tensor fake quantization with straight-through
    gradients (ref: fake_quantizer.cu + QAT path of basic_layer.py).
    `bits` may be a traced scalar (bit-decay schedules)."""
    qmax = jnp.exp2(jnp.asarray(bits, jnp.float32) - 1.0) - 1.0
    absmax = jnp.max(jnp.abs(w)).astype(jnp.float32)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = (jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax)
         * scale).astype(w.dtype)
    return w + jax.lax.stop_gradient(q - w)  # STE


def _decayed_bits(step, start_bits: int, target_bits: int, period: int):
    """Progressive bit narrowing (ref: runtime/quantize.py
    compute_quantization:129 — one bit is removed each time the step
    counter crosses q_period, and the period DOUBLES per reduction, 'to
    go slowly toward the target'). Reductions land at steps p0, 2*p0,
    4*p0, ...; closed form so it traces branchlessly."""
    if period <= 0 or start_bits <= target_bits:
        return jnp.float32(target_bits)
    s = jnp.maximum(jnp.asarray(step, jnp.float32), 0.0)
    n_red = jnp.where(
        s < period, 0.0,
        jnp.floor(jnp.log2(jnp.maximum(s / period, 1.0))) + 1.0,
    )
    return jnp.maximum(jnp.float32(start_bits) - n_red,
                       jnp.float32(target_bits))


def _sparse_mask(w, dense_ratio: float):
    """Keep the top dense_ratio fraction by magnitude (l1/topk methods
    coincide for unstructured magnitude pruning)."""
    thresh = jnp.quantile(jnp.abs(w).astype(jnp.float32), 1.0 - dense_ratio)
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def _rank_keep(norms, k: int):
    """Keep mask dropping exactly the k smallest (rank-based, so ties /
    all-equal norms — e.g. zero-init weights — prune exactly k, never
    the whole tensor)."""
    ranks = jnp.argsort(jnp.argsort(norms, axis=-1), axis=-1)
    return ranks >= k


def _row_mask(w, dense_ratio: float):
    """Zero the lowest-norm output features (last dim), decided PER
    LEADING INDEX — a scanned [L, E, F] stack prunes each layer
    independently, matching the reference's per-Linear pruning
    (ref: basic_layer.py row pruning)."""
    if w.ndim < 2:
        return jnp.ones_like(w)
    norms = jnp.linalg.norm(w.astype(jnp.float32), axis=-2)  # [..., C]
    C = norms.shape[-1]
    k = max(int(C * (1.0 - dense_ratio)), 0)
    if k == 0:
        return jnp.ones_like(w)
    keep = _rank_keep(norms, k).astype(w.dtype)  # [..., C]
    return jnp.broadcast_to(keep[..., None, :], w.shape)


def _head_mask(w, dense_ratio: float):
    """Zero whole attention heads on [..., H, D, E] attention-output
    leaves; head dim = -3 (ref: basic_layer.py head pruning on the attn
    output projection). Callers MUST name the target leaves explicitly
    (init_compression enforces it) — the layout assumption is not
    checkable from shape alone."""
    if w.ndim < 3:
        return jnp.ones_like(w)
    norms = jnp.sqrt(jnp.sum(
        jnp.square(w.astype(jnp.float32)), axis=(-2, -1)))  # [..., H]
    H = norms.shape[-1]
    k = max(int(H * (1.0 - dense_ratio)), 0)
    if k == 0:
        return jnp.ones_like(w)
    keep = _rank_keep(norms, k).astype(w.dtype)
    return keep[..., None, None]


def init_compression(config: Dict[str, Any]):
    """Validate + normalize a 'compression_training' block into a list of
    (kind, patterns, params) rules (ref: compress.py init_compression:100
    — there it rewires modules; here it compiles a rule table)."""
    rules: List[Tuple[str, Tuple[str, ...], Dict[str, Any]]] = []
    wq = config.get("weight_quantization") or {}
    # reference default: every technique is DISABLED unless
    # shared_parameters.enabled is true (ref: compression/constants.py
    # WEIGHT_QUANTIZE_ENABLED_DEFAULT = False etc.)
    if not wq.get("shared_parameters", {}).get("enabled", False):
        wq = {}
    for gname, group in (wq.get("different_groups") or {}).items():
        params = group.get("params", {})
        bits = int(params.get("target_bits", params.get("bits", 8)))
        # start_bits + quantization_period: the reference's progressive
        # bit-narrowing (runtime/quantize.py compute_quantization) —
        # bits walk from start_bits down to target_bits, one bit per
        # period crossing with the period doubling each time
        start_bits = int(params.get("start_bits", bits))
        period = int(params.get("quantization_period", 0))
        offset = int(wq.get("shared_parameters", {}).get("schedule_offset", 0))
        mods = tuple(group.get("modules", ["*"]))
        rules.append(("qat", mods, {
            "bits": bits, "start_bits": start_bits, "period": period,
            "offset": offset,
        }))
    if config.get("activation_quantization", {}).get("shared_parameters", {}) \
            .get("enabled") or (config.get("activation_quantization") or {}) \
            .get("different_groups"):
        raise NotImplementedError(
            "activation_quantization is configured on the model in "
            "deepspeed_tpu (models are functional — there is no module to "
            "hook): set TransformerConfig(activation_quant_bits=8); the "
            "same fake-quant then applies in training AND serving"
        )
    for kind, key in (("sparse", "sparse_pruning"), ("row", "row_pruning"),
                      ("head", "head_pruning")):
        block = config.get(key) or {}
        shared = block.get("shared_parameters", block)
        if not shared.get("enabled", False):
            continue  # reference default: disabled unless explicitly enabled
        groups = block.get("different_groups") or {}
        entries = (
            [(g.get("params", {}), tuple(g.get("modules", ["*"])))
             for g in groups.values()]
            if groups else [(shared, ("*",))]
        )
        for params, mods in entries:
            if kind == "head" and any(p == "*" for p in mods):
                raise ValueError(
                    "head_pruning needs explicit 'modules' naming attention "
                    "output leaves with [..., heads, head_dim, embed] layout "
                    "(e.g. ['layers/wo']) — a '*' wildcard would misread "
                    "MLP/QKV layouts as heads"
                )
            ratio = float(params.get("dense_ratio", params.get("ratio", 0.5)))
            offset = int(shared.get("schedule_offset", params.get("schedule_offset", 0)))
            rules.append((kind, mods, {"dense_ratio": ratio, "offset": offset}))
    return rules


_MASKS = {"sparse": _sparse_mask, "row": _row_mask, "head": _head_mask}


def build_compression(config: Dict[str, Any]) -> Optional[Callable]:
    """-> apply(params, step) composed into the loss by the engine, or
    None when every sub-block is disabled (disabled blocks no-op,
    matching the config-compat convention elsewhere).

    Schedule offsets gate each rule with a branchless where on the step
    (the scheduler.py role, collapsed into the compiled program)."""
    rules = init_compression(config)
    if not rules:
        return None

    def apply(params, step):
        def leaf(path, w):
            if w.ndim == 0:
                return w
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            for kind, mods, prm in rules:
                if not _match(name, mods):
                    continue
                if kind == "qat":
                    # decay counts from schedule_offset (the reference's
                    # q_period counter starts when quantization starts),
                    # so the warm high-bit phases survive a late offset
                    out = _fake_quant(w, _decayed_bits(
                        step - prm["offset"], prm["start_bits"],
                        prm["bits"], prm["period"]))
                else:
                    out = w * jax.lax.stop_gradient(
                        _MASKS[kind](w, prm["dense_ratio"]))
                w = jnp.where(step >= prm["offset"], out, w)
            return w

        return jax.tree_util.tree_map_with_path(leaf, params)

    return apply


def student_initialization(teacher_params, config: Dict[str, Any],
                           teacher_pipeline_stages: int = 1,
                           teacher_virtual_stages: int = 1):
    """Initialize a shallower student from chosen teacher layers
    (ref: compression/compress.py:192 student_initialization — there it
    copies module-by-module via recursive_getattr over the
    layer_reduction config; here layers are ONE stacked [L, ...] array,
    so the whole re-init is a gather on the layer dim plus carrying the
    non-layer leaves over, the other_module_name copy collapsed).

    config: the compression_training block; uses
    layer_reduction.{enabled, teacher_layer} (module_name_prefix /
    other_module_name are module-tree artifacts with no functional
    analog — every non-layer leaf is copied)."""
    lr = config.get("layer_reduction") or {}
    if not lr.get("enabled", False):
        return teacher_params
    idx = jnp.asarray(list(lr["teacher_layer"]), jnp.int32)
    keep = lr.get("keep_number_layers")
    if keep is not None and int(keep) != int(idx.shape[0]):
        raise ValueError(
            f"keep_number_layers {keep} != len(teacher_layer) {idx.shape[0]}"
        )
    layers = teacher_params["layers"]
    if teacher_pipeline_stages > 1:
        # a pipelined teacher stores layers stage-partitioned — flatten
        # so teacher_layer indexes LAYERS, not stage blocks
        from ..runtime.pipe import unpartition_layers

        layers = unpartition_layers(layers, virtual=teacher_virtual_stages)
    student = {k: v for k, v in teacher_params.items() if k != "layers"}
    student["layers"] = jax.tree.map(lambda w: w[idx], layers)
    return student


def make_distillation_loss_fn(
    student_cfg, teacher_cfg, teacher_params,
    alpha: float = 0.5, temperature: float = 2.0, loss_chunks: int = 8,
):
    """KD training loss: alpha * CE(student, labels) +
    (1-alpha) * T^2 * KL(teacher_soft || student_soft).

    The reference's compression pipeline initializes the student
    (compress.py:192) and leaves the KD objective to the example
    scripts; with a functional engine the objective IS the hook, so it
    ships in-tree. Teacher runs under stop_gradient in the same compiled
    step (one program; XLA overlaps the two forwards). Returns a loss_fn
    for ds.initialize."""
    from ..models import transformer as T

    frozen_teacher = jax.tree.map(jax.lax.stop_gradient, teacher_params)

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        mask = T._shift_mask(batch if isinstance(batch, dict) else {}, tgt)
        # ONE student forward feeds both the CE and KD terms; KD runs
        # chunked over the sequence like _chunked_ce, so no [B,S,V]
        # fp32 logits tensor is ever resident (student OR teacher)
        x_s = T.forward_hidden(params, inp, student_cfg, rng)
        x_t = jax.lax.stop_gradient(
            T.forward_hidden(frozen_teacher, inp, teacher_cfg, None))
        head_s = T._lm_head(params, student_cfg)
        head_t = T._lm_head(frozen_teacher, teacher_cfg)
        n = T._ce_chunk_count(inp.shape[1], loss_chunks)
        ce_sum, cnt = T._chunked_ce(x_s, head_s, tgt, mask, n)
        ce = ce_sum / jnp.maximum(cnt, 1.0)

        B, S, _ = x_s.shape
        C = S // n

        @jax.checkpoint
        def kd_chunk(xs_c, xt_c, m_c):
            s_log = jnp.einsum("bce,ev->bcv", xs_c,
                               head_s.astype(xs_c.dtype)).astype(jnp.float32)
            t_log = jnp.einsum("bce,ev->bcv", xt_c,
                               head_t.astype(xt_c.dtype)).astype(jnp.float32)
            t_soft = jax.nn.log_softmax(t_log / temperature, axis=-1)
            s_soft = jax.nn.log_softmax(s_log / temperature, axis=-1)
            kl = jnp.sum(jnp.exp(t_soft) * (t_soft - s_soft), axis=-1)
            return jnp.sum(kl * m_c)

        def body(carry, xs):
            return carry + kd_chunk(*xs), None

        chunks = (
            x_s.reshape(B, n, C, -1).swapaxes(0, 1),
            x_t.reshape(B, n, C, -1).swapaxes(0, 1),
            mask.reshape(B, n, C).swapaxes(0, 1),
        )
        kl_sum, _ = jax.lax.scan(body, jnp.float32(0.0), chunks)
        kl = kl_sum / jnp.maximum(cnt, 1.0)
        return alpha * ce + (1.0 - alpha) * (temperature ** 2) * kl

    return loss_fn


def clean_compressed_params(params, config: Dict[str, Any], step: Optional[int] = None):
    """Bake the compression into the weights for export
    (ref: compress.py redundancy_clean:148)."""
    import numpy as np

    apply = build_compression(config)
    if apply is None:
        return jax.tree.map(lambda x: np.asarray(x), params)
    big = jnp.int32(2**30 if step is None else step)
    return jax.tree.map(lambda x: np.asarray(x), apply(params, big))
