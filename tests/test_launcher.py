"""Launcher + env-report tests (ref: tests/unit/launcher)."""

import json
import os
import subprocess
import sys

import numpy as np

from deepspeed_tpu.launcher.runner import launch_local


def test_env_report_runs():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.env_report"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr
    assert "op compatibility" in out.stdout
    assert "async_io" in out.stdout
    # the device probe runs under a watchdog: a healthy backend reports
    # its devices, a wedged accelerator runtime/tunnel reports the
    # timeout instead of hanging the tool (and this test with it)
    assert ("device count" in out.stdout
            or "TIMED OUT" in out.stdout), out.stdout


def test_launch_local_spawns_world(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "import deepspeed_tpu as ds\n"
        "ds.comm.init_distributed()\n"
        "assert ds.comm.get_process_count() == 2\n"
        "assert ds.comm.get_world_size() == 4\n"
        "print(f'rank {os.environ[\"RANK\"]} sees world '\n"
        "      f'{ds.comm.get_world_size()}')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rc = launch_local(
        [sys.executable, str(script)], num_procs=2, devices_per_proc=2,
        env_extra={"PYTHONPATH": repo},
    )
    assert rc == 0


def test_launch_local_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    rc = launch_local([sys.executable, str(script)], num_procs=2)
    assert rc == 3


class TestPodLauncher:
    """Pod fan-out CLI (ref: launcher/runner.py:388 + multinode_runner
    PDSHRunner) — command assembly + per-worker log aggregation, driven
    against a stub gcloud (the real one needs a pod)."""

    def test_command_assembly(self):
        from deepspeed_tpu.launcher.pod import build_worker_command

        cmd = build_worker_command(
            "slice-a", "us-east5-a", ["python", "train.py", "--lr", "1e-4"],
            worker="all", project="proj",
            env={"JAX_X": "1", "A": "b c"}, chdir="/work")
        assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                           "slice-a"]
        assert "--project=proj" in cmd and "--zone=us-east5-a" in cmd
        assert "--worker=all" in cmd
        inner = cmd[-1]
        assert inner.startswith("export A='b c'; export JAX_X=1; ")
        assert "cd /work && python train.py --lr 1e-4" in inner

    def _stub_gcloud(self, tmp_path):
        stub = tmp_path / "gcloud"
        stub.write_text(
            "#!/bin/sh\n"
            "# echo the worker flag + run the --command locally\n"
            'for a in "$@"; do case "$a" in --worker=*) W=${a#--worker=};;'
            " esac; done\n"
            'CMD=""\n'
            'prev=""\n'
            'for a in "$@"; do if [ "$prev" = "--command" ]; then CMD="$a";'
            ' fi; prev="$a"; done\n'
            'echo "hello from worker $W"\n'
            'sh -c "$CMD"\n')
        stub.chmod(0o755)
        return str(stub)

    def test_per_worker_logs_and_exit(self, tmp_path, capsys):
        from deepspeed_tpu.launcher.pod import run_on_pod

        rc = run_on_pod(
            "s", "z", ["echo", "ran"], workers="0,1",
            log_dir=str(tmp_path / "logs"), gcloud=self._stub_gcloud(tmp_path))
        assert rc == 0
        out = capsys.readouterr().out
        assert "[worker 0] hello from worker 0" in out
        assert "[worker 1] hello from worker 1" in out
        for w in ("0", "1"):
            log = (tmp_path / "logs" / f"worker_{w}.log").read_text()
            assert f"hello from worker {w}" in log and "ran" in log

    def test_failure_propagates(self, tmp_path):
        from deepspeed_tpu.launcher.pod import run_on_pod

        rc = run_on_pod("s", "z", ["sh", "-c", "exit 3"], workers="all",
                        gcloud=self._stub_gcloud(tmp_path))
        assert rc == 3

    def test_cli_env_report_spelling(self, tmp_path, capsys):
        from deepspeed_tpu.launcher.pod import main

        rc = main(["--tpu", "s", "--zone", "z",
                   "--gcloud", self._stub_gcloud(tmp_path), "--",
                   "echo", "ok"])
        assert rc == 0
        assert "ok" in capsys.readouterr().out


class TestCommBench:
    """ds_bench analog (ref: bin/ds_bench → benchmarks/communication/):
    the sweep must run every op on the virtual mesh and report busbw
    with the reference's ring-correction convention."""

    def test_sweep_all_ops(self):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.comm.bench import OPS, _busbw_factor, sweep

        records = sweep(list(OPS), [64 * 1024], trials=2,
                        dtype=jnp.float32)
        assert {r["op"] for r in records} == set(OPS)
        n = len(jax.devices())
        for r in records:
            assert r["devices"] == n
            assert r["bytes_per_device"] > 0
            assert r["algbw_GBps"] > 0
            np.testing.assert_allclose(
                r["busbw_GBps"],
                r["algbw_GBps"] * _busbw_factor(r["op"], n))

    def test_busbw_convention(self):
        from deepspeed_tpu.comm.bench import _busbw_factor

        # ref benchmarks/communication/utils.py busbw notes
        assert _busbw_factor("all_reduce", 8) == 2 * 7 / 8
        assert _busbw_factor("all_gather", 8) == 7 / 8
        assert _busbw_factor("ppermute", 8) == 1.0

    def test_cli_json_line(self, capsys):
        from deepspeed_tpu.comm.bench import main

        rc = main(["--ops", "all_gather", "--sizes-mb", "0.0625",
                   "--trials", "1", "--dtype", "float32", "--json"])
        assert rc == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(line)["ds_bench"]
        assert rec[0]["op"] == "all_gather"
