"""Multi-process launcher.

TPU-native analog of the reference launcher stack
(ref: launcher/runner.py main:388 → multinode_runner.py PDSH/MPI/Slurm →
launcher/launch.py main:132 per-node spawner with per-rank env +
terminate_process_tree:118). On TPU pods the heavy half disappears: the
TPU runtime already starts one process per host with coordinator env set
— `deepspeed_tpu.comm.init_distributed()` picks it up, so "launching" a
pod job is just running the script on every host (gcloud ... --worker=all).

What remains useful — and is implemented here — is the LOCAL spawner:
run N controller processes on one machine (each with a slice of fake or
real devices) for multi-process testing and single-host multi-chip
setups. It assigns a free coordinator port, sets MASTER_ADDR/PORT +
RANK/WORLD_SIZE per rank (the env contract init_distributed consumes),
prefixes each rank's output, and kills the whole tree if any rank dies
(the launch.py sigkill semantics).

Usage:
  python -m deepspeed_tpu.launcher --num_procs 2 \
      [--devices_per_proc 4] your_script.py --your-args
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from typing import List


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int) -> None:
    for line in proc.stdout:  # type: ignore[union-attr]
        sys.stdout.write(f"[rank{rank}] {line}")
        sys.stdout.flush()


def launch_local(
    cmd: List[str],
    num_procs: int,
    devices_per_proc: int = 0,
    env_extra=None,
    timeout_s: float = 0,
) -> int:
    """Spawn `num_procs` copies of cmd with the distributed env contract.
    Returns the first nonzero exit code (0 if all succeeded; 124 on
    timeout — the test-harness hang-kill, ref: tests/unit/common.py:165)."""
    port = str(_free_port())
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    for rank in range(num_procs):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["MASTER_ADDR"] = "127.0.0.1"
        env["MASTER_PORT"] = port
        env["WORLD_SIZE"] = str(num_procs)
        env["RANK"] = str(rank)
        env["LOCAL_RANK"] = str(rank)  # reference env contract (launch.py)
        if devices_per_proc:
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={devices_per_proc}"
            )
        p = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, rank), daemon=True)
        t.start()
        threads.append(t)

    def _terminate_all(*_):
        # ref: launch.py terminate_process_tree:118
        for p in procs:
            if p.poll() is None:
                p.terminate()

    old = signal.signal(signal.SIGINT, _terminate_all)
    try:
        import time

        rc = 0
        deadline = time.monotonic() + timeout_s if timeout_s else None
        # poll so one dead rank kills the whole tree instead of leaving
        # the survivors blocked in rendezvous (ref: launch.py main loop +
        # terminate_process_tree:118)
        while True:
            if deadline is not None and time.monotonic() > deadline:
                print("[launcher] timeout; terminating all ranks",
                      file=sys.stderr)
                rc = 124
                _terminate_all()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                break
            codes = [p.poll() for p in procs]
            failed = [(i, c) for i, c in enumerate(codes) if c not in (None, 0)]
            if failed:
                rank, rc = failed[0]
                print(f"[launcher] rank {rank} exited with {rc}; "
                      "terminating remaining ranks", file=sys.stderr)
                _terminate_all()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                break
            if all(c is not None for c in codes):
                break
            time.sleep(0.2)
        for t in threads:
            t.join(timeout=5)
        return rc
    finally:
        signal.signal(signal.SIGINT, old)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("--num_procs", type=int, default=1,
                        help="controller processes to spawn on this host")
    parser.add_argument("--devices_per_proc", type=int, default=0,
                        help="virtual CPU devices per process (testing)")
    parser.add_argument("--module", "-m", action="store_true",
                        help="run script as a python module")
    parser.add_argument("script", help="training script (SPMD: runs on every rank)")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    cmd = [sys.executable]
    if args.module:
        cmd.append("-m")
    cmd.append(args.script)
    cmd.extend(args.script_args)
    return launch_local(cmd, args.num_procs, args.devices_per_proc)


if __name__ == "__main__":
    sys.exit(main())
