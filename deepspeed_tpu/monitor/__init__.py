from .monitor import (
    CsvMonitor,
    MonitorMaster,
    TensorBoardMonitor,
    inference_cache_events,
    serving_events,
)
