"""Mixed precision: dynamic loss scaling for fp16, master-weight policy.

TPU-native analog of the reference precision machinery
(ref: runtime/fp16/loss_scaler.py DynamicLossScaler, runtime/
fp16/fused_optimizer.py FP16_Optimizer overflow handling,
runtime/bf16_optimizer.py BF16_Optimizer master-weight linkage).
On TPU the recommended low-precision dtype is bf16 (no scaler needed);
fp16 + dynamic scaling is provided for numerics parity. The scaler is a
pure-array state machine so it lives inside the compiled train step —
overflow check, skip-update, and scale adjustment are all traced
(no host round-trip per step, unlike the reference's `.item()` checks).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config.config import FP16Config

# numpy dtype name -> XLA/HLO shorthand, the vocabulary the numerics
# sanitizer (analysis/numerics.py) compares compiled programs against
_HLO_DTYPE_NAMES = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16",
    "int64": "s64", "int32": "s32", "int16": "s16", "int8": "s8",
    "uint64": "u64", "uint32": "u32", "uint16": "u16", "uint8": "u8",
    "bool": "pred",
}
# config-file dtype spellings (reference data_types block) -> HLO names
_CONFIG_DTYPE_NAMES = {
    "fp32": "f32", "float32": "f32", "f32": "f32",
    "fp16": "f16", "float16": "f16", "f16": "f16",
    "bf16": "bf16", "bfloat16": "bf16",
}


def hlo_dtype_name(dtype) -> str:
    """HLO shorthand ('f32', 'bf16', ...) of a numpy/jax dtype."""
    import numpy as np

    return _HLO_DTYPE_NAMES.get(np.dtype(dtype).name, str(np.dtype(dtype)))


class PrecisionPolicy(NamedTuple):
    """The precision contract a config DECLARES for its compiled steps —
    what the numerics sanitizer (N001-N004) verifies the HLO against.
    All dtype fields use HLO shorthand ('f32', 'bf16', 'f16')."""

    compute: str                 # forward/backward compute dtype
    master: Optional[str]        # master-weight dtype (None = no master)
    grad_accum: str              # gradient ACCUMULATION dtype (scan acc)
    grad_comm: str               # gradient-reduction COLLECTIVE payload
    loss_scaled: bool            # fp16 dynamic loss scaling active
    compressed: Optional[str] = None  # 'onebit' | 'zoadam' | 'qgz' | None


def precision_policy(config, compressed: Optional[str] = None) -> PrecisionPolicy:
    """Derive the declared policy from a DeepSpeedTPUConfig: compute
    dtype from the bf16/fp16 blocks, fp32 master per bf16.master_weights
    (fp16 always keeps one), grad accumulation from
    `data_types.grad_accum_dtype` (default fp32 — the engine's scan
    accumulators are fp32 by construction), collective payload from
    `communication_data_type` (default: the compute dtype, the
    reference default — XLA places the data-parallel grad psum on the
    low-precision side of the master-cast boundary)."""
    compute = hlo_dtype_name(config.compute_dtype)
    use_master = compute != "f32" and (
        config.bf16.master_weights if config.bf16.enabled else True)
    declared = getattr(config, "data_types", None)
    accum = getattr(declared, "grad_accum_dtype", None) if declared else None
    comm = getattr(config, "communication_data_type", None)
    return PrecisionPolicy(
        compute=compute,
        master="f32" if use_master else None,
        grad_accum=_CONFIG_DTYPE_NAMES.get(str(accum).lower(), "f32")
        if accum else "f32",
        grad_comm=_CONFIG_DTYPE_NAMES.get(str(comm).lower(), compute)
        if comm else compute,
        loss_scaled=config.fp16.enabled,
        compressed=compressed,
    )


class LossScaleState(NamedTuple):
    scale: jnp.ndarray  # f32 scalar
    good_steps: jnp.ndarray  # i32 — consecutive overflow-free steps
    hysteresis_left: jnp.ndarray  # i32


def init_loss_scale(cfg: FP16Config) -> LossScaleState:
    if cfg.loss_scale and cfg.loss_scale > 0:
        scale = float(cfg.loss_scale)  # static scale
    else:
        scale = float(2.0**cfg.initial_scale_power)
    return LossScaleState(
        scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis_left=jnp.asarray(cfg.hysteresis, jnp.int32),
    )


def found_inf_in_grads(grads) -> jnp.ndarray:
    """Global overflow flag (ref: fused_optimizer.py overflow check via
    _check_overflow). All-finite reduction fuses into the grad epilogue.
    Integer-dtype leaves (token counts, masks riding a grad pytree) are
    always finite and are skipped; an empty grad pytree reports no
    overflow instead of raising."""

    def is_float(g):
        dt = getattr(g, "dtype", None)
        return dt is None or jnp.issubdtype(dt, jnp.inexact)

    leaves = [g for g in jax.tree.leaves(grads) if is_float(g)]
    if not leaves:
        return jnp.bool_(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def update_loss_scale(
    state: LossScaleState, found_inf: jnp.ndarray, cfg: FP16Config
) -> LossScaleState:
    """ref: loss_scaler.py DynamicLossScaler.update_scale with the
    reference default consecutive_hysteresis=False: hysteresis is spent
    by overflows and only refilled when the scale grows — so once
    exhausted, every further overflow halves the scale (fast recovery
    from divergence); it is NOT refilled by good steps or backoffs."""
    if cfg.loss_scale and cfg.loss_scale > 0:
        return state  # static scale never moves
    exhausted = state.hysteresis_left <= 1
    do_backoff = jnp.logical_and(found_inf, exhausted)
    new_scale = jnp.where(
        do_backoff,
        jnp.maximum(state.scale / 2.0, cfg.min_loss_scale),
        state.scale,
    )
    hyst = jnp.where(
        jnp.logical_and(found_inf, jnp.logical_not(exhausted)),
        state.hysteresis_left - 1,
        state.hysteresis_left,
    )
    good = jnp.where(found_inf, 0, state.good_steps + 1)
    if cfg.consecutive_hysteresis:
        # reference's consecutive_hysteresis=True: refill on every
        # overflow-free step
        hyst = jnp.where(found_inf, hyst, jnp.asarray(cfg.hysteresis, jnp.int32))
    do_grow = good >= cfg.loss_scale_window
    new_scale = jnp.where(do_grow, new_scale * 2.0, new_scale)
    hyst = jnp.where(do_grow, jnp.asarray(cfg.hysteresis, jnp.int32), hyst)
    good = jnp.where(do_grow, 0, good)
    return LossScaleState(scale=new_scale, good_steps=good, hysteresis_left=hyst)


def cast_params(params, dtype):
    """Cast float leaves only (embedding tables of ints etc. untouched)."""
    return jax.tree.map(
        lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p, params
    )


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over the whole grad pytree (ref: engine/stage3 global-norm
    computation). Under jit+SPMD the per-shard partial sums are combined
    by XLA automatically."""
    leaves = jax.tree.leaves(grads)
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(total)


def clip_grads_by_global_norm(grads, max_norm: float, grad_norm: jnp.ndarray):
    """ref: runtime/utils clip_grad_norm_ equivalent; no-op when max_norm<=0."""
    if max_norm <= 0:
        return grads
    factor = jnp.minimum(1.0, max_norm / (grad_norm + 1e-6))
    return jax.tree.map(lambda g: g * factor.astype(g.dtype), grads)
