"""Static analysis for compiled TPU programs and the codebase itself.

Seven prongs (see docs/static_analysis.md):

  sanitizer — ground-truth checks on compiled/lowered artifacts:
              donation aliasing (S001), PartitionSpec survival (S002),
              recompilation-hazard classification (S003). Run against a
              live engine with `engine.sanitize(batch)`.
  costmodel — compile-time cost predictions over the same artifacts:
              per-device HBM budget (S004), collective-volume blowups
              and baseline regressions (S005), roofline balance (S006).
              Baselines persist to MEMBUDGET.json
              (`python scripts/ds_budget.py --capture / --check`).
  schedule  — schedule-aware analysis over the same artifacts:
              exposed-collective time (S007), hierarchy-aware replica-
              group placement (S008), critical-path step-time
              projection (S009) — the autotuner's AOT score. Baselines
              persist to SCHEDULE.json
              (`python scripts/ds_schedule.py --capture / --check`).
  numerics  — precision-flow analysis over the same artifacts: low-
              precision accumulation (N001), fp32 master-weight
              integrity (N002), loss-scale coverage (N003),
              quantized-collective sanity (N004). Dtype ledgers
              persist to NUMERICS.json
              (`python scripts/ds_numerics.py --capture / --check`).
  lint      — `ds-lint`, an AST pass with project rules R001-R008
              (`python scripts/ds_lint.py --strict`).
  concurrency — interprocedural lockset race detection (C001),
              lock-order deadlock cycles (C002), and callback-thread
              escape analysis (C003) over the whole tree at once; the
              lock ledger persists to CONCURRENCY.json
              (`python scripts/ds_race.py --capture / --check`). R003
              is a per-file shim over C001.
  determinism — RNG-discipline and bitwise-reproducibility analysis:
              layout-dependent PRNG draws (D001), reassociation hazards
              on bitwise-pinned programs (D002), host-side ordering
              nondeterminism (D003), serving draw-key discipline
              (D004); the rng-op/reduce-class ledger persists to
              DETERMINISM.json
              (`python scripts/ds_determinism.py --capture / --check`).
              R008 is the per-file lint shim over D001.
"""

from .report import Finding, LintReport, SanitizerReport, merge_reports
from .sanitizer import (
    RecompileTracker,
    abstract_signature,
    check_donation,
    check_sharding,
)
from .costmodel import (
    ICI_GBPS,
    CostReport,
    build_cost_report,
    check_against_baseline,
    check_collective_volume,
    check_hbm_budget,
    check_roofline,
    load_baseline,
    roofline,
    save_baseline,
)
from .schedule import (
    PodTopology,
    ScheduleAnalysis,
    analyze_compiled,
    analyze_schedule,
    check_exposed_comm,
    check_hierarchy_placement,
    check_step_time,
)
from .numerics import (
    check_accumulation_dtypes,
    check_loss_scale,
    check_master_integrity,
    check_program_numerics,
    check_quantized_groups,
    diff_ledgers,
    dtype_ledger,
    grad_elem_counts,
)
from .lint import lint_paths, lint_source, RULES
from .concurrency import (
    C_RULES,
    ConcurrencyReport,
    analyze_paths,
    analyze_sources,
)
from .determinism import (
    BITWISE_PINS,
    BitwisePin,
    D_RULES,
    check_draw_keys,
    check_host_ordering,
    check_reassociation,
    check_rng_discipline,
    pin_for,
    program_determinism,
)

__all__ = [
    "Finding",
    "LintReport",
    "SanitizerReport",
    "merge_reports",
    "RecompileTracker",
    "abstract_signature",
    "check_donation",
    "check_sharding",
    "ICI_GBPS",
    "CostReport",
    "build_cost_report",
    "check_against_baseline",
    "check_collective_volume",
    "check_hbm_budget",
    "check_roofline",
    "load_baseline",
    "roofline",
    "save_baseline",
    "PodTopology",
    "ScheduleAnalysis",
    "analyze_compiled",
    "analyze_schedule",
    "check_exposed_comm",
    "check_hierarchy_placement",
    "check_step_time",
    "check_accumulation_dtypes",
    "check_loss_scale",
    "check_master_integrity",
    "check_program_numerics",
    "check_quantized_groups",
    "diff_ledgers",
    "dtype_ledger",
    "grad_elem_counts",
    "lint_paths",
    "lint_source",
    "RULES",
    "C_RULES",
    "ConcurrencyReport",
    "analyze_paths",
    "analyze_sources",
    "BITWISE_PINS",
    "BitwisePin",
    "D_RULES",
    "check_draw_keys",
    "check_host_ordering",
    "check_reassociation",
    "check_rng_discipline",
    "pin_for",
    "program_determinism",
]
