#!/usr/bin/env python
"""Scaling-credibility bench (VERDICT r3 item 5): the 0.45-MFU north
star (BASELINE.json: Llama-2-70B ZeRO-3 on v5p-256) cannot be verified
on one chip — what CAN be measured is whether per-layer compute
efficiency HOLDS as d_model grows from the 350M flagship (d1024) to 7B
(d4096) and 70B (d8192) layer geometry. This runs a fwd+bwd step over a
LAYER SLICE of each geometry on the real chip and reports MFU against
the same 6N+attention flop model bench.py uses. (Optimizer state for a
70B slice exceeds HBM; fwd+bwd is the part whose efficiency the
north-star argument needs — the optimizer is bandwidth-trivial per
PROFILE_r03's roofline note.)

Writes the 'layer_mfu' block of SCALING_r04.json; the ICI projection
half comes from scripts/ici_projection.py (CPU mesh). docs/PROFILE_r04.md
assembles the argument.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GEOMETRIES = {
    # name: (layers, d_model, heads, kv_heads, d_ff, seq, micro)
    "flagship_350m": (4, 1024, 8, 8, None, 2048, 8),
    "llama7b_slice": (4, 4096, 32, 32, 11008, 4096, 1),
    "llama70b_slice": (2, 8192, 64, 8, 28672, 4096, 1),
}


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.platform.accelerator import (
        bench_device_guard,
        get_accelerator,
    )

    # backend-init timeouts are flaky infra (BENCH_r04/r05): retry with
    # backoff, then emit an infra_flake-marked line instead of hanging
    rc = bench_device_guard("layer_mfu_scaling")
    if rc is not None:
        return rc

    acc = get_accelerator()
    assert acc.is_tpu(), "scaling bench needs the chip"
    peak = acc.peak_flops()
    out = {}
    for name, (L, E, H, KV, F, S, B) in GEOMETRIES.items():
        cfg = T.TransformerConfig(
            vocab_size=32000, n_layers=L, n_heads=H, n_kv_heads=KV,
            d_model=E, d_ff=F, max_seq=S, variant="llama",
            remat="save_attn_qkv", use_flash=True,
            flash_block_q=1024, flash_block_k=1024)
        params = jax.jit(lambda k, c=cfg: jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), T.init(c, k))
        )(jax.random.PRNGKey(0))
        loss_fn = T.make_loss_fn(cfg, loss_chunks=16)

        @jax.jit
        def fwdbwd(p, batch):
            loss, g = jax.value_and_grad(
                lambda q: loss_fn(q, batch, None))(p)
            # fold grads into a scalar so nothing params-sized transfers
            return loss, sum(jnp.sum(x * x) for x in jax.tree.leaves(g))

        r = np.random.default_rng(0)
        batch = {"tokens": r.integers(0, 32000, (B, S + 1)).astype(np.int32)}
        loss, gn = fwdbwd(params, batch)
        np.asarray(jax.device_get(loss))
        steps = 8
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, gn = fwdbwd(params, batch)
        np.asarray(jax.device_get(loss))
        dt = (time.perf_counter() - t0) / steps
        # 6N + attention fwd+bwd flops (flops_per_token counts exactly
        # the train-step model flops; the optimizer's 2N FMA-class work
        # is excluded by construction of 6N = fwd 2N + bwd 4N)
        tok = B * S
        flops = cfg.flops_per_token(S) * tok
        mfu = flops / dt / peak
        out[name] = {
            "layers": L, "d_model": E, "seq": S, "micro_batch": B,
            "params_m": round(T.param_count(cfg) / 1e6, 1),
            "step_ms": round(dt * 1e3, 1),
            "achieved_tflops": round(flops / dt / 1e12, 1),
            "fwd_bwd_mfu": round(mfu, 4),
        }
        print(name, out[name], flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SCALING_r04.json")
    doc = {}
    if os.path.exists(path):
        doc = json.load(open(path))
    doc["layer_mfu"] = out
    doc["peak_tflops"] = peak / 1e12
    json.dump(doc, open(path, "w"), indent=1, sort_keys=True)
    print(json.dumps({"scaling": out}))


if __name__ == "__main__":
    main()
