"""Comms volume/bandwidth logger.

TPU-native analog of the reference comms logging
(ref: deepspeed/utils/comms_logging.py CommsLogger:67 + calc_bw_log:34
and the timed_op decorator comm/comm.py:101-141). Under XLA, individual
collectives cannot be host-timed inside a compiled step, so this logger
records *trace-time* op counts and message volumes (exact, from shapes)
per (op, axis) bucket; bandwidth figures come from dividing recorded
volume by measured step time at the engine level.
"""

from collections import defaultdict
from typing import Dict, Tuple

from ..utils.logging import logger


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self._records: Dict[Tuple[str, str], Dict[str, float]] = defaultdict(
            lambda: {"count": 0, "volume": 0}
        )

    def configure(self, enabled: bool = False, verbose: bool = False):
        self.enabled = enabled
        self.verbose = verbose

    def record(self, op_name: str, volume_bytes: int, axis_name):
        if not self.enabled:
            return
        key = (op_name, str(axis_name))
        rec = self._records[key]
        rec["count"] += 1
        rec["volume"] += volume_bytes
        if self.verbose:
            logger.info(
                f"comm: {op_name} over axis={axis_name} "
                f"msg={volume_bytes / 2**20:.2f}MiB (trace-time)"
            )

    def record_compiled(self, volumes: Dict[str, Dict[str, float]]):
        """Record ground-truth per-op volumes extracted from a compiled
        step's HLO (profiling/hlo.py collective_volumes) — the collectives
        the engine ACTUALLY runs, vs the facade's trace-time bookkeeping
        (fixes VERDICT r1 W6)."""
        if not self.enabled:
            return
        for op, v in volumes.items():
            rec = self._records[(op, "hlo")]
            rec["count"] += int(v["count"])
            rec["volume"] += int(v["bytes"])

    def reset(self):
        self._records.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {f"{op}@{ax}": dict(rec) for (op, ax), rec in self._records.items()}

    def total_volume(self) -> int:
        return int(sum(rec["volume"] for rec in self._records.values()))

    def log_summary(self):
        """ref: comms_logging.py log_summary — per-op table."""
        if not self._records:
            logger.info("comms summary: no collectives recorded")
            return
        lines = ["comms summary (trace-time counts per compiled step):"]
        lines.append(f"{'op':<16}{'axis':<18}{'count':>8}{'volume':>14}")
        for (op, ax), rec in sorted(self._records.items()):
            lines.append(
                f"{op:<16}{ax:<18}{int(rec['count']):>8}{rec['volume'] / 2**20:>11.2f}MiB"
            )
        logger.info("\n".join(lines))


comms_logger = CommsLogger()
