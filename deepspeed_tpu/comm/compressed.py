"""Error-feedback sign-compressed reduction (1-bit Adam/LAMB backbone).

TPU-native redesign of the reference compressed comm backends
(ref: runtime/comm/nccl.py:51 NcclBackend.compressed_allreduce — the
1-bit algorithm's two-hop exchange: workers sign-compress with local
error feedback, all-to-all int8 chunks, each rank server-reduces its
chunk, compresses again with server error feedback, allgathers). The
same two hops here are expressed as ONE SPMD computation on worker-major
arrays:

  partials [dp, N]   dim 0 sharded over the data axes
  hop 1:   resharding [dp_w, dp_c, C] from worker-dim to chunk-dim
           sharding — XLA lowers it to an all-to-all of int8 codes
  server:  per-chunk weighted sum of worker signs (local math)
  hop 2:   replication constraint on the re-compressed chunk codes —
           an int8 all-gather

Wire traffic per step ≈ N bytes of int8 each hop + O(dp) fp32 scales,
vs 4N (fp32) for a ring allreduce — the reference's ~5x comm reduction
(docs/_tutorials/onebit-adam.md) falls out of the dtypes in the HLO,
which tests assert via profiling/hlo.py.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

DP_AXES = ("data", "zero")


def _live_axes(mesh) -> Tuple[str, ...]:
    return tuple(a for a in DP_AXES if mesh.shape.get(a, 1) > 1)


def padded_cols(n: int, dp: int) -> int:
    """Columns of the [dp, ·] error buffers for an N-element leaf."""
    per = (n + dp - 1) // dp
    return per * dp


def _sign(x):
    # sign with sign(0)=+1 so the code always carries magnitude
    # (ref: nccl.py sign compression adds the sign of the compensated buffer)
    return jnp.where(x >= 0, jnp.int8(1), jnp.int8(-1))


def compressed_mean(partials, e_worker, e_server, mesh):
    """Mean over the worker dim of `partials` with 1-bit compression and
    worker+server error feedback.

    partials: [dp, *shape] (dim 0 sharded over data axes)
    e_worker: [dp, Npad]   worker-side error memory
    e_server: [dp, Npad//dp] server-side error memory (chunk-owned)

    Returns (mean_approx [*shape], e_worker', e_server').
    """
    axes = _live_axes(mesh)
    dp = partials.shape[0]
    shape = partials.shape[1:]
    n = int(np.prod(shape)) if shape else 1
    npad = e_worker.shape[1]
    C = npad // dp

    def cst(x, spec):
        if not axes:
            return x
        return jax.lax.with_sharding_constraint(x, P(*spec))

    flat = partials.reshape(dp, n).astype(jnp.float32)
    if npad != n:
        flat = jnp.pad(flat, ((0, 0), (0, npad - n)))
    flat = cst(flat, (axes, None))

    # worker compression (error-compensated)
    c = flat + e_worker
    scale_w = jnp.mean(jnp.abs(c), axis=1)  # [dp]
    sign_w = _sign(c)
    e_worker_new = c - scale_w[:, None] * sign_w.astype(jnp.float32)

    # hop 1: worker-dim → chunk-dim resharding of int8 codes (all-to-all);
    # the barrier pins the int8 dtype at the collective (see quantized_mean)
    chunked = sign_w.reshape(dp, dp, C)
    chunked = cst(chunked, (axes, None, None))
    chunked = jax.lax.optimization_barrier(chunked)
    chunked = cst(chunked, (None, axes, None))
    chunked = jax.lax.optimization_barrier(chunked)
    # server reduce: mean of scale_w[w] * sign[w] for my chunk
    r = jnp.einsum("w,wkc->kc", scale_w / dp, chunked.astype(jnp.float32))
    r = cst(r, (axes, None))

    # server compression (error-compensated)
    c2 = r + e_server
    scale_s = jnp.mean(jnp.abs(c2), axis=1)  # [dp]
    sign_s = _sign(c2)
    e_server_new = c2 - scale_s[:, None] * sign_s.astype(jnp.float32)

    # hop 2: replicate the int8 chunk codes (all-gather)
    sign_s = cst(sign_s, (axes, None))
    sign_s = jax.lax.optimization_barrier(sign_s)
    sign_all = cst(sign_s, (None, None))
    scale_all = cst(scale_s, (None,))
    # barrier the REPLICATED codes and pin the decompressed product
    # replicated at birth: it must be reconstructed locally from the
    # gathered codes — otherwise the partitioner computes it sharded (to
    # please the sharded optimizer-update consumers) and satisfies the
    # replicated-momentum storage with a 4-byte/param f32 gather,
    # re-introducing the traffic the int8 hop just saved
    sign_all, scale_all = jax.lax.optimization_barrier((sign_all, scale_all))
    prod = scale_all[:, None] * sign_all.astype(jnp.float32)
    prod = cst(prod, (None, None))
    prod = jax.lax.optimization_barrier(prod)
    out = prod.reshape(npad)[:n]
    return out.reshape(shape), e_worker_new, e_server_new


def compressed_mean_tree(partials_tree, e_worker_tree, e_server_tree, mesh):
    """Leaf-wise compressed_mean over a gradient/momentum pytree."""
    outs = jax.tree.map(
        lambda p, ew, es: compressed_mean(p, ew, es, mesh),
        partials_tree, e_worker_tree, e_server_tree,
    )
    is3 = lambda x: isinstance(x, tuple) and len(x) == 3
    mean = jax.tree.map(lambda o: o[0], outs, is_leaf=is3)
    ew = jax.tree.map(lambda o: o[1], outs, is_leaf=is3)
    es = jax.tree.map(lambda o: o[2], outs, is_leaf=is3)
    return mean, ew, es


def quantized_mean(partials, mesh, block: int = 2048):
    """ZeRO++ qgZ: mean over the worker dim via int8 block-quantized
    two-hop exchange (ref: runtime/comm/coalesced_collectives.py:31
    all_to_all_quant_reduce + csrc/quantization/quant_reduce.cu —
    quantize → all-to-all → dequant-reduce → re-quantize → gather).

    Unlike the 1-bit path there is no error feedback: fine-grained
    per-block scales keep the quantization error small enough for direct
    use on gradients (the reference uses int4/int8 blocks the same way).

    partials: [dp, *shape], dim 0 sharded over the data axes.
    Returns the approximate mean [*shape].
    """
    axes = _live_axes(mesh)
    dp = partials.shape[0]
    shape = partials.shape[1:]
    n = int(np.prod(shape)) if shape else 1

    # chunk (per server) and block (per scale) geometry, block-aligned so
    # scale windows never cross chunk/shard boundaries
    C0 = (n + dp - 1) // dp
    beff = min(block, C0) if C0 else 1
    nbc = (C0 + beff - 1) // beff  # blocks per chunk
    C = nbc * beff
    npad = dp * C

    def cst(x, spec):
        if not axes:
            return x
        return jax.lax.with_sharding_constraint(x, P(*spec))

    flat = partials.reshape(dp, n).astype(jnp.float32)
    if npad != n:
        flat = jnp.pad(flat, ((0, 0), (0, npad - n)))
    # [worker, chunk, blocks/chunk, block]
    b = cst(flat.reshape(dp, dp, nbc, beff), (axes, None, None, None))
    absmax = jnp.max(jnp.abs(b), axis=3)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(b / scale[..., None]), -127, 127).astype(jnp.int8)

    # hop 1: worker-dim → chunk-dim resharding (int8 all-to-all + small
    # f32 scales). The optimization barrier pins the int8 dtype AT the
    # collective — without it XLA may hoist the f32 dequant across the
    # resharding and put fp32 on the wire.
    # pin the codes in WORKER layout first, then constrain to CHUNK layout:
    # the only way to satisfy both is moving the int8 across the wire
    q = cst(q, (axes, None, None, None))
    scale = cst(scale, (axes, None, None))
    q, scale = jax.lax.optimization_barrier((q, scale))
    q = cst(q, (None, axes, None, None))
    scale = cst(scale, (None, axes, None))
    q, scale = jax.lax.optimization_barrier((q, scale))
    r = jnp.mean(q.astype(jnp.float32) * scale[..., None], axis=0)  # [dp, nbc, beff]
    r = cst(r, (axes, None, None))

    # hop 2: re-quantize my chunk, gather int8 codes
    absmax2 = jnp.max(jnp.abs(r), axis=2)
    scale2 = jnp.where(absmax2 > 0, absmax2 / 127.0, 1.0)
    q2 = jnp.clip(jnp.round(r / scale2[..., None]), -127, 127).astype(jnp.int8)
    q2 = cst(q2, (axes, None, None))
    scale2 = cst(scale2, (axes, None))
    q2, scale2 = jax.lax.optimization_barrier((q2, scale2))
    q2 = cst(q2, (None, None, None))
    scale2 = cst(scale2, (None, None))
    out = (q2.astype(jnp.float32) * scale2[..., None]).reshape(npad)[:n]
    return out.reshape(shape)


def quantized_mean_tree(partials_tree, mesh, block: int = 2048):
    return jax.tree.map(lambda p: quantized_mean(p, mesh, block), partials_tree)


def init_error_buffers(params, dp: int):
    """Zero worker/server error memories for every leaf
    (ref: nccl.py worker_error/server_error allocation)."""

    def ew(p):
        npad = padded_cols(int(np.prod(p.shape)) if p.shape else 1, dp)
        return jnp.zeros((dp, npad), jnp.float32)

    def es(p):
        npad = padded_cols(int(np.prod(p.shape)) if p.shape else 1, dp)
        return jnp.zeros((dp, npad // dp), jnp.float32)

    return jax.tree.map(ew, params), jax.tree.map(es, params)
