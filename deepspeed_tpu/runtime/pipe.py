"""Pipeline parallelism as a single SPMD collective-permute program.

TPU-native redesign of the reference pipeline engine
(ref: runtime/pipe/engine.py PipelineEngine:55, schedule.py
TrainSchedule:189 (1F1B), module.py LayerSpec:30 / _partition_layers:370,
p2p.py). The reference runs one process per stage and executes an
instruction schedule (LoadMicroBatch / SendActivation / RecvActivation /
ForwardPass / ...) with eager p2p between stage processes. On TPU the
whole pipeline is ONE jitted SPMD program:

- The stacked layer pytree [L, ...] is reshaped to [P, L/P, ...]
  (`partition_layers` — the LayerSpec/_partition_layers analog) with the
  stage dim sharded over the 'pipe' mesh axis.
- A stage-major shift register [P, mb, ...] (dim 0 sharded over 'pipe')
  holds one in-flight microbatch per stage. Each loop iteration applies
  every stage's local layers in parallel (`jax.vmap` over the stage dim
  with spmd_axis_name='pipe') and rotates the register one slot
  (`jnp.roll` on the sharded dim → XLA collective-permute over ICI —
  the p2p.py send/recv analog, but compiler-scheduled).
- M microbatches drain in M+P-1 iterations: the same bubble fraction
  (P-1)/(M+P-1) as the reference's 1F1B schedule. 1F1B's memory
  advantage over GPipe is recovered by jax.checkpoint on the stage body
  (activations rematerialize in backward) instead of schedule
  interleaving; `jax.grad` through the loop automatically runs the
  reversed pipeline (the transpose of a collective-permute is the
  reverse permute), giving backward the same overlap structure.

Warmup/drain slots compute on garbage that never reaches an output —
bubbles cost wasted FLOPs here instead of idle time, identical wall-clock.

Activations may be arbitrary pytrees (e.g. hidden states plus an
accumulating MoE aux-loss channel); every leaf travels the register with
a leading microbatch dim.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _is_spec(x):
    return isinstance(x, P)


def num_stages(stage_params) -> int:
    return jax.tree.leaves(stage_params)[0].shape[0]


def partition_layers(stacked_params, n_stages: int, method: str = "uniform"):
    """[L, ...] layer-stacked pytree → [P, L/P, ...] stage-partitioned.

    The LayerSpec partitioner analog (ref: runtime/pipe/module.py
    _partition_layers:370). The reference offers uniform/parameters/
    regex/profile strategies over heterogeneous nn.Module lists; a
    scanned stack is homogeneous by construction, so 'uniform' is exact
    load balance and the only strategy that changes anything.
    """
    if method != "uniform":
        raise NotImplementedError(
            f"partition method '{method}' — scanned layer stacks are "
            "homogeneous; only 'uniform' applies"
        )

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages != 0:
            raise ValueError(
                f"layer count {L} not divisible by pipeline stages {n_stages}"
            )
        return leaf.reshape((n_stages, L // n_stages) + leaf.shape[1:])

    return jax.tree.map(reshape, stacked_params)


def unpartition_layers(stage_params):
    """[P, L/P, ...] → [L, ...] (for export / checkpoint consolidation)."""
    return jax.tree.map(
        lambda leaf: leaf.reshape((leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:]),
        stage_params,
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: Any,
    rng: Optional[jax.Array] = None,
    state_spec: Any = None,
):
    """Run M microbatches through a P-stage pipeline.

    stage_fn(stage_local_params, carry, mb_rng, stage_idx) -> carry'
    applies one stage's local layers to one microbatch's activation
    pytree. It is vmapped over the stage dim with spmd_axis_name='pipe',
    so sharding constraints inside it compose with the stage sharding.

    x: activation pytree, every leaf [M, ...] (microbatch-major).
    rng: per-call key; microbatch m travels with fold_in(rng, m), the
         same per-microbatch key derivation the flat engine uses.
    state_spec: optional PartitionSpec pytree for the [P, ...] shift
         register leaves (e.g. P('pipe', ('data','expert'), 'seq')).

    Returns the same pytree with leaves [M, ...]: microbatch m's output
    of the final stage.
    """
    n_stage = num_stages(stage_params)
    M = jax.tree.leaves(x)[0].shape[0]
    T = M + n_stage - 1

    # Inject garbage for the drain iterations — those slots' outputs fall
    # beyond the ys slice and are never observed (the scheduler-bubble
    # analog: compute runs, result is discarded).
    def pad_leaf(leaf):
        pad = jnp.zeros((n_stage - 1,) + leaf.shape[1:], leaf.dtype)
        return jnp.concatenate([leaf, pad], axis=0)

    xs_in = jax.tree.map(pad_leaf, x)

    if rng is not None:
        mb_keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(jnp.arange(T))
    else:
        mb_keys = jnp.zeros((T, 2), jnp.uint32)

    state = jax.tree.map(
        lambda leaf: jnp.zeros((n_stage,) + leaf.shape[1:], leaf.dtype), x
    )
    key_state = jnp.zeros((n_stage,) + mb_keys.shape[1:], mb_keys.dtype)
    stage_ids = jnp.arange(n_stage)

    # Outside a pipe>1 mesh (pure-function tests, pipe folded away) run as
    # a plain vmap with no sharding annotations.
    mesh = jax.sharding.get_abstract_mesh()
    has_pipe = (
        mesh is not None and not mesh.empty and mesh.shape.get("pipe", 1) > 1
    )
    vstage = jax.vmap(
        stage_fn,
        in_axes=(0, 0, 0, 0),
        spmd_axis_name="pipe" if has_pipe else None,
    )

    def constrain(tree):
        if state_spec is None or not has_pipe:
            return tree
        return jax.tree.map(
            lambda t, s: jax.lax.with_sharding_constraint(t, s) if s is not None else t,
            tree,
            state_spec,
            is_leaf=lambda v: v is None or _is_spec(v),
        )

    def body(carry, xs_t):
        h_state, k_state = carry
        x_t, k_t = xs_t
        # LoadMicroBatch: stage-0 slot takes the next microbatch
        # (ref: pipe/engine.py _exec_load_micro_batch:810).
        h_state = jax.tree.map(lambda s, v: s.at[0].set(v), h_state, x_t)
        k_state = k_state.at[0].set(k_t)
        h_state = constrain(h_state)
        # ForwardPass on every stage in parallel
        # (ref: pipe/engine.py _exec_forward_pass:653).
        new_state = vstage(stage_params, h_state, k_state, stage_ids)
        y = jax.tree.map(lambda s: s[-1], new_state)
        # Send/RecvActivation: rotate the register one stage
        # (ref: pipe/p2p.py — here one collective-permute over ICI).
        h_state = constrain(jax.tree.map(lambda s: jnp.roll(s, 1, axis=0), new_state))
        k_state = jnp.roll(k_state, 1, axis=0)
        return (h_state, k_state), y

    (_, _), ys = jax.lax.scan(body, (state, key_state), (xs_in, mb_keys))
    # Microbatch m surfaces at the last stage on iteration m + P - 1.
    return jax.tree.map(lambda l: l[n_stage - 1 :], ys)


def stage_slice_keys(mb_key, n_layers: int, stage_idx, layers_per_stage: int):
    """Per-layer dropout keys for one stage, matching the flat model's
    `jax.random.split(rng, n_layers)` exactly: split over ALL layers,
    then slice this stage's span — so pipe=P reproduces pipe=1 numerics."""
    all_keys = jax.random.split(mb_key, n_layers)
    return jax.lax.dynamic_slice_in_dim(
        all_keys, stage_idx * layers_per_stage, layers_per_stage, axis=0
    )
