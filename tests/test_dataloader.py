"""Dataloader tests (ref model: tests around runtime/dataloader.py)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader, RepeatingLoader


class ToyDataset:
    def __init__(self, n=20):
        self.items = [{"tokens": np.full((4,), i, np.int32)} for i in range(n)]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]


def test_batching():
    dl = DeepSpeedTPUDataLoader(ToyDataset(20), batch_size=8)
    batches = list(dl)
    assert len(batches) == 2  # drop_last
    assert batches[0]["tokens"].shape == (8, 4)


def test_no_drop_last():
    dl = DeepSpeedTPUDataLoader(ToyDataset(20), batch_size=8, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[-1]["tokens"].shape == (4, 4)


def test_shuffle_deterministic_per_epoch():
    d = ToyDataset(16)
    dl1 = DeepSpeedTPUDataLoader(d, batch_size=16, shuffle=True, seed=3)
    dl2 = DeepSpeedTPUDataLoader(d, batch_size=16, shuffle=True, seed=3)
    b1, b2 = next(iter(dl1)), next(iter(dl2))
    np.testing.assert_array_equal(b1["tokens"], b2["tokens"])
    # second epoch differs
    b1b = next(iter(dl1))
    assert not np.array_equal(b1["tokens"], b1b["tokens"])


def test_too_small_dataset():
    with pytest.raises(ValueError):
        DeepSpeedTPUDataLoader(ToyDataset(4), batch_size=8)


def test_repeating_loader():
    dl = DeepSpeedTPUDataLoader(ToyDataset(16), batch_size=8)
    rl = RepeatingLoader(dl)
    batches = [next(rl) for _ in range(5)]  # wraps past 2-batch epochs
    assert batches[0]["tokens"].shape == (8, 4)
