from .runner import main
