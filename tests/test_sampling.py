"""On-device sampling: the compiled chain must be exact, replayable,
and identical between the fused multi-step decode and stepwise put()
(ref contract: the reference samples GPU-side via MII + gathers logits
on device, inference/v2/kernels/ragged_ops/logits_gather/; VERDICT r3
item 2's done-criterion is reproducing the draws under a fixed seed
with no [batch, vocab] host transfer per decode step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.inference.sampling import (
    SamplingConfig,
    host_oracle_token,
    presence_from_prompts,
    sample_tokens,
)
from deepspeed_tpu.models import transformer as T


def small_model(variant="llama", **kw):
    cfg = T.TransformerConfig(
        vocab_size=kw.pop("vocab_size", 128), n_layers=2,
        n_heads=kw.pop("n_heads", 4), d_model=kw.pop("d_model", 64),
        max_seq=kw.pop("max_seq", 64), variant=variant,
        use_flash=False, **kw)
    params = T.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def engine_for(cfg, params, **ckw):
    base = dict(max_seq_len=64, kv_block_size=8, num_kv_blocks=64,
                min_prefill_bucket=8, max_batch_size=16)
    base.update(ckw)
    return init_inference(params, cfg, base, dtype=jnp.float32)


class TestSamplerUnit:
    @pytest.mark.parametrize("kw", [
        dict(do_sample=False),
        dict(do_sample=True, temperature=0.8),
        dict(do_sample=True, temperature=1.2, top_k=7),
        dict(do_sample=True, temperature=0.9, top_p=0.7),
        dict(do_sample=True, temperature=1.0, top_k=9, top_p=0.85,
             repetition_penalty=1.4),
    ])
    def test_matches_host_oracle(self, rng, kw):
        cfg = SamplingConfig(**kw)
        S, V = 5, 64
        logits = jnp.asarray(rng.normal(size=(S, V)) * 3, jnp.float32)
        presence = jnp.asarray(rng.integers(0, 2, (S, V)), jnp.uint8)
        base = jax.random.PRNGKey(42)
        keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            base, jnp.arange(S, dtype=jnp.uint32))
        steps = jnp.asarray(rng.integers(0, 50, S), jnp.int32)
        toks = sample_tokens(logits, cfg, keys, steps,
                             presence=presence if cfg.needs_presence
                             else None)
        for s in range(S):
            want = host_oracle_token(
                np.asarray(logits[s]), cfg, keys[s], int(steps[s]),
                presence_row=np.asarray(presence[s])
                if cfg.needs_presence else None)
            assert int(toks[s]) == want, f"row {s}"

    def test_greedy_is_argmax(self, rng):
        logits = jnp.asarray(rng.normal(size=(3, 32)), jnp.float32)
        toks = sample_tokens(logits, SamplingConfig(do_sample=False),
                             None, None)
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.argmax(np.asarray(logits), -1))

    def test_top_k_restricts_support(self, rng):
        cfg = SamplingConfig(do_sample=True, temperature=5.0, top_k=3)
        logits = jnp.asarray(rng.normal(size=(1, 64)), jnp.float32)
        top3 = set(np.argsort(np.asarray(logits[0]))[-3:].tolist())
        base = jax.random.PRNGKey(0)
        keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            base, jnp.zeros((1,), jnp.uint32))
        for t in range(50):
            tok = int(sample_tokens(logits, cfg, keys,
                                    jnp.asarray([t], jnp.int32))[0])
            assert tok in top3

    def test_penalty_discourages_seen(self, rng):
        """With a harsh penalty and near-flat logits, a seen token with
        the (slightly) max logit loses greedy argmax."""
        V = 32
        logits = np.zeros((1, V), np.float32)
        logits[0, 5] = 0.1   # max, positive, seen -> 0.01 after /10
        logits[0, 7] = 0.05  # unseen runner-up wins post-penalty
        presence = np.zeros((1, V), np.uint8)
        presence[0, 5] = 1
        cfg = SamplingConfig(do_sample=False, repetition_penalty=10.0)
        tok = sample_tokens(jnp.asarray(logits), cfg, None, None,
                            presence=jnp.asarray(presence))
        assert int(tok[0]) == 7


class TestGenerateOnDevice:
    def test_seeded_reproducible(self, rng):
        cfg, params = small_model()
        eng = engine_for(cfg, params)
        prompts = [list(rng.integers(0, 128, 7)), list(rng.integers(0, 128, 4))]
        kw = dict(max_new_tokens=10, do_sample=True, temperature=1.1,
                  top_k=20, seed=9)
        a = eng.generate(prompts, **kw)
        b = eng.generate(prompts, **kw)
        c = eng.generate(prompts, max_new_tokens=10, do_sample=True,
                         temperature=1.1, top_k=20, seed=10)
        assert a == b
        assert a != c  # overwhelmingly likely over 20 draws

    def test_seed_independent_of_inflight_uids(self, rng):
        """Streams key by generate's SLOT index, not the allocated uid:
        the same seed must reproduce even when other sequences hold the
        low uids (r4 review finding)."""
        cfg, params = small_model()
        idle = engine_for(cfg, params)
        prompts = [list(rng.integers(0, 128, 6))]
        kw = dict(max_new_tokens=8, do_sample=True, temperature=1.1,
                  top_k=16, seed=4)
        a = idle.generate(prompts, **kw)
        busy = engine_for(cfg, params)
        busy.put([0, 1], [np.asarray(rng.integers(0, 128, 5), np.int32),
                          np.asarray(rng.integers(0, 128, 4), np.int32)])
        b = busy.generate(prompts, **kw)  # allocates uid 2, slot 0
        assert a == b

    def test_fused_chunks_match_stepwise(self, rng):
        """chunk=8 (fused decode_multi) and chunk=1 must produce the
        SAME tokens for the same seed — draws are keyed by
        (seed, uid, position), not by program shape."""
        cfg, params = small_model()
        eng = engine_for(cfg, params)
        prompts = [list(rng.integers(0, 128, 7)), list(rng.integers(0, 128, 4))]
        kw = dict(max_new_tokens=11, do_sample=True, temperature=0.9,
                  top_k=12, top_p=0.9, seed=3)
        a = eng.generate(prompts, chunk=8, **kw)
        b = eng.generate(prompts, chunk=1, **kw)
        assert a == b

    def test_generate_matches_put_replay(self, rng):
        """The fused-generate trajectory replayed through stepwise
        put(return_tokens=True) — same seed, same uids — reproduces
        every token (the host-replay done-criterion)."""
        cfg, params = small_model()
        eng = engine_for(cfg, params)
        prompts = [list(rng.integers(0, 128, 6)), list(rng.integers(0, 128, 9))]
        kw = dict(do_sample=True, temperature=1.0, top_k=10,
                  repetition_penalty=1.3)
        got = eng.generate(prompts, max_new_tokens=8, seed=5, **kw)

        replay = engine_for(cfg, params)
        pres = presence_from_prompts(prompts, cfg.vocab_size, len(prompts))
        toks = replay.put([0, 1], [np.asarray(p, np.int32) for p in prompts],
                          return_tokens=True, sampling=kw, seed=5,
                          presence=pres)
        seqs = [[int(toks[0])], [int(toks[1])]]
        pres[0, int(toks[0])] = 1
        pres[1, int(toks[1])] = 1
        for _ in range(7):
            toks = replay.put(
                [0, 1], [np.asarray([s[-1]], np.int32) for s in seqs],
                return_tokens=True, sampling=kw, seed=5, presence=pres)
            for i in range(2):
                seqs[i].append(int(toks[i]))
                pres[i, int(toks[i])] = 1
        assert got == seqs

    def test_greedy_generate_unchanged(self, rng):
        """Greedy fused generate == greedy stepwise logits argmax (the
        pre-existing behavior contract)."""
        cfg, params = small_model()
        prompt = np.asarray(rng.integers(0, 128, 7), np.int32)
        want_eng = engine_for(cfg, params)
        lg = want_eng.put([0], [prompt.copy()])
        want = []
        for _ in range(9):
            t = int(np.argmax(lg[0]))
            want.append(t)
            lg = want_eng.put([0], [np.asarray([t], np.int32)])
        got = engine_for(cfg, params).generate([list(prompt)],
                                               max_new_tokens=9)
        assert got[0] == want

    def test_eos_mid_chunk(self, rng):
        """A sequence hitting EOS inside a fused chunk stops there."""
        cfg, params = small_model()
        eng = engine_for(cfg, params)
        prompt = list(rng.integers(0, 128, 5))
        full = eng.generate([prompt], max_new_tokens=12, seed=1)
        if len(full[0]) < 3:
            pytest.skip("trajectory too short to pick a mid-chunk eos")
        eos = full[0][2]
        cut = eng.generate([prompt], max_new_tokens=12, seed=1,
                           eos_token_id=eos)
        assert cut[0] == full[0][: full[0].index(eos) + 1]

    def test_put_return_tokens_greedy_matches_logits(self, rng):
        cfg, params = small_model()
        a = engine_for(cfg, params)
        b = engine_for(cfg, params)
        prompts = [np.asarray(rng.integers(0, 128, 6), np.int32),
                   np.asarray(rng.integers(0, 128, 3), np.int32)]
        lg = a.put([0, 1], [p.copy() for p in prompts])
        tk = b.put([0, 1], [p.copy() for p in prompts], return_tokens=True)
        np.testing.assert_array_equal(np.argmax(lg, -1), tk)
        # decode rows too
        nxt = [np.asarray([t], np.int32) for t in tk]
        lg = a.put([0, 1], [n.copy() for n in nxt])
        tk2 = b.put([0, 1], [n.copy() for n in nxt], return_tokens=True)
        np.testing.assert_array_equal(np.argmax(lg, -1), tk2)

    def test_penalty_without_presence_raises(self, rng):
        cfg, params = small_model()
        eng = engine_for(cfg, params)
        with pytest.raises(ValueError, match="presence"):
            eng.put([0], [np.asarray([1, 2, 3], np.int32)],
                    return_tokens=True,
                    sampling=dict(do_sample=True, repetition_penalty=1.5))
