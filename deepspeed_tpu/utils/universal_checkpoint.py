"""Universal checkpoint: cross-topology layout conversion.

TPU-native analog of the reference's universal-checkpoint tooling
(ref: deepspeed/checkpoint/ds_to_universal.py — extract zero shards :87,
merge TP slices :156; universal_checkpoint.py load_hp_checkpoint_state
:12; reshape_meg_2d.py). Most of that machinery is unnecessary here:
orbax checkpoints store LOGICAL arrays, so mesh-shape/ZeRO-stage/
precision changes reshard for free on load (tested in
tests/test_checkpoint.py). The one change that alters the TREE itself is
the pipeline-parallel degree: pipelined engines store the layer stack
stage-partitioned [P, L/P, ...] (runtime/pipe.partition_layers). This
tool rewrites a checkpoint between pipeline degrees — the
`ds_to_universal` role reduced to its TPU-remaining core.

Usage:
    python -m deepspeed_tpu.utils.universal_checkpoint \
        <ckpt_dir> <out_dir> --source-stages 2 --target-stages 1
"""

import json
import os
import shutil
from typing import Any, Dict, Optional


def _reshape_layer_leaf(leaf, source_stages: int, target_stages: int,
                        source_virtual: int = 1, target_virtual: int = 1):
    """Re-partition one stacked layer leaf between pipeline layouts.

    The circular (interleaved) layout [v, P, lc, ...] assigns chunk
    c = r*P + p to stage p at round r (runtime/pipe.partition_layers)
    — and flat layer index l = (r*P + p)*lc + c_in_chunk equals the
    plain row-major reshape, so collapsing ALL leading layout dims
    recovers the flat [L, ...] stack exactly. What conversion cannot do
    from shapes alone is know HOW MANY leading dims are layout (a
    [v, P, lc] stack with v == P reads like [P, L/P] with a weight dim)
    — hence the explicit source_virtual (recorded in checkpoint meta as
    pipeline_virtual_stages; ref reshaper:
    deepspeed/checkpoint/reshape_3d_utils.py)."""
    import numpy as np

    x = np.asarray(leaf)
    if source_stages > 1:
        lead = 3 if source_virtual > 1 else 2
        L = int(np.prod(x.shape[:lead]))
        x = x.reshape((L,) + x.shape[lead:])
    if target_stages > 1:
        L = x.shape[0]
        if L % (target_stages * target_virtual):
            raise ValueError(
                f"layer count {L} not divisible by target stages "
                f"{target_stages} x virtual {target_virtual}"
            )
        if target_virtual > 1:
            x = x.reshape((target_virtual, target_stages,
                           L // (target_stages * target_virtual))
                          + x.shape[1:])
        else:
            x = x.reshape((target_stages, L // target_stages) + x.shape[1:])
    return x


def _convert_tree(tree: Any, source: int, target: int,
                  source_virtual: int = 1, target_virtual: int = 1):
    """Reshape the 'layers' subtree of a params-shaped tree (params,
    master, or an optimizer moment). Trees whose layer leaves do NOT
    match the params layout (e.g. 1-bit error buffers) are rejected by
    the caller's shape check."""
    if not isinstance(tree, dict) or "layers" not in tree:
        return tree
    out = dict(tree)
    out["layers"] = {
        k: _reshape_layer_leaf(v, source, target, source_virtual,
                               target_virtual)
        for k, v in tree["layers"].items()
    }
    return out


def convert_pipeline_layout(
    ckpt_dir: str,
    out_dir: str,
    source_stages: int,
    target_stages: int,
    tag: Optional[str] = None,
    source_virtual: int = 1,
    target_virtual: int = 1,
) -> str:
    """Rewrite <ckpt_dir>/<tag> into <out_dir>/<tag> with the layer stack
    re-partitioned from source_stages to target_stages (1 = flat).
    source_virtual/target_virtual handle circular (interleaved)
    [v, P, lc, ...] layouts on either side."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    from .zero_to_fp32 import _resolve_tag

    ckpt_dir = os.path.abspath(ckpt_dir)
    tag = _resolve_tag(ckpt_dir, tag)
    raw = ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).restore(
        os.path.join(ckpt_dir, tag, "state")
    )
    raw = jax.tree.map(lambda x: np.asarray(x), raw)

    params = raw["params"]
    layer_shapes = {k: np.asarray(v).shape for k, v in params["layers"].items()}

    def convert_like_params(tree):
        if tree is None or not isinstance(tree, dict):
            return tree
        if "layers" in tree:
            shapes = {k: np.asarray(v).shape for k, v in tree["layers"].items()}
            if shapes != layer_shapes:
                raise ValueError(
                    "tree has a 'layers' subtree whose shapes do not match "
                    "params (e.g. 1-bit error buffers) — conversion of such "
                    "state is not supported; resume with a fresh optimizer "
                    "or the original pipeline degree"
                )
        return _convert_tree(tree, source_stages, target_stages,
                             source_virtual, target_virtual)

    out = dict(raw)
    out["params"] = convert_like_params(params)
    if raw.get("master") is not None:
        out["master"] = convert_like_params(raw["master"])
    if raw.get("opt") is not None:
        out["opt"] = {k: convert_like_params(v) for k, v in raw["opt"].items()}

    os.makedirs(os.path.join(out_dir, tag), exist_ok=True)
    ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).save(
        os.path.join(out_dir, tag, "state"), out, force=True
    )
    meta_src = os.path.join(ckpt_dir, tag, "meta.json")
    if os.path.exists(meta_src):
        shutil.copy(meta_src, os.path.join(out_dir, tag, "meta.json"))
    with open(os.path.join(out_dir, "latest"), "w") as f:
        f.write(tag)
    return os.path.join(out_dir, tag)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint_dir")
    p.add_argument("output_dir")
    p.add_argument("--source-stages", type=int, required=True)
    p.add_argument("--target-stages", type=int, required=True)
    p.add_argument("--source-virtual", type=int, default=1)
    p.add_argument("--target-virtual", type=int, default=1)
    p.add_argument("--tag", default=None)
    a = p.parse_args(argv)
    out = convert_pipeline_layout(
        a.checkpoint_dir, a.output_dir, a.source_stages, a.target_stages,
        a.tag, source_virtual=a.source_virtual,
        target_virtual=a.target_virtual,
    )
    print(f"wrote converted checkpoint to {out}")


if __name__ == "__main__":
    main()
