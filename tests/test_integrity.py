"""Silent-data-corruption guardian (resilience/integrity.py,
docs/fault_tolerance.md SDC section): seeded dtype-aware bit flips,
blake2b integrity envelopes, the EMA z-score anomaly detector, the
digest-verified peer-mirror reconstruct, handoff payload verification,
and the ElasticTrainer guardian journey (veto -> verified-mirror
rollback -> bitwise-clean replay). The full multi-fault lane is gated
end-to-end by `bench.py --sdc-chaos` / scripts/ds_sdc.py (tier-1
pre-test gate); here the pieces are proven fast and in isolation.
"""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.resilience import (
    AnomalyDetector,
    FaultPlan,
    HandoffIntegrityError,
    PeerRedundantStore,
    PersistentAnomalyError,
    UnrecoverableWorldError,
    armed,
    corrupt_payload,
    corrupt_tree,
    fault_point,
    flip_bits,
    payload_digest,
    tree_digest,
)


# ---------------------------------------------------------------------------
# seeded dtype-aware bit flips
# ---------------------------------------------------------------------------

class TestFlipBits:
    def test_same_key_same_flips(self):
        a = np.linspace(1, 2, 16).astype(np.float32)
        c1, l1 = flip_bits(a, seed=7, invocation=3, path="p")
        c2, l2 = flip_bits(a, seed=7, invocation=3, path="p")
        np.testing.assert_array_equal(c1, c2)
        assert l1 == l2 and len(l1) == 1

    def test_different_invocation_or_path_differs(self):
        a = np.linspace(1, 2, 4096).astype(np.float32)
        c1, l1 = flip_bits(a, 7, 3, "p")
        c2, l2 = flip_bits(a, 7, 4, "p")
        c3, l3 = flip_bits(a, 7, 3, "q")
        assert l1 != l2 and l1 != l3  # (index, bit) draws diverge

    def test_original_untouched_and_dtype_preserved(self):
        a = np.ones((8,), np.float32)
        c, _ = flip_bits(a, 0, 1, "x")
        assert np.all(a == 1.0)
        assert c.dtype == a.dtype and not np.array_equal(a, c)

    def test_exponent_class_moves_orders_of_magnitude(self):
        a = np.full((4,), 1.5, np.float32)
        c, [(idx, bit)] = flip_bits(a, 1, 1, "g", bit_class="exponent")
        assert 23 <= bit <= 30  # f32 exponent field, sign excluded
        ratio = abs(float(c[idx])) / 1.5
        assert ratio > 2.0 or ratio < 0.5

    def test_bfloat16_flips_in_its_own_word(self):
        ml_dtypes = pytest.importorskip("ml_dtypes")
        a = np.ones((4,), ml_dtypes.bfloat16)
        c, [(idx, bit)] = flip_bits(a, 0, 1, "b")
        assert c.dtype == a.dtype and bit < 16
        assert float(np.asarray(c, np.float32)[idx]) != 1.0

    def test_corrupt_tree_flips_one_leaf_and_logs_path(self):
        t = {"w": np.arange(6, dtype=np.float32),
             "b": np.arange(3, dtype=np.float32)}
        d0 = tree_digest(t)
        ct, log = corrupt_tree(t, seed=1, invocation=1)
        assert tree_digest(t) == d0          # original untouched
        assert tree_digest(ct) != d0 and len(log) == 1
        assert "^bit" in log[0]

    def test_corrupt_payload_breaks_its_digest(self):
        p = {"seen_tokens": 5, "n_blocks": 1, "token_ids": [1, 2],
             "k": np.ones((2, 1, 4), np.float32),
             "v": np.zeros((2, 1, 4), np.float32)}
        p["digest"] = payload_digest(p)
        cp, log = corrupt_payload(p, seed=0, invocation=1)
        assert payload_digest(cp) != cp["digest"] and log
        assert payload_digest(p) == p["digest"]  # original untouched


# ---------------------------------------------------------------------------
# integrity envelopes
# ---------------------------------------------------------------------------

class TestDigests:
    def test_tree_digest_sensitive_to_value_dtype_shape_path(self):
        base = {"a": np.arange(4, dtype=np.float32)}
        d = tree_digest(base)
        assert tree_digest({"a": np.arange(4, dtype=np.float32)}) == d
        v = {"a": np.arange(4, dtype=np.float32)}
        v["a"][2] = np.nextafter(v["a"][2], 4)  # one ULP: still caught
        assert tree_digest(v) != d
        assert tree_digest({"a": np.arange(4, dtype=np.float64)}) != d
        assert tree_digest(
            {"a": np.arange(4, dtype=np.float32).reshape(2, 2)}) != d
        assert tree_digest({"b": np.arange(4, dtype=np.float32)}) != d

    def test_payload_digest_excludes_envelope_and_orders_keys(self):
        p = {"x": np.ones(3, np.float32), "n": 2}
        d = payload_digest(p)
        p["digest"] = d
        assert payload_digest(p) == d  # the envelope rides inside
        assert payload_digest({"n": 2, "x": np.ones(3, np.float32)}) == d

    def test_none_and_scalar_leaves(self):
        a = payload_digest({"token_ids": None, "n": 1})
        b = payload_digest({"token_ids": [0], "n": 1})
        assert a != b


# ---------------------------------------------------------------------------
# the anomaly detector
# ---------------------------------------------------------------------------

class TestAnomalyDetector:
    def test_warmup_is_exempt_then_spike_trips(self):
        # warmup observations only feed the window — they can never
        # trip, however wild (the compile/init-transient exemption)
        det = AnomalyDetector(zscore=8.0, window=8, warmup=3)
        assert det.observe({"loss": 1e30}) == "ok"
        assert det.trips == 0
        det = AnomalyDetector(zscore=8.0, window=8, warmup=3)
        for i in range(6):
            assert det.observe({"loss": 4.0 - 0.01 * i}) == "ok"
        assert det.observe({"loss": 4e8}) == "anomaly"
        assert det.trips == 1

    def test_trip_not_absorbed_into_window(self):
        det = AnomalyDetector(zscore=8.0, window=8, warmup=2)
        for i in range(6):
            det.observe({"g": 1.0 + 0.01 * i})
        assert det.observe({"g": 1e20}) == "anomaly"
        # had the spike widened sigma, a second spike would pass
        assert det.observe({"g": 1e20}) == "anomaly"
        assert det.consecutive_trips == 2
        assert det.observe({"g": 1.05}) == "ok"
        assert det.consecutive_trips == 0

    def test_nonfinite_trips_regardless_of_window(self):
        det = AnomalyDetector(warmup=1)
        assert det.observe({"loss": float("nan")}) == "nonfinite"
        assert det.nonfinite_trips == 1

    def test_skip_counts_without_touching_stats(self):
        det = AnomalyDetector(warmup=2)
        det.observe({"loss": 4.0})
        stats = dict(det._stats)
        det.note_skip()
        assert det.skips == 1 and det._stats == stats

    def test_benign_training_drift_never_trips(self):
        det = AnomalyDetector(zscore=8.0, window=16, warmup=4)
        rng = np.random.default_rng(0)
        loss, g = 5.0, 2.0
        for _ in range(200):
            loss *= 0.995
            g *= float(rng.uniform(0.97, 1.03))
            assert det.observe({"loss": loss, "grad_norm": g}) == "ok"

    def test_bad_params_rejected(self):
        with pytest.raises(ValueError):
            AnomalyDetector(zscore=0)
        with pytest.raises(ValueError):
            AnomalyDetector(warmup=0)


# ---------------------------------------------------------------------------
# digest-verified peer-mirror reconstruct
# ---------------------------------------------------------------------------

def _payloads(world, step=0):
    return {r: {"w": np.full((4,), 100 * step + r, np.float32)}
            for r in range(world)}


class TestMirrorIntegrity:
    def test_corrupted_holder_falls_over_to_next(self):
        st = PeerRedundantStore(world=4, spare=2)
        st.snapshot(3, _payloads(4, 3), shared={"k": 1})
        # rank 2's first holder (rank 3) took a silent flip
        st._mirror[3][2] = corrupt_tree(st._mirror[3][2], 9, 1)[0]
        st.lose([2])
        _, out, _ = st.reconstruct()
        np.testing.assert_array_equal(
            out[2]["w"], np.full((4,), 302, np.float32))
        assert st.integrity_failures == 1

    def test_local_copy_verified_too(self):
        st = PeerRedundantStore(world=2, spare=1)
        st.snapshot(1, _payloads(2))
        st._local[0] = corrupt_tree(st._local[0], 9, 1)[0]
        _, out, _ = st.reconstruct()  # falls over to rank 1's mirror
        np.testing.assert_array_equal(
            out[0]["w"], np.zeros((4,), np.float32))
        assert st.integrity_failures == 1

    def test_all_copies_corrupt_is_unrecoverable(self):
        st = PeerRedundantStore(world=2, spare=1)
        st.snapshot(1, _payloads(2))
        st.lose([0])
        st._mirror[1][0] = corrupt_tree(st._mirror[1][0], 9, 1)[0]
        with pytest.raises(UnrecoverableWorldError) as ei:
            st.reconstruct()
        assert ei.value.missing_ranks == [0]
        assert st.integrity_failures == 1

    def test_verify_false_skips_digests(self):
        st = PeerRedundantStore(world=2, spare=1)
        st.snapshot(1, _payloads(2))
        st._local[0] = corrupt_tree(st._local[0], 9, 1)[0]
        _, out, _ = st.reconstruct(verify=False)
        assert st.integrity_failures == 0  # trusted as-is

    def test_mirror_fault_point_corrupts_exact_entry(self):
        plan = FaultPlan([{"point": "mirror.payload", "kind": "corrupt",
                           "where": {"holder": 1, "owner": 0},
                           "at": 1, "times": 1}], seed=5)
        st = PeerRedundantStore(world=2, spare=1)
        with armed(plan) as p:
            st.snapshot(1, _payloads(2))
        assert p.fired == ["mirror.payload#1:corrupt:corrupt"]
        # the holder's copy diverged; the local copy did not
        assert tree_digest(st._mirror[1][0]) != st._digests[0]
        assert tree_digest(st._local[0]) == st._digests[0]
        # same plan, fresh store: byte-identical corruption
        st2 = PeerRedundantStore(world=2, spare=1)
        with armed(FaultPlan(plan.to_dict()["faults"], seed=5)):
            st2.snapshot(1, _payloads(2))
        np.testing.assert_array_equal(
            st._mirror[1][0]["w"], st2._mirror[1][0]["w"])


# ---------------------------------------------------------------------------
# verified control-plane broadcast (comm layer envelope)
# ---------------------------------------------------------------------------

class TestVerifiedBroadcast:
    def test_envelope_rides_the_guarded_collective(self):
        import deepspeed_tpu.comm as comm

        v = {"resume_step": np.int32(7),
             "order": np.arange(4, dtype=np.int32)}
        got = comm.broadcast_host(v, verify=True)
        np.testing.assert_array_equal(got["order"], v["order"])
        # the verified variant goes through the same timeout+retry
        # guard (its own op name, so plans can target it)
        plan = FaultPlan([{"point": "comm.collective", "kind": "raise",
                           "error": "io",
                           "where": {"op": "broadcast_host[verified]"},
                           "times": 1}])
        with armed(plan) as p:
            assert comm.broadcast_host({"a": 1}, verify=True) == {"a": 1}
        assert len(p.fired) == 1  # fired once, healed by the retry


# ---------------------------------------------------------------------------
# fault-plan corrupt determinism through the FaultAction channel
# ---------------------------------------------------------------------------

class TestCorruptActionDeterminism:
    def test_action_carries_seed_and_invocation(self):
        plan = FaultPlan([{"point": "x.y", "kind": "corrupt",
                           "times": -1}], seed=42)
        with armed(plan):
            a1 = fault_point("x.y")
            a2 = fault_point("x.y")
        assert (a1.seed, a1.invocation) == (42, 1)
        assert (a2.seed, a2.invocation) == (42, 2)
        t = {"w": np.ones((16,), np.float32)}
        c1 = corrupt_tree(t, a1.seed, a1.invocation)[0]
        c2 = corrupt_tree(t, a2.seed, a2.invocation)[0]
        # replaying the plan reproduces each invocation's flip exactly
        plan.reset()
        with armed(plan):
            b1 = fault_point("x.y")
        np.testing.assert_array_equal(
            c1["w"], corrupt_tree(t, b1.seed, b1.invocation)[0]["w"])
        assert not np.array_equal(c1["w"], c2["w"])


# ---------------------------------------------------------------------------
# KV handoff envelope (inference engine level)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kv_engines():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference import init_inference
    from deepspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=64, n_layers=1, n_heads=2, d_model=32, max_seq=32,
        variant="llama", use_flash=False)
    params = T.init(cfg, jax.random.PRNGKey(0))

    def mk():
        return init_inference(
            params, cfg,
            dict(max_seq_len=32, kv_block_size=8, num_kv_blocks=16,
                 min_prefill_bucket=8, max_batch_size=4),
            dtype=jnp.float32)

    return mk


class TestHandoffEnvelope:
    def test_export_attaches_digest_import_verifies(self, kv_engines):
        src, dst = kv_engines(), kv_engines()
        prompt = np.arange(1, 11, dtype=np.int32)
        src.put([7], [prompt], return_tokens=True)
        payload = src.export_kv(7)
        assert payload["digest"] == payload_digest(payload)
        dst.import_kv(7, payload)  # verifies + adopts cleanly
        assert dst.state.get(7).seen_tokens == payload["seen_tokens"]

    def test_tampered_payload_rejected_before_allocation(self, kv_engines):
        src, dst = kv_engines(), kv_engines()
        src.put([3], [np.arange(1, 11, dtype=np.int32)],
                return_tokens=True)
        payload = src.export_kv(3)
        evil = dict(payload)
        evil["k"] = np.array(payload["k"])
        evil["k"].reshape(-1)[0] += 1e-6  # sub-noise nudge: still caught
        free_before = dst.state.free_blocks
        with pytest.raises(HandoffIntegrityError):
            dst.import_kv(3, evil)
        assert dst.state.get(3) is None  # nothing allocated
        assert dst.state.free_blocks == free_before

    def test_fault_point_corrupt_detected(self, kv_engines):
        src, dst = kv_engines(), kv_engines()
        src.put([1], [np.arange(1, 11, dtype=np.int32)],
                return_tokens=True)
        payload = src.export_kv(1)
        plan = FaultPlan([{"point": "handoff.payload",
                           "kind": "corrupt", "times": 1}])
        with armed(plan) as p:
            with pytest.raises(HandoffIntegrityError):
                dst.import_kv(1, payload)
        assert p.fired == ["handoff.payload#1:corrupt:corrupt"]
        # the caller's payload object was not mutated: a retry works
        dst.import_kv(1, payload)


# ---------------------------------------------------------------------------
# the trainer guardian journey (veto -> verified rollback -> clean replay)
# ---------------------------------------------------------------------------

ELASTIC = {"enabled": True, "max_train_batch_size": 8,
           "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8}
GUARD = {"zscore": 8.0, "window": 16, "warmup": 2, "persistent_trips": 2}


def _make_engine(world, **over):
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.platform.mesh import build_mesh

    mcfg = T.TransformerConfig(vocab_size=64, n_layers=1, n_heads=2,
                               d_model=32, max_seq=16, variant="llama",
                               use_flash=False)
    mesh = build_mesh({"data": world}, devices=jax.devices()[:world])
    cfg = {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "elasticity": dict(ELASTIC),
           "zero_optimization": {"stage": 1},
           "seed": 3, "steps_per_print": 10**9}
    cfg.update(over)
    return ds.initialize(
        cfg,
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
        mesh=mesh)


def _make_loader():
    from deepspeed_tpu.runtime.dataloader import (
        DeepSpeedTPUDataLoader,
        RepeatingLoader,
    )

    class Tok:
        def __init__(self, n=24):
            r = np.random.default_rng(9)
            self.items = [
                {"tokens": r.integers(0, 64, (17,)).astype(np.int32)}
                for _ in range(n)]

        def __len__(self):
            return len(self.items)

        def __getitem__(self, i):
            return self.items[i]

    return RepeatingLoader(DeepSpeedTPUDataLoader(
        Tok(), batch_size=8, shuffle=True, seed=5))


class TestTrainerGuardian:
    def test_grad_flip_vetoed_rollback_replay_bitwise(self):
        from deepspeed_tpu.elasticity import ElasticTrainer
        from deepspeed_tpu.monitor.monitor import (
            training_resilience_events,
        )

        T_STEPS = 6
        clean = ElasticTrainer(_make_engine, 2, _make_loader(),
                               every_k_steps=2,
                               elastic_block=dict(ELASTIC),
                               guardian=dict(GUARD))
        clean.run(T_STEPS)
        plan = FaultPlan([{"point": "engine.grads", "kind": "corrupt",
                           "where": {"step": 4}, "times": 1}])
        chaos = ElasticTrainer(_make_engine, 2, _make_loader(),
                               every_k_steps=2,
                               elastic_block=dict(ELASTIC),
                               guardian=dict(GUARD))
        with armed(plan) as p:
            chaos.run(T_STEPS)
        assert p.fired == ["engine.grads#1:corrupt:corrupt"]
        assert chaos.anomalies_detected == 1
        assert chaos.integrity_rollbacks == 1
        assert chaos.last_rollback_steps <= 2  # mirror cadence K=2
        # the corrupted update never committed: trajectory and sample
        # ledger are byte-identical to the clean run
        assert sorted(chaos.history) == list(range(1, T_STEPS + 1))
        assert all(clean.history[s] == chaos.history[s]
                   for s in range(1, T_STEPS + 1))
        assert json.dumps(sorted(clean.ledger.items())) \
            == json.dumps(sorted(chaos.ledger.items()))
        # guardian counters flow through the monitor feed
        names = {n for n, _, _ in
                 training_resilience_events(chaos, step=T_STEPS)}
        assert {"train/resilience/anomalies_detected",
                "train/resilience/integrity_rollbacks",
                "train/resilience/mirror_integrity_failures",
                "train/resilience/skipped_steps"} <= names

    def test_persistent_anomaly_escalates(self):
        from deepspeed_tpu.elasticity import ElasticTrainer

        # times=-1: the same step's readout corrupts on EVERY replay —
        # after persistent_trips verified rollbacks the guardian must
        # escalate instead of looping forever (step 4 sits past the
        # detector's warmup window)
        plan = FaultPlan([{"point": "engine.grads", "kind": "corrupt",
                           "where": {"step": 4}, "times": -1}])
        tr = ElasticTrainer(_make_engine, 2, _make_loader(),
                            every_k_steps=1,
                            elastic_block=dict(ELASTIC),
                            guardian={**GUARD, "persistent_trips": 1})
        with armed(plan):
            with pytest.raises(PersistentAnomalyError):
                tr.run(5)
        assert tr.integrity_rollbacks == 1  # one verified attempt


# ---------------------------------------------------------------------------
# found-inf skipped step: ledger stays in sync, EMA window unpolluted
# ---------------------------------------------------------------------------

class TestFoundInfSkip:
    def test_fp16_overflow_skip_keeps_ledger_and_window_clean(self):
        import jax

        from deepspeed_tpu.elasticity import ElasticTrainer

        # 2^20 loss scale overflows f16 immediately (hysteresis=1 so
        # the scale halves on the first overflow and recovers fast)
        tr = ElasticTrainer(
            lambda w: _make_engine(
                w, fp16={"enabled": True, "initial_scale_power": 20,
                         "hysteresis": 1, "loss_scale_window": 1000}),
            2, _make_loader(), every_k_steps=2,
            elastic_block=dict(ELASTIC), guardian=dict(GUARD))
        master_before = jax.device_get(tr.engine.state.master)
        assert tr.step() is None  # overflow -> in-graph skip
        assert tr.skipped_steps == 1
        assert tr.engine.global_steps == 0  # host re-synced to device
        master_after = jax.device_get(tr.engine.state.master)
        assert all(np.array_equal(a, b) for a, b in zip(
            jax.tree.leaves(master_before),
            jax.tree.leaves(master_after)))  # update really skipped
        for _ in range(40):
            if tr.engine.global_steps >= 3:
                break
            tr.step()
        # committed steps number 1..3 with no gap or duplicate, each
        # with exactly one ledger entry; the skipped batches were
        # consumed (reference overflow semantics) but never committed
        assert sorted(tr.history) == [1, 2, 3]
        assert sorted(tr.ledger) == [1, 2, 3]
        # the skips never reached the anomaly window
        assert tr.guardian.skips == tr.skipped_steps >= 1
        assert tr.guardian.trips == 0
        assert tr.guardian.observed == 3

    def test_nonfinite_guard_skips_in_graph_outside_fp16(self):
        import dataclasses

        import jax

        eng = _make_engine(2, integrity={"enabled": True})
        # poison one weight: the loss goes non-finite, so grads do too
        flat, treedef = jax.tree_util.tree_flatten(eng.state.params)
        bad = [np.full(np.shape(l), np.inf, np.asarray(l).dtype)
               if i == 0 else l for i, l in enumerate(flat)]
        eng.state = dataclasses.replace(
            eng.state, params=jax.tree_util.tree_unflatten(treedef, bad))
        before = jax.device_get(eng.state.params)
        batch = {"tokens": np.random.default_rng(0).integers(
            0, 64, (8, 17)).astype(np.int32)}
        metrics = eng.train_batch(batch)
        assert metrics["skipped"] == 1  # found_inf_in_grads tripped
        after = jax.device_get(eng.state.params)
        assert all(np.array_equal(a, b) for a, b in zip(
            jax.tree.leaves(before), jax.tree.leaves(after)))

    def test_guard_off_by_default(self):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig

        assert DeepSpeedTPUConfig().integrity.enabled is False
        with pytest.raises(ValueError):
            DeepSpeedTPUConfig(integrity={"zscore": -1})
        with pytest.raises(ValueError):
            DeepSpeedTPUConfig(integrity={"persistent_trips": 0})


# ---------------------------------------------------------------------------
# gate CLI + committed baseline consistency
# ---------------------------------------------------------------------------

class TestSdcGate:
    def test_committed_baseline_parses_and_matches_plan(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(root, "SDCCHAOS.json")
        assert os.path.exists(path), "SDCCHAOS.json must be committed"
        raw = json.load(open(path))
        plan = FaultPlan.from_dict(raw)
        points = {f.point for f in plan.faults}
        assert {"engine.grads", "mirror.payload",
                "handoff.payload"} <= points
        expect = raw["expect"]
        # the committed ledger asserts 100% detection per flip class
        for cls in ("grad", "mirror", "handoff"):
            assert expect[f"{cls}_flips_detected"] \
                == expect[f"{cls}_flips_injected"] > 0

    def test_default_plan_round_trips(self):
        import bench

        d = bench._default_sdc_chaos_plan()
        plan = FaultPlan.from_dict(d)
        assert FaultPlan.from_dict(plan.to_dict()).to_dict() \
            == plan.to_dict()

    def test_cli_help_exits_zero(self):
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "ds_sdc.py"),
             "--help"], capture_output=True, text=True, timeout=120)
        assert r.returncode == 0
        assert "--capture" in r.stdout and "--strict" in r.stdout
