"""Compile-time cost model: does this program FIT, and what does it move?

The sanitizer (sanitizer.py) verifies *properties* of a compiled program
— donation honored, specs survived, signatures stable. This module
predicts its *costs* before a single step runs on real hardware: peak
HBM per device (args + outputs + temps, donation-credited), collective
byte volume per step, and the roofline balance between flops, HBM
traffic and ICI traffic. All three are static properties of the
compiled artifact (`compiled.memory_analysis()` / `cost_analysis()` +
the profiling/hlo.py HLO parsers) — ground truth, not invocation-side
bookkeeping, in the same discipline as the rest of `analysis/`.

Three checks (findings ride the sanitizer report machinery):

  S004  check_hbm_budget       — peak program HBM exceeds the
        per-device budget of the target topology (chip capacity from
        platform/accelerator.py; sharded entry parameters project to
        meshes larger than the compiling host via their `sharding=`
        annotations).
  S005  check_collective_volume — all-gather bytes exceed k x the live
        sharded-param bytes (the "accidental replication" class: a
        sharded table materialized whole), or per-step comm bytes
        regressed beyond tolerance against a captured baseline.
  S006  check_roofline         — a program the spec declares
        compute-bound compiles comm- or memory-bound (flops vs
        bytes-accessed vs ICI bytes against the chip's peak rates).

Baselines persist to MEMBUDGET.json (scripts/ds_budget.py --capture /
--check, the tier-1 pre-test gate next to ds-lint).
"""

import dataclasses
import json
import os
import re
from typing import Any, Dict, Optional

from ..profiling.hlo import (
    compiled_cost_stats,
    compiled_memory_stats,
    parse_entry_parameters,
    parse_hlo_collectives,
)
from .report import Finding, SanitizerReport

__all__ = [
    "ICI_GBPS",
    "CostReport",
    "build_cost_report",
    "check_hbm_budget",
    "check_collective_volume",
    "check_roofline",
    "check_against_baseline",
    "roofline",
    "load_baseline",
    "save_baseline",
]

# Effective per-chip ICI bandwidth (bytes/s) for the ring-collective
# projection — re-exported from the single link-table authority
# (platform/accelerator.LINKS, shared with scripts/ici_projection.py
# and analysis/schedule.py; tests assert no local re-declaration).
from ..platform.accelerator import LINKS as _LINKS

ICI_GBPS = _LINKS["ici_bytes_per_s"]

_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


@dataclasses.dataclass
class CostReport:
    """Static cost profile of ONE compiled program (per-device view:
    every byte count is what a single device holds or moves)."""

    label: str
    arg_bytes: int = 0
    out_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0          # donated args whose storage outputs reuse
    sharded_arg_bytes: int = 0    # entry params carrying a devices=[...] tile
    replicated_arg_bytes: int = 0
    flops: float = 0.0
    bytes_accessed: float = 0.0
    collectives: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)  # {op: {count, bytes}}
    n_devices: int = 1
    estimated: bool = False       # memory_analysis unavailable: args only
    # schedule-aware projection (analysis/schedule.py S007-S009): the
    # critical-path step time — serial roofline leg + EXPOSED comm only
    # — and its summary ledger. The autotuner's AOT score reads
    # step_time_s; the full ScheduleAnalysis rides the non-field
    # `_schedule` attribute for the checks.
    step_time_s: float = 0.0
    exposed_comm_s: float = 0.0
    schedule: Optional[Dict[str, Any]] = None

    @property
    def peak_hbm_bytes(self) -> int:
        """Resident bytes while the program runs: arguments + outputs +
        scratch, minus the donated storage outputs alias in place."""
        return max(
            0, self.arg_bytes + self.out_bytes + self.temp_bytes
            - self.alias_bytes)

    @property
    def comm_bytes(self) -> int:
        return int(sum(v["bytes"] for v in self.collectives.values()))

    @property
    def all_gather_bytes(self) -> int:
        return int(self.collectives.get("all-gather", {}).get("bytes", 0))

    def projected_arg_bytes(self, target_devices: int) -> int:
        """Per-device argument bytes at a LARGER topology: sharded entry
        parameters keep shrinking with the mesh (per-shard dims scale by
        compiled/target device ratio), replicated parameters do not."""
        scale = self.n_devices / max(1, int(target_devices))
        return int(self.sharded_arg_bytes * scale) + self.replicated_arg_bytes

    def projected_peak_hbm(self, target_devices: int) -> int:
        """Peak HBM projected to `target_devices`. Outputs/temps follow
        the sharded-argument scaling fraction (they are dominated by the
        same tensors); replicated residency is held constant."""
        if self.arg_bytes <= 0:
            return self.peak_hbm_bytes
        frac = self.sharded_arg_bytes / self.arg_bytes
        scale = self.n_devices / max(1, int(target_devices))
        scaled = 1.0 - frac + frac * scale
        rest = self.out_bytes + self.temp_bytes - self.alias_bytes
        return max(0, int(self.projected_arg_bytes(target_devices)
                          + rest * scaled))

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["peak_hbm_bytes"] = self.peak_hbm_bytes
        d["comm_bytes"] = self.comm_bytes
        return d

    def render(self) -> str:
        mb = 1 / 2**20
        return (
            f"cost[{self.label}]: peak {self.peak_hbm_bytes * mb:.1f} MiB "
            f"(args {self.arg_bytes * mb:.1f} | out {self.out_bytes * mb:.1f}"
            f" | temp {self.temp_bytes * mb:.1f} | aliased "
            f"-{self.alias_bytes * mb:.1f}), comm "
            f"{self.comm_bytes * mb:.1f} MiB/step, "
            f"{self.flops / 1e9:.2f} GFLOP"
            + (" [estimated]" if self.estimated else "")
        )


def _is_sharded(sharding: Optional[str]) -> bool:
    """Does a `sharding=` annotation actually tile the value? A bare
    `replicated`/`maximal` (or `devices=[1,1,...]`) holds a full copy."""
    if not sharding or "devices" not in sharding:
        return False
    m = re.search(r"devices=\[([\d,]+)\]", sharding)
    if not m:
        return False
    tile = [int(x) for x in m.group(1).split(",") if x]
    if "last_tile_dim_replicate" in sharding and len(tile) > 1:
        tile = tile[:-1]
    n = 1
    for t in tile:
        n *= t
    return n > 1


def build_cost_report(compiled: Any, label: str = "program",
                      hide_sync_slack: bool = True,
                      ) -> Optional[CostReport]:
    """Cost profile of one compiled program, or None when even the HLO
    text is unavailable. Degrades gracefully: without memory_analysis()
    (some backends) the argument footprint is rebuilt from the entry
    parameters and `estimated` is set.

    hide_sync_slack feeds the schedule analyzer's latency-hiding
    credit (analysis/schedule.py): the engine passes
    `zero_optimization.overlap_comm` here, so an overlap-off engine's
    S009 projection models serialized execution — the overlap-off twin
    ds_schedule commits."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    params = parse_entry_parameters(text)
    sharded = sum(p["nbytes"] for p in params if _is_sharded(p["sharding"]))
    replicated = sum(
        p["nbytes"] for p in params if not _is_sharded(p["sharding"]))
    m = _NUM_PARTITIONS_RE.search(text[: text.find("\n")])
    n_devices = int(m.group(1)) if m else 1

    rep = CostReport(label=label, n_devices=n_devices,
                     sharded_arg_bytes=int(sharded),
                     replicated_arg_bytes=int(replicated))
    mem = compiled_memory_stats(compiled)
    if mem is not None:
        rep.arg_bytes = mem["argument_bytes"]
        rep.out_bytes = mem["output_bytes"]
        rep.temp_bytes = mem["temp_bytes"]
        rep.alias_bytes = mem["alias_bytes"]
        # keep the sharded/replicated split consistent with the backend's
        # total (layout padding makes the parsed sum a slight undercount)
        parsed = sharded + replicated
        if parsed > 0 and rep.arg_bytes > 0:
            ratio = rep.arg_bytes / parsed
            rep.sharded_arg_bytes = int(sharded * ratio)
            rep.replicated_arg_bytes = rep.arg_bytes - rep.sharded_arg_bytes
    else:
        rep.arg_bytes = int(sharded + replicated)
        rep.estimated = True
    cost = compiled_cost_stats(compiled)
    if cost is not None:
        rep.flops = cost["flops"]
        rep.bytes_accessed = cost["bytes_accessed"]
    agg: Dict[str, Dict[str, float]] = {}
    for c in parse_hlo_collectives(text):
        slot = agg.setdefault(c["op"], {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += c["bytes"]
    rep.collectives = agg
    # schedule-aware step-time projection (S007-S009 input + the
    # autotuner's AOT score); never fatal — a backend without
    # cost_analysis still gets the comm-only schedule ledger
    try:
        from ..platform.accelerator import get_accelerator
        from .schedule import analyze_schedule

        try:
            acc = get_accelerator()
            peak, hbm = acc.peak_flops(), acc.hbm_bandwidth()
        except Exception:
            peak, hbm = 1.0, 1.0
        sched = analyze_schedule(
            text, flops=rep.flops, bytes_accessed=rep.bytes_accessed,
            peak_flops=peak, hbm_bandwidth=hbm, n_devices=n_devices,
            label=label, hide_sync_slack=hide_sync_slack)
    except Exception:
        sched = None
    if sched is not None:
        rep.step_time_s = sched.step_time_s
        rep.exposed_comm_s = sched.exposed_s
        rep.schedule = sched.to_dict()
        rep._schedule = sched
    return rep


# ----------------------------------------------------------------------
# check S004: per-device HBM budget
# ----------------------------------------------------------------------

def check_hbm_budget(
    report: CostReport,
    budget_bytes: Optional[int] = None,
    target_devices: Optional[int] = None,
    label: Optional[str] = None,
) -> SanitizerReport:
    """One S004 error when the program's peak HBM footprint exceeds the
    per-device budget. budget_bytes defaults to the running chip's HBM
    capacity (platform/accelerator.py). target_devices projects the
    footprint to a mesh larger than the compiling host: sharded entry
    parameters keep shrinking, replicated residency does not — exactly
    the term that OOMs a "it fit on 8 devices" program at scale."""
    label = label or report.label
    out = SanitizerReport(label=f"{label}/hbm_budget")
    if budget_bytes is None:
        from ..platform.accelerator import get_accelerator

        budget_bytes = get_accelerator().hbm_per_device()
    if target_devices is None or target_devices == report.n_devices:
        peak, where = report.peak_hbm_bytes, f"{report.n_devices} device(s)"
    else:
        peak = report.projected_peak_hbm(target_devices)
        where = (f"projected {target_devices} devices "
                 f"(compiled on {report.n_devices})")
    if peak > budget_bytes:
        gib = 1 / 2**30
        out.findings.append(Finding(
            rule="S004", path=label, line=0, severity="error",
            message=(
                f"peak HBM {peak * gib:.2f} GiB at {where} exceeds the "
                f"per-device budget {budget_bytes * gib:.2f} GiB "
                f"(args {report.arg_bytes * gib:.2f} + out "
                f"{report.out_bytes * gib:.2f} + temp "
                f"{report.temp_bytes * gib:.2f} - aliased "
                f"{report.alias_bytes * gib:.2f}; replicated residency "
                f"{report.replicated_arg_bytes * gib:.2f} GiB does not "
                "shrink with the mesh)"),
            fix_hint=(
                "shard the replicated state (zero stage / TP specs), "
                "donate large buffers so outputs alias, or lower the "
                "batch/sequence buckets"),
        ))
    return out


# ----------------------------------------------------------------------
# check S005: collective-volume blowups
# ----------------------------------------------------------------------

def check_collective_volume(
    report: CostReport,
    live_sharded_bytes: Optional[int] = None,
    k: float = 4.0,
    baseline: Optional[Dict[str, Any]] = None,
    tolerance: float = 0.10,
    label: Optional[str] = None,
) -> SanitizerReport:
    """S005: (a) accidental replication — the program's all-gather bytes
    exceed k x the live sharded-param bytes it could legitimately need
    to materialize per step (a sharded table gathered whole, or gathered
    once per consumer instead of once); (b) comm regression — per-step
    collective bytes grew more than `tolerance` over a captured baseline
    entry ({"comm_bytes": N}, see save_baseline)."""
    label = label or report.label
    out = SanitizerReport(label=f"{label}/collective_volume")
    ag = report.all_gather_bytes
    if live_sharded_bytes and ag > k * live_sharded_bytes:
        mb = 1 / 2**20
        out.findings.append(Finding(
            rule="S005", path=label, line=0, severity="error",
            message=(
                f"all-gather moves {ag * mb:.1f} MiB/step — "
                f"{ag / live_sharded_bytes:.1f}x the {live_sharded_bytes * mb:.1f} "
                f"MiB of live sharded params (allowed {k:.1f}x): a sharded "
                "value is being materialized replicated (accidental "
                "full-gather)"),
            fix_hint=(
                "keep the consumer sharded (with_sharding_constraint per "
                "parallel/sharding.py), or gather once and reuse — diff "
                "collective_volumes() against the expected gather set"),
        ))
    if baseline:
        base = float(baseline.get("comm_bytes", 0))
        if base > 0 and report.comm_bytes > base * (1.0 + tolerance):
            out.findings.append(Finding(
                rule="S005", path=label, line=0, severity="error",
                message=(
                    f"per-step collective volume regressed: "
                    f"{report.comm_bytes / 2**20:.1f} MiB vs baseline "
                    f"{base / 2**20:.1f} MiB "
                    f"(+{100 * (report.comm_bytes / base - 1):.1f}% > "
                    f"{100 * tolerance:.0f}% tolerance)"),
                fix_hint=(
                    "inspect collective_volumes() per op kind; re-capture "
                    "the baseline (scripts/ds_budget.py --capture) only if "
                    "the growth is intended"),
            ))
    return out


# ----------------------------------------------------------------------
# check S006: roofline balance
# ----------------------------------------------------------------------

def roofline(
    report: CostReport,
    peak_flops: float,
    hbm_bandwidth: float,
    ici_bandwidth: float = ICI_GBPS,
) -> Dict[str, float]:
    """Per-leg lower-bound times for one program and its binding leg.

    t_flops = flops / peak, t_hbm = bytes_accessed / HBM bandwidth,
    t_ici = collective bytes / ICI bandwidth. `bound` is the largest
    leg; `intensity` is flops per HBM byte (classic roofline x-axis)."""
    t_flops = report.flops / max(peak_flops, 1.0)
    t_hbm = report.bytes_accessed / max(hbm_bandwidth, 1.0)
    t_ici = report.comm_bytes / max(ici_bandwidth, 1.0)
    legs = {"compute": t_flops, "memory": t_hbm, "comm": t_ici}
    bound = max(legs, key=legs.get)
    return {
        "t_flops": t_flops, "t_hbm": t_hbm, "t_ici": t_ici,
        "bound": bound,
        "intensity": report.flops / max(report.bytes_accessed, 1.0),
    }


def check_roofline(
    report: CostReport,
    peak_flops: Optional[float] = None,
    hbm_bandwidth: Optional[float] = None,
    ici_bandwidth: float = ICI_GBPS,
    expect: str = "compute",
    comm_only: bool = False,
    label: Optional[str] = None,
) -> SanitizerReport:
    """S006: the program compiles with a different binding leg than the
    spec declares (`expect`: compute|memory|comm). comm_only=True flags
    only the comm-bound case — the right setting for small verification
    slices, which are legitimately memory-bound at toy sizes but should
    NEVER be dominated by collective traffic."""
    label = label or report.label
    out = SanitizerReport(label=f"{label}/roofline")
    if peak_flops is None or hbm_bandwidth is None:
        from ..platform.accelerator import get_accelerator

        acc = get_accelerator()
        peak_flops = peak_flops or acc.peak_flops()
        hbm_bandwidth = hbm_bandwidth or acc.hbm_bandwidth()
    if report.flops <= 0 and report.bytes_accessed <= 0:
        return out  # no cost_analysis on this backend: nothing to judge
    r = roofline(report, peak_flops, hbm_bandwidth, ici_bandwidth)
    if r["bound"] == expect or (comm_only and r["bound"] != "comm"):
        return out
    out.findings.append(Finding(
        rule="S006", path=label, line=0, severity="warning",
        message=(
            f"program compiles {r['bound']}-bound but is declared "
            f"{expect}-bound: t_flops {r['t_flops']:.2e}s, t_hbm "
            f"{r['t_hbm']:.2e}s, t_ici {r['t_ici']:.2e}s (arithmetic "
            f"intensity {r['intensity']:.1f} flop/byte)"),
        fix_hint=(
            "comm-bound: cut collective volume (S005 diagnoses which op); "
            "memory-bound: raise arithmetic intensity (fuse, batch, "
            "larger tiles) or accept and re-declare the spec"),
    ))
    return out


# ----------------------------------------------------------------------
# baseline persistence (MEMBUDGET.json / scripts/ds_budget.py)
# ----------------------------------------------------------------------

def load_baseline(path: str) -> Optional[Dict[str, Any]]:
    """The MEMBUDGET.json document, or None when absent/unreadable."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def save_baseline(
    path: str,
    programs: Dict[str, CostReport],
    budgets: Optional[Dict[str, Any]] = None,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Write a MEMBUDGET.json baseline: one entry per program with the
    regression-gated scalars, plus the budget block --check enforces."""
    doc = {
        "schema": 1,
        **(meta or {}),
        "budgets": {"hbm_regression_tolerance": 0.10, **(budgets or {})},
        "programs": {
            name: {
                "peak_hbm_bytes": rep.peak_hbm_bytes,
                "arg_bytes": rep.arg_bytes,
                "out_bytes": rep.out_bytes,
                "temp_bytes": rep.temp_bytes,
                "alias_bytes": rep.alias_bytes,
                "comm_bytes": rep.comm_bytes,
                "flops": rep.flops,
                "n_devices": rep.n_devices,
            }
            for name, rep in programs.items()
        },
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def check_against_baseline(
    report: CostReport,
    baseline_entry: Dict[str, Any],
    tolerance: float = 0.10,
    label: Optional[str] = None,
) -> SanitizerReport:
    """S004 regression form: peak HBM grew more than `tolerance` over
    the captured baseline entry (the ds_budget.py --check gate — a PR
    that quietly inflates a step's footprint fails like a lint
    finding). Comm regressions ride check_collective_volume."""
    label = label or report.label
    out = SanitizerReport(label=f"{label}/baseline")
    base = float(baseline_entry.get("peak_hbm_bytes", 0))
    if base > 0 and report.peak_hbm_bytes > base * (1.0 + tolerance):
        out.findings.append(Finding(
            rule="S004", path=label, line=0, severity="error",
            message=(
                f"peak HBM regressed: {report.peak_hbm_bytes / 2**20:.1f} "
                f"MiB vs baseline {base / 2**20:.1f} MiB "
                f"(+{100 * (report.peak_hbm_bytes / base - 1):.1f}% > "
                f"{100 * tolerance:.0f}% tolerance)"),
            fix_hint=(
                "find the new residency (args/out/temp breakdown in the "
                "cost report); re-capture with scripts/ds_budget.py "
                "--capture only if the growth is intended"),
        ))
    return out
