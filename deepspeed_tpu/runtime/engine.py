"""Training engine.

TPU-native redesign of the reference core engine
(ref: runtime/engine.py DeepSpeedEngine:180 — forward:1791,
backward:1933, step:2132, allreduce_gradients:1913, checkpointing
:3064/:2700). The reference splits a training step across three eager
calls with hook machinery between them; here the whole thing —
gradient-accumulation loop, loss scaling, grad clipping, ZeRO
reduce-scatter/all-gather, optimizer update, LR schedule — is ONE
compiled SPMD program per step (`train_batch`). Collectives are not
issued by Python; they fall out of the sharding specs derived in
`zero.py` and the XLA SPMD partitioner.

State lives as a `TrainState` pytree of sharded global arrays:
  params  — compute/storage dtype (bf16 recommended), replicated over
            'data' (stage<3) or sharded (stage 3)
  master  — fp32 master copy, 'data'-sharded for stage>=1
            (ref: bf16_optimizer.py fp32 partitioned master)
  opt     — optimizer moments, sharded like master
            (ref: stage_1_and_2.py optimizer-state partitioning)
"""

import contextlib
import dataclasses
import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.config import DeepSpeedTPUConfig
from ..comm.logger import comms_logger
from ..monitor.monitor import MonitorMaster
from ..ops.optimizers import Optimizer, build_optimizer
from ..parallel import sharding as shd
from ..platform.mesh import build_mesh, data_parallel_size, describe, use_mesh
from ..resilience.faults import fault_point
from ..utils.logging import log_dist, logger
from ..utils.timers import BATCH_TIMER, STEP_TIMER, SynchronizedWallClockTimer, ThroughputTimer
from . import overlap, zero
from .checkpoint import CheckpointEngine
from .lr_schedules import build_schedule
from .precision import (
    LossScaleState,
    cast_params,
    clip_grads_by_global_norm,
    found_inf_in_grads,
    global_grad_norm,
    init_loss_scale,
    update_loss_scale,
)


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["step", "params", "master", "opt", "loss_scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class TrainState:
    step: Any
    params: Any
    master: Any  # None when params are already fp32 (then params ARE master)
    opt: Any
    loss_scale: Any  # LossScaleState or None


class DeepSpeedTPUEngine:
    """Engine over a (loss_fn, params) pair.

    loss_fn(params, batch, rng) -> loss  (scalar, mean over the batch)
    or -> (loss, aux_dict).
    """

    def __init__(
        self,
        config: DeepSpeedTPUConfig,
        loss_fn: Callable,
        params: Any,
        param_logical_specs: Any = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[Dict[str, Any]] = None,
        has_aux: bool = False,
        param_init_fn: Optional[Callable] = None,
        init_rng: Optional[Any] = None,
        pipelined: bool = False,
        pipeline_virtual_stages: Optional[int] = None,
    ):
        """`params` is either a concrete pytree, or (with `param_init_fn`)
        a pytree of ShapeDtypeStructs — then params are materialized
        *directly sharded* by running init under jit with out_shardings,
        the functional zero.Init (ref: partition_parameters.py Init:780).

        pipelined=True declares a pipeline-parallel loss_fn (e.g.
        models.transformer.make_pipelined_loss_fn): it receives the WHOLE
        [gas, micro_batch, ...] batch in one call and runs the microbatch
        loop itself through the stage-sharded layer stack
        (runtime/pipe.py) — the PipelineEngine analog
        (ref: runtime/pipe/engine.py:55).

        pipeline_virtual_stages: the interleave degree v of a circular
        [v, P, lc, ...] layer stack. Declare it whenever v > 1 — the
        checkpoint meta records it and universal-checkpoint conversion
        depends on it; shape inference alone cannot distinguish v == P
        stacks from plain [P, L/P, ...] ones (r3 advisor finding)."""
        self.config = config
        self.loss_fn = loss_fn
        self.has_aux = has_aux
        self.pipelined = pipelined
        self._pipe_virtual = (int(pipeline_virtual_stages)
                              if pipeline_virtual_stages else None)
        axis_sizes = config.mesh.axis_sizes()
        hpz = config.zero_optimization.zero_hpz_partition_size
        if hpz and hpz > 1:
            # hpZ/MiCS: factor the data dimension into data×zero so ZeRO
            # shards within the sub-group and replicates across groups
            # (ref: zero/mics.py:64; zero_hpz_partition_size config.py:264).
            if axis_sizes.get("zero", 1) not in (1, hpz):
                raise ValueError(
                    f"mesh.zero={axis_sizes['zero']} conflicts with "
                    f"zero_hpz_partition_size={hpz}"
                )
            axis_sizes["zero"] = hpz
            if axis_sizes.get("data", -1) > 0:
                if axis_sizes["data"] % hpz:
                    raise ValueError(
                        f"data axis {axis_sizes['data']} not divisible by "
                        f"zero_hpz_partition_size {hpz}"
                    )
                axis_sizes["data"] //= hpz
        self.mesh = mesh if mesh is not None else build_mesh(axis_sizes)
        if self.mesh.shape.get("pipe", 1) > 1 and not pipelined:
            # Devices on a pipe axis would hold replicated params and
            # receive no batch shard — fail loudly (VERDICT r1 W3).
            raise NotImplementedError(
                "mesh {pipe: >1} requires a pipelined loss "
                "(models.transformer.make_pipelined_loss_fn + "
                "initialize(..., pipelined=True)) or folding pipe into "
                "data/model axes"
            )
        self.dp_world_size = data_parallel_size(self.mesh)
        if config.elasticity.enabled:
            # derive the batch triangle from the elastic config + current
            # device count (ref: engine._set_batch_related_parameters under
            # DEEPSPEED_ELASTICITY_CONFIG; resize = rebuild mesh + reshard
            # checkpoint, no agent restart needed on TPU)
            from ..elasticity import compute_elastic_config

            if (
                not config.elasticity.ignore_non_elastic_batch_info
                and (config.train_batch_size is not None
                     or config.train_micro_batch_size_per_gpu is not None
                     or config.gradient_accumulation_steps is not None)
            ):
                raise ValueError(
                    "elasticity is enabled but the config also pins batch "
                    "sizes / gradient_accumulation_steps; remove them or "
                    "set ignore_non_elastic_batch_info"
                )
            batch, _valid, micro = compute_elastic_config(
                {"elasticity": config.elasticity.model_dump()},
                world_size=self.dp_world_size,
            )
            config.train_batch_size = batch
            config.train_micro_batch_size_per_gpu = micro
            config.gradient_accumulation_steps = None
        config.resolve_batch_sizes(self.dp_world_size)
        log_dist(
            f"engine: {describe(self.mesh)} | zero stage {config.zero_stage} | "
            f"batch {config.train_batch_size} = micro {config.train_micro_batch_size_per_gpu}"
            f" x gas {config.gradient_accumulation_steps} x dp {self.dp_world_size}",
            ranks=[0],
        )

        comms_logger.configure(config.comms_logger.enabled, config.comms_logger.verbose)

        self.compute_dtype = config.compute_dtype
        self._fp32 = self.compute_dtype == jnp.float32
        self._use_master = (not self._fp32) and (
            config.bf16.master_weights if config.bf16.enabled else True
        )

        # ZeRO-Offload/Infinity: optimizer state + fp32 master in host
        # DRAM or NVMe (ref: stage_1_and_2.py cpu_offload,
        # csrc/adam/cpu_adam.cpp, runtime/swap_tensor/ + csrc/aio).
        off_device = config.zero_optimization.offload_optimizer.device
        self._offload = off_device in ("cpu", "nvme")
        self._offload_nvme = off_device == "nvme"
        # ZeRO-Infinity param tier: compute-dtype params parked in host DRAM
        # between steps (memory_kind='pinned_host') and streamed into HBM
        # inside the compiled step — XLA's latency-hiding scheduler overlaps
        # the H2D fetch with compute (ref: runtime/zero/
        # partitioned_param_coordinator.py fetch/release + aio param swap;
        # config gate guarantees stage 3).
        self._offload_param = (
            config.zero_optimization.offload_param.device == "cpu"
        )
        # offload_param=nvme: params resident NOWHERE between steps —
        # re-materialized from the swap files' master sections each step
        # (full ZeRO-Infinity; requires the optimizer tier on NVMe, whose
        # files already hold the authoritative fp32 masters).
        self._offload_param_nvme = (
            config.zero_optimization.offload_param.device == "nvme"
        )
        if self._offload_param_nvme and not self._offload_nvme:
            raise NotImplementedError(
                "offload_param.device=nvme requires "
                "offload_optimizer.device=nvme (params re-materialize from "
                "the optimizer tier's swap files)"
            )
        if self._offload:
            if config.fp16.enabled:
                raise NotImplementedError(
                    "offload_optimizer with fp16 dynamic loss scaling is not "
                    "implemented; use bf16 (the TPU-native precision)"
                )
            # cpu: the host tier holds the fp32 authoritative copy inside
            # TrainState; nvme: master+moments live in swap files OUTSIDE
            # TrainState (state.master/opt stay None)
            self._use_master = not self._offload_nvme

        # --- sharding derivation (the ZeRO core; pipeline x ZeRO x TP
        # compose through one emitter, parallel/sharding.pipe3d_specs) --
        shapes = jax.tree.map(lambda p: tuple(p.shape), params)
        zcfg = config.zero_optimization
        if param_logical_specs is None:
            tp_specs = jax.tree.map(lambda p: P(), params)
            combined = {
                "tp": tp_specs,
                "storage": zero.derive_param_storage_specs(
                    tp_specs, shapes, self.mesh, zcfg),
                "opt": zero.derive_optimizer_specs(
                    tp_specs, shapes, self.mesh, zcfg),
            }
            combined["grads"] = zero.derive_grad_specs(
                combined["storage"], combined["opt"], zcfg)
        else:
            combined = shd.pipe3d_specs(
                param_logical_specs, shapes, self.mesh, zcfg, rules)
        self.tp_specs = combined["tp"]
        self.param_specs = combined["storage"]
        self.opt_specs = combined["opt"]
        self.grad_specs = combined["grads"]
        zero.validate_no_conflicts(self.param_specs)
        zero.validate_no_conflicts(self.opt_specs)
        # ZeRO++ qwZ: int8-quantized weight all-gather for zero-sharded
        # leaves (ref: zeropp.md qwZ; partition_parameters.py:725).
        self._qwz_apply = (
            zero.make_qwz_gather(self.param_specs, self.tp_specs, shapes,
                                 self.mesh)
            if zcfg.zero_quantized_weights
            else None
        )
        # compression training (ref: compression/compress.py:100
        # init_compression — here a param transform composed into the loss)
        if config.compression_training:
            from ..compression import build_compression

            if config.optimizer.type.lower().replace("_", "") in (
                "onebitadam", "onebitlamb",
            ):
                raise NotImplementedError(
                    "compression_training with 1-bit optimizers is not supported"
                )
            if zcfg.zero_quantized_gradients:
                # the qgZ worker-gradient path bypasses the compression
                # transform — refuse rather than silently train uncompressed
                raise NotImplementedError(
                    "compression_training with zero_quantized_gradients is "
                    "not supported"
                )
            self._compression = build_compression(config.compression_training)
        else:
            self._compression = None

        # ZeRO++ qgZ: per-worker grads reduced through the int8 two-hop
        # quantized exchange (ref: coalesced_collectives.py:31).
        self._qgz = zcfg.zero_quantized_gradients
        if self._qgz:
            if zcfg.stage > 2:
                raise NotImplementedError(
                    "zero_quantized_gradients needs params replicated over "
                    "the data axes (zero stage <= 2)"
                )
            if config.fp16.enabled:
                # the worker-partial path doesn't thread the loss scale
                raise NotImplementedError(
                    "zero_quantized_gradients does not compose with fp16; "
                    "use bf16"
                )
            # pipeline: the worker accumulator runs the pipelined loss
            # whole-batch with 'pipe' auto; expert: the expert-axis grad
            # reduction happens natively inside the worker shard (auto
            # psum), the compressed hop covers the data axes — both
            # compose (r3 VERDICT item 6)

        # --- optimizer / schedule / scaler ------------------------------
        opt_block = config.optimizer
        opt_params = dict(opt_block.params)
        opt_key = opt_block.type.lower().replace("_", "")
        self._onebit = opt_key in ("onebitadam", "onebitlamb")
        # 0/1 Adam shares the worker-partial-gradient machinery and all of
        # the 1-bit composition restrictions (ref: onebit/zoadam.py).
        self._zoadam = opt_key in ("zerooneadam", "zoadam")
        if self._onebit or self._zoadam:
            # 1-bit Adam needs per-worker partial gradients (params
            # replicated over the data axes) — ref: onebit/adam.py is
            # likewise an FP16_Optimizer-path feature, not a ZeRO one.
            # 1-bit × ZeRO-1 composes here (master+nu shard over 'zero';
            # mu/error memories stay replicated — see _build_onebit_step);
            # higher stages shard grads/params, which the compression hop
            # fundamentally conflicts with.
            max_stage = 1 if self._onebit else 0
            if config.zero_stage > max_stage:
                raise NotImplementedError(
                    f"{'1-bit Adam supports zero stages 0-1' if self._onebit else '0/1 Adam requires zero stage 0'}"
                )
            if config.fp16.enabled:
                raise NotImplementedError("1-bit Adam: use bf16, not fp16")
            # pipeline/expert compose through the worker accumulator's
            # pipelined whole-batch branch / auto expert reduction (see
            # the qgZ note above)
            if config.gradient_clipping > 0:
                # clipping needs the exact global grad norm, whose reduction
                # the compression phase exists to avoid (the reference 1-bit
                # optimizers don't clip either) — raise, don't silently stop
                # clipping at freeze_step
                raise NotImplementedError(
                    "gradient_clipping is not supported with 1-bit Adam"
                )
            if config.zero_optimization.offload_optimizer.device != "none":
                # the offload dispatch path would bypass the compression
                # phase entirely — refuse rather than silently run plain Adam
                raise NotImplementedError(
                    "1-bit Adam does not compose with offload_optimizer"
                )
            opt_params["dp"] = int(
                self.mesh.shape["data"] * self.mesh.shape["zero"]
            )
        self.optimizer: Optimizer = build_optimizer(opt_block.type, opt_params)
        if self._zoadam:
            # host-side replica of the deterministic 0/1 Adam schedule
            self._zo_sched = self.optimizer.make_schedule()
            self._zo_programs: Dict[str, Any] = {}
            self._zo_transitioned = False
        base_lr = float(opt_block.params.get("lr", 1e-3))
        self.lr_schedule = build_schedule(
            config.scheduler.type, config.scheduler.params, base_lr=base_lr
        )
        if self._offload_nvme:
            from .swap import NVMeOptimizerSwapper

            nvme_path = config.zero_optimization.offload_optimizer.nvme_path
            if not nvme_path:
                raise ValueError(
                    "offload_optimizer.device=nvme requires nvme_path"
                )
            self.swapper = NVMeOptimizerSwapper(
                self.optimizer, self.lr_schedule, config.gradient_clipping,
                self.compute_dtype, nvme_path,
                n_threads=config.aio.thread_count,
                block_size=config.aio.block_size,
            )
        elif self._offload:
            from .offload import HostOptimizer

            self.host_optimizer = HostOptimizer(
                self.optimizer, self.lr_schedule, config.gradient_clipping,
                self.compute_dtype,
            )

        # --- build sharded state -----------------------------------------
        self._rng_seed = config.seed
        if param_init_fn is not None and init_rng is None:
            init_rng = jax.random.PRNGKey(config.seed)
        self.state = self._init_state(params, param_init_fn, init_rng)

        # --- compiled step cache -----------------------------------------
        self._train_step_fn = None
        self._train_compiled = None  # most recent AOT step (profiling source)
        self._train_compiled_cache: Dict[Any, Any] = {}  # per batch-shape key
        self._eval_step_fn = None
        self._grad_step_fn = None
        # classifies every AOT-cache miss (weak-type drift, shape churn,
        # ...) — surfaced by sanitize() (analysis/sanitizer.py)
        from ..analysis.sanitizer import RecompileTracker

        self._recompile_tracker = RecompileTracker()

        # --- observability ------------------------------------------------
        # flops profiler from XLA cost analysis (ref: profiling/
        # flops_profiler/profiler.py:28; VERDICT r1 missing item 6)
        if config.flops_profiler.enabled:
            from ..profiling.flops_profiler import FlopsProfiler

            self.flops_profiler = FlopsProfiler(
                config.flops_profiler, batch_size=config.train_batch_size
            )
        else:
            self.flops_profiler = None
        # set by callers that know the model's analytic flops (e.g.
        # TransformerConfig.flops_per_token * tokens) for MFU reporting
        self.model_flops_per_step: Optional[float] = None

        self.timers = SynchronizedWallClockTimer()
        self.tput = ThroughputTimer(batch_size=config.train_batch_size)
        self.monitor = MonitorMaster(config.monitor)
        self.global_steps = 0
        self._metrics_host: Dict[str, float] = {}
        # chaos accounting (resilience/faults.py 'engine.step' point):
        # injected straggler time accrues here for the driver to charge
        # (virtual clocks) or sleep (real runs); disk_restores counts
        # load_checkpoint calls — the peer-redundant recovery path
        # (elasticity/trainer.py) gates on it staying zero
        self.fault_delay_s = 0.0
        self.disk_restores = 0
        # per-stage injected boundary-comm delay (the 'pipe.permute'
        # guarded fault point, comm.pipe_permute_tick) — the per-stage
        # step-time-skew feed of monitor.training_events reads it
        self.pipe_stage_delay_s: Dict[int, float] = {}

        # elastic-agent integration (ref: elasticity/elastic_agent.py:28
        # DSElasticAgent): when launched under run_elastic, beat the
        # heartbeat each step and watch peers — a dead host must be seen
        # BEFORE the next collective (XLA collectives never time out)
        from ..elasticity.agent import HealthMonitor, heartbeat_from_env

        self._heartbeat = heartbeat_from_env(jax.process_index())
        self._health_monitor = None
        if self._heartbeat is not None and jax.process_count() > 1:
            self._health_monitor = HealthMonitor(
                self._heartbeat.dir, jax.process_index(),
                jax.process_count(),
                timeout_s=float(os.environ.get(
                    "DS_ELASTIC_HEARTBEAT_TIMEOUT_S", "60")),
                generation=self._heartbeat.generation,
            ).start()

        if config.nebula.enabled:
            # tiered fast/durable checkpointing (ref: nebula engine role)
            from .checkpoint import TieredCheckpointEngine

            ncfg = config.nebula
            self.checkpoint_engine = TieredCheckpointEngine(
                persistent_storage_path=ncfg.persistent_storage_path,
                persistent_time_interval=ncfg.persistent_time_interval,
                num_of_version_in_retention=ncfg.num_of_version_in_retention,
                load_path=ncfg.load_path,
                enable_tier_load=ncfg.enable_nebula_load,
                async_save=True,
            )
        else:
            self.checkpoint_engine = CheckpointEngine(
                async_save=config.checkpoint.async_save
            )

        if config.progressive_layer_drop.enabled:
            # PLD rides the fused/offload gradient paths (theta needs the
            # step; the worker-partial paths don't thread it)
            if pipelined or self._onebit or self._zoadam or self._qgz:
                raise NotImplementedError(
                    "progressive_layer_drop does not compose with "
                    "pipeline/1-bit/0-1-Adam/qgZ gradient paths"
                )

        # curriculum learning (ref: runtime/data_pipeline/
        # curriculum_scheduler.py wired at engine.py train-batch level).
        # 'seqlen' truncates each batch to the scheduled length; ANY
        # other metric name routes through the analyzer-built difficulty
        # index (runtime/data_analyzer.CurriculumDataSampler) — the
        # engine samples the batch instead of reshaping it
        # (train_batch_with_curriculum).
        self.curriculum = None
        self.curriculum_sampler = None
        if config.curriculum_learning.enabled:
            from .data_pipeline import CurriculumScheduler

            if config.curriculum_learning.curriculum_type == "seqlen":
                self.curriculum = CurriculumScheduler(
                    config.curriculum_learning.model_dump()
                )
            else:
                from .data_analyzer import build_curriculum_sampler

                name = config.curriculum_learning.curriculum_type
                de = config.data_efficiency
                declared = list(
                    dict(de.data_sampling.get("curriculum_learning", {}))
                    .get("curriculum_metrics", {})
                ) if de.enabled else []
                if name not in declared:
                    raise ValueError(
                        f"curriculum_type={name!r} needs the analyzer-built "
                        "metric index: configure data_efficiency."
                        "data_sampling.curriculum_learning.curriculum_metrics"
                        f".{name} (run DataAnalyzer first; declared: "
                        f"{declared})"
                    )
                self.curriculum_sampler = build_curriculum_sampler(
                    config, global_batch_size=config.train_batch_size
                )

    # ------------------------------------------------------------------
    # param storage tier helpers (ZeRO-Infinity offload_param)
    # ------------------------------------------------------------------
    def _param_storage_sharding(self, spec) -> NamedSharding:
        """Where state.params live between steps: HBM, or host DRAM when
        offload_param is on (same PartitionSpec either way — the host tier
        is still sharded per-process on multihost)."""
        s = NamedSharding(self.mesh, spec)
        if not self._offload_param:
            return s
        try:
            return s.with_memory_kind("pinned_host")
        except ValueError:
            # backend without a pinned_host space (CPU, jax 0.4.x): the
            # default memory IS host memory there, so the tier placement
            # is already what offload_param asks for
            return s

    def _make_param_fetch(self):
        """Returns an inside-jit H2D fetch of the host-parked param tree
        (identity when params already live in HBM)."""
        if not self._offload_param:
            return lambda params: params
        mesh, specs = self.mesh, self.param_specs

        def fetch(params):
            return jax.tree.map(
                lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
                params,
                specs,
            )

        return fetch

    def _park_params(self, state: TrainState) -> TrainState:
        """D2H park of updated params back into the host tier, OUTSIDE the
        compiled step (the XLA SPMD partitioner rejects device→pinned_host
        placement annotations in-program; the transfer still overlaps the
        next step's dispatch via JAX async dispatch)."""
        if not self._offload_param:
            return state
        return dataclasses.replace(
            state,
            params=jax.tree.map(
                lambda p, s: jax.device_put(p, self._param_storage_sharding(s)),
                state.params,
                self.param_specs,
            ),
        )

    # ------------------------------------------------------------------
    # state construction ("zero.Init" analog, functional:
    # ref: partition_parameters.py Init:780 — here params are placed
    # sharded by jit out_shardings instead of patched __init__s)
    # ------------------------------------------------------------------
    def _init_state(self, params, param_init_fn=None, init_rng=None) -> TrainState:
        if self._offload:
            return self._init_state_offload(params, param_init_fn, init_rng)
        mesh = self.mesh
        p_shd = shd.tree_shardings(self.param_specs, mesh)
        o_shd = shd.tree_shardings(self.opt_specs, mesh)

        def make(arg):
            params = param_init_fn(arg) if param_init_fn is not None else arg
            params_f32 = cast_params(params, jnp.float32)
            master = cast_params(params_f32, jnp.float32) if self._use_master else None
            stored = cast_params(params_f32, self.compute_dtype)
            opt = self.optimizer.init(params_f32)
            ls = init_loss_scale(self.config.fp16) if self.config.fp16.enabled else None
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=stored,
                master=master,
                opt=opt,
                loss_scale=ls,
            )

        # Optimizer state is a dict of moment buffers, each with the param
        # tree's structure and shapes → each inherits the opt shardings.
        abstract_params = jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
        )
        opt_struct = jax.eval_shape(lambda p: self.optimizer.init(p), abstract_params)
        opt_shardings = {}
        for k in opt_struct.keys():
            if k.startswith(("error_", "worker_")):
                # 1-bit/0-1 worker-major leaves: dim 0 over the data axes
                opt_shardings[k] = jax.tree.map(
                    lambda _: NamedSharding(mesh, P(("data", "zero"))),
                    opt_struct[k],
                )
            elif k == "mu" and self._onebit and self.config.zero_stage >= 1:
                # 1-bit × ZeRO-1: momentum stays replicated — the local
                # accumulation b1*mu + (1-b1)*g_w needs the full tree on
                # every worker, and sharding it would re-introduce an
                # fp32 allgather per step (master + nu still shard)
                opt_shardings[k] = shd.tree_shardings(self.param_specs, mesh)
            else:
                opt_shardings[k] = o_shd
        # every step program constrains its opt/master outputs to this
        # layout, so (a) phase-switching optimizers (1-bit warmup →
        # compressed) never see a layout drift XLA chose for one program
        # but not the other, and (b) the update math stays SHARDED with
        # the ZeRO layout instead of gathering fp32 state
        self._opt_state_shardings = opt_shardings
        # the fp32 update's natural layout (ZeRO shards) — used by the
        # finalizer to pin the compute-dtype cast BEFORE the param
        # regather even when no master is stored
        self._master_shardings = o_shd
        out_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=p_shd,
            master=o_shd if self._use_master else None,
            opt=opt_shardings,
            loss_scale=(
                LossScaleState(
                    scale=NamedSharding(mesh, P()),
                    good_steps=NamedSharding(mesh, P()),
                    hysteresis_left=NamedSharding(mesh, P()),
                )
                if self.config.fp16.enabled
                else None
            ),
        )
        arg = init_rng if param_init_fn is not None else params
        with jax.transfer_guard("allow"), use_mesh(mesh):
            state = jax.jit(make, out_shardings=out_shardings)(arg)
        # park the freshly initialized params in the host tier (no-op
        # unless offload_param; steady-state parking happens the same way
        # after every compiled step — see _park_params)
        return self._park_params(state)

    def _init_state_offload(self, params, param_init_fn, init_rng) -> TrainState:
        """Offload init runs ON the host: the fp32 master materializes in
        host DRAM (bit-identical to device init — jax.random is
        platform-invariant) and only the compute-dtype cast ships to the
        mesh; fp32 optimizer state never touches HBM."""
        from .offload import host_device

        mesh = self.mesh
        cpu = host_device()
        arg = init_rng if param_init_fn is not None else params
        arg = jax.tree.map(lambda x: jax.device_put(x, cpu), arg)

        def make_master(a):
            p = param_init_fn(a) if param_init_fn is not None else a
            return cast_params(p, jnp.float32)

        master_host = jax.jit(make_master)(arg)
        stored_host = jax.jit(
            lambda m: cast_params(m, self.compute_dtype)
        )(master_host)
        if self._offload_param_nvme:
            params_dev = None  # swap files are the only resident copy
        else:
            params_dev = jax.tree.map(
                lambda x, s: jax.device_put(x, self._param_storage_sharding(s)),
                stored_host,
                self.param_specs,
            )
        step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
        state = TrainState(
            step=step, params=params_dev, master=None, opt=None, loss_scale=None
        )
        if self._offload_nvme:
            self.swapper.init_state(master_host)  # → swap files
        else:
            master, opt = self.host_optimizer.init_state(master_host)
            state = dataclasses.replace(state, master=master, opt=opt)
        return state

    # ------------------------------------------------------------------
    # the compiled train step
    # ------------------------------------------------------------------
    def _remat_wrapped_loss_fn(self):
        """The user loss_fn with the config-driven remat policy applied.

        Activation checkpointing (ref: runtime/activation_checkpointing/
        checkpointing.py:989 — there a wrapper around user-chosen module
        calls; here a policy on the whole compiled micro-step, composing
        with any model-internal per-layer remat). Shared by every
        gradient path: fused, offload, and the per-worker (qgZ/1-bit)
        accumulators."""
        loss_fn = self.loss_fn
        ac = self.config.activation_checkpointing
        if ac.policy != "none":
            if ac.cpu_checkpointing:
                # saved dot outputs live in host DRAM between fwd and bwd
                # (ref: checkpointing.py cpu_checkpointing; config gate
                # guarantees policy='dots_no_batch')
                remat_policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                    "device", "pinned_host"
                )
            else:
                remat_policy = {
                    "full": None,
                    "dots": jax.checkpoint_policies.checkpoint_dots,
                    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                }[ac.policy]
            loss_fn = jax.checkpoint(loss_fn, policy=remat_policy, static_argnums=())
        return loss_fn

    def _overlap_plan(self):
        """The OverlapPlan this engine traces its loss under, or None
        when zero_optimization.overlap_comm is false (the serialized
        twin). Prefetch specs (the `layers` subtrees of the storage/TP
        spec trees) ride along only where the scan-carried gather
        applies: a flat (non-pipelined) scanned stack under ZeRO-3,
        with the weight tree not already gathered up front by qwZ /
        compression transforms."""
        zcfg = self.config.zero_optimization
        if not zcfg.overlap_comm:
            return None
        layer_store = layer_tp = None
        if (not self.pipelined
                and zcfg.stage >= 3
                and zcfg.prefetch_depth >= 1
                and self._qwz_apply is None
                and self._compression is None
                and isinstance(self.param_specs, dict)
                and "layers" in self.param_specs):
            layer_store = self.param_specs["layers"]
            layer_tp = self.tp_specs["layers"]
        return overlap.OverlapPlan(
            mesh=self.mesh,
            prefetch_depth=zcfg.prefetch_depth,
            bucket_mb=zcfg.bucket_mb,
            layer_store_specs=layer_store,
            layer_tp_specs=layer_tp,
        )

    def overlap_stats(self):
        """Per-step overlap feed for monitor.training_events
        (docs/overlap.md): exposed_comm_us / achieved_overlap_frac /
        hideable_slack_us plus the per-bucket reduce-scatter ledger,
        from the last sanitized step's schedule artifact. None before
        sanitize() or on backends without HLO text."""
        return overlap.overlap_stats(
            getattr(self, "_overlap_schedule", None))

    def _make_accumulator(self):
        """(master_f32, batch, base_rng, scale, step) -> (mean grads, loss).

        The shared gradient path: GAS micro-scan with ZeRO grad-layout
        constraints (or one pipelined whole-batch call). Used by the
        fused train step and by the offload grad step."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        mesh = self.mesh
        grad_specs = self.grad_specs
        compute_dtype = self.compute_dtype
        has_aux = self.has_aux
        pipelined = self.pipelined
        qwz_apply = self._qwz_apply
        compression = self._compression
        pld = cfg.progressive_layer_drop
        # comm/compute overlap (runtime/overlap.py): the plan rides an
        # ambient scope around the loss trace — forward_hidden picks up
        # the prefetch specs, runtime/pipe.py the permute reorder
        plan = self._overlap_plan()
        loss_fn = overlap.scoped_loss(self._remat_wrapped_loss_fn(), plan)
        bucket_mb = plan.bucket_mb if plan is not None else 0.0

        def with_pld(b, step):
            """Inject the PLD keep-floor theta(t) = (1-θ)e^{-γt}+θ (ref:
            progressive_layer_drop.py update_state) into a batch dict —
            computed in-graph from the step, so no per-step recompiles."""
            if not pld.enabled:
                return b
            theta = (1.0 - pld.theta) * jnp.exp(
                -pld.gamma * step.astype(jnp.float32)
            ) + pld.theta
            return dict(b, pld_theta=theta)

        if self._qgz:
            worker_acc = self._make_worker_accumulator()

            def accumulate_qgz(master, batch, base_rng, scale, step):
                from ..comm.compressed import quantized_mean_tree

                wgrads, losses = worker_acc(master, batch, base_rng)
                grads = quantized_mean_tree(wgrads, mesh)
                grads = jax.tree.map(
                    lambda g, s: shd.constraint(g, s, mesh), grads, grad_specs
                )
                return grads, jnp.mean(losses)

            return accumulate_qgz

        def accumulate(master, batch, base_rng, scale, step):
            def to_model_params(m):
                p = cast_params(m, compute_dtype)
                if qwz_apply is not None:
                    p = qwz_apply(p)
                if compression is not None:
                    p = compression(p, step)
                return p

            if pipelined:
                # The pipelined loss consumes ALL microbatches in one call
                # (the microbatch loop lives inside runtime/pipe.py's
                # collective-permute program) — no outer GAS scan.
                def scaled_loss(m):
                    p = to_model_params(m)
                    out = loss_fn(p, with_pld(batch, step), base_rng)
                    l, _aux = out if has_aux else (out, None)
                    return l * scale, l

                grads, loss = jax.grad(scaled_loss, has_aux=True)(master)
                inv = 1.0 / scale
                if bucket_mb > 0:
                    # bucketed launches: each bucket's reduce-scatters
                    # issue under the previous bucket's unscale compute
                    grads = overlap.bucketed_apply(
                        grads, grad_specs, mesh, bucket_mb,
                        lambda j, g: g * inv)
                else:
                    grads = jax.tree.map(
                        lambda g, s: shd.constraint(g, s, mesh),
                        grads, grad_specs)
                    grads = jax.tree.map(lambda g: g * inv, grads)
                return grads, loss

            def micro(carry, xs):
                acc, loss_sum = carry
                idx, micro_batch = xs
                rng = jax.random.fold_in(base_rng, idx)

                def scaled_loss(m):
                    p = to_model_params(m)
                    out = loss_fn(p, with_pld(micro_batch, step), rng)
                    loss, aux = out if has_aux else (out, None)
                    return loss * scale, loss

                grads, loss = jax.grad(scaled_loss, has_aux=True)(master)
                # ZeRO>=2: constrain per-micro grads to the sharded layout →
                # XLA reduce-scatters inside the accumulation loop
                # (ref: stage_1_and_2.py overlap_comm reduction during bwd).
                if bucket_mb > 0:
                    # bucket_mb-sized launch groups, pipelined against
                    # the accumulate adds (runtime/overlap.py)
                    acc_leaves = jax.tree.leaves(acc)
                    acc = overlap.bucketed_apply(
                        grads, grad_specs, mesh, bucket_mb,
                        lambda j, g: acc_leaves[j] + g)
                else:
                    grads = jax.tree.map(
                        lambda g, s: shd.constraint(g, s, mesh),
                        grads, grad_specs,
                    )
                    acc = jax.tree.map(jnp.add, acc, grads)
                return (acc, loss_sum + loss), None

            zeros = jax.tree.map(
                lambda m, s: shd.constraint(jnp.zeros(m.shape, jnp.float32), s, mesh),
                master,
                grad_specs,
            )
            idxs = jnp.arange(gas)
            (grads, loss_sum), _ = jax.lax.scan(
                micro, (zeros, jnp.float32(0.0)), (idxs, batch)
            )
            inv = 1.0 / (gas * scale)
            grads = jax.tree.map(lambda g: g * inv, grads)
            return grads, loss_sum / gas

        return accumulate

    def _make_finalizer(self):
        """(new_master, new_opt, new_step, loss_scale, metrics) ->
        (TrainState, metrics): the shared tail of every compiled step —
        cast the updated master to the compute dtype under the param
        storage constraint (the ZeRO allgather point) and rebuild the
        TrainState. Extracted so the plain/1-bit/0-1-Adam step builders
        are each just 'produce grads → optimizer stage → finalize'
        (avoiding the reference engine.py's per-path duplication,
        ref: runtime/engine.py:180's 3.6k-line fate)."""
        mesh = self.mesh
        param_specs = self.param_specs
        compute_dtype = self.compute_dtype
        use_master = self._use_master
        opt_shd = getattr(self, "_opt_state_shardings", None)
        master_shd = getattr(self, "_master_shardings", None)

        def finish(new_master, new_opt, new_step, loss_scale, metrics):
            if opt_shd is not None:
                new_opt = jax.tree.map(
                    jax.lax.with_sharding_constraint, new_opt, opt_shd
                )
            if use_master and master_shd is not None:
                new_master = jax.tree.map(
                    jax.lax.with_sharding_constraint, new_master, master_shd
                )

            def cast_gather(m, store_spec, mshd=None):
                x = m.astype(compute_dtype)
                if mshd is not None:
                    # pin the compute-dtype cast to the SHARDED layout and
                    # barrier before regathering, so the ZeRO param
                    # allgather moves bf16, not fp32 (XLA otherwise
                    # reorders to gather-then-convert)
                    x = jax.lax.with_sharding_constraint(x, mshd)
                    x = jax.lax.optimization_barrier(x)
                return shd.constraint(x, store_spec, mesh)

            if master_shd is not None:
                new_params = jax.tree.map(
                    cast_gather, new_master, param_specs, master_shd
                )
            else:
                new_params = jax.tree.map(
                    cast_gather, new_master, param_specs
                )
            state = TrainState(
                step=new_step,
                params=new_params,
                master=new_master if use_master else None,
                opt=new_opt,
                loss_scale=loss_scale,
            )
            metrics.setdefault("skipped", jnp.zeros((), jnp.int32))
            return state, metrics

        return finish

    def _build_train_step(self):
        cfg = self.config
        optimizer = self.optimizer
        schedule = self.lr_schedule
        use_master = self._use_master
        fp16 = cfg.fp16.enabled
        clip = cfg.gradient_clipping
        seed = self._rng_seed
        accumulate = self._make_accumulator()
        fetch_params = self._make_param_fetch()
        finish = self._make_finalizer()

        # runtime non-finite gradient guard (integrity block,
        # docs/fault_tolerance.md SDC section): outside fp16 a NaN/Inf
        # gradient would silently poison master + optimizer state —
        # with integrity.enabled the step skips the update in-graph,
        # exactly like the fp16 overflow path but without loss-scale
        # coupling. Off by default: the selects change the canonical
        # HLO pinned by MEMBUDGET/NUMERICS.
        nonfinite_guard = (not fp16) and cfg.integrity.enabled

        def step_fn(state: TrainState, batch):
            master = (
                state.master
                if use_master
                else cast_params(fetch_params(state.params), jnp.float32)
            )
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            base_rng = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)

            grads, loss = accumulate(master, batch, base_rng, scale, state.step)

            grad_norm = global_grad_norm(grads)
            if fp16:
                # any inf/nan leaf makes the sum-of-squares norm non-finite,
                # so this single check subsumes a per-leaf isfinite pass
                found_inf = jnp.logical_not(jnp.isfinite(grad_norm))
            elif nonfinite_guard:
                found_inf = found_inf_in_grads(grads)
            else:
                found_inf = jnp.bool_(False)
            grads = clip_grads_by_global_norm(grads, clip, grad_norm)

            new_step = state.step + 1
            lr = schedule(state.step)
            new_master, new_opt = optimizer.update(grads, state.opt, master, lr, new_step)

            if fp16 or nonfinite_guard:
                # skip the update on overflow (ref: fused_optimizer.py step
                # overflow path) — select is branchless and free on TPU.
                sel = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(found_inf, o, n), new, old
                )
                new_master = sel(new_master, master)
                new_opt = sel(new_opt, state.opt)
                new_step = jnp.where(found_inf, state.step, new_step)
            if fp16:
                new_ls = update_loss_scale(state.loss_scale, found_inf, cfg.fp16)
            else:
                new_ls = state.loss_scale

            metrics = {
                "loss": loss,
                "grad_norm": grad_norm,
                "lr": lr,
                "skipped": found_inf.astype(jnp.int32),
            }
            if fp16:
                metrics["loss_scale"] = new_ls.scale
            return finish(new_master, new_opt, new_step, new_ls, metrics)

        # donated: every TrainState leaf aliases the returned TrainState
        # one-to-one (same shape/dtype/sharding) — verified against the
        # lowered module by engine.sanitize() (analysis.check_donation)
        return jax.jit(step_fn, donate_argnums=(0,))

    def _make_worker_accumulator(self, with_delta: bool = False):
        """(master[, worker_delta], batch, base_rng) ->
        (worker grads [dp, ·], mean loss).

        The per-worker partial-gradient path: shard_map maps over the
        data axes only (model/seq stay auto, so TP/Ulysses constraints
        inside the model still apply), each worker runs the GAS scan on
        its local batch shard WITHOUT any cross-worker reduction — the
        reduction is the caller's (compressed) job.
        (ref: the implicit per-rank grads of torch DDP that
        runtime/comm/nccl.py compressed_allreduce consumes).

        with_delta: the loss is evaluated at `master + worker_delta[w]`
        — the 0/1 Adam local-step view, where TrainState.params hold the
        last-synced weights and worker_delta the per-worker drift."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        mesh = self.mesh
        compute_dtype = self.compute_dtype
        loss_fn = self._remat_wrapped_loss_fn()
        has_aux = self.has_aux
        pipelined = self.pipelined
        manual = tuple(a for a in ("data", "zero") if mesh.shape.get(a, 1) > 1)

        def body(master, delta, batch, base_rng):
            if with_delta:
                local = jax.tree.map(lambda m, d: m + d[0], master, delta)
            else:
                local = master

            if pipelined:
                # the pipelined loss consumes ALL microbatches in one call
                # (GAS loop + schedule live inside runtime/pipe.py); the
                # 'pipe' axis stays AUTO inside this shard_map, so the
                # stage collectives partition as usual — this is how
                # 1-bit/0-1/qgZ compose with pipeline parallelism
                # (ref: 1-bit Adam under Megatron PP, onebit/adam.py)
                def local_loss(m):
                    p = cast_params(m, compute_dtype)
                    out = loss_fn(p, batch, base_rng)
                    return out[0] if has_aux else out

                loss, grads = jax.value_and_grad(local_loss)(local)
                grads = jax.tree.map(lambda g: g[None], grads)
                return grads, loss[None]

            def micro(carry, xs):
                acc, loss_sum = carry
                idx, micro_batch = xs
                rng = jax.random.fold_in(base_rng, idx)

                def local_loss(m):
                    p = cast_params(m, compute_dtype)
                    out = loss_fn(p, micro_batch, rng)
                    return out[0] if has_aux else out

                loss, grads = jax.value_and_grad(local_loss)(local)
                acc = jax.tree.map(jnp.add, acc, grads)
                return (acc, loss_sum + loss), None

            zeros = jax.tree.map(lambda m: jnp.zeros(m.shape, jnp.float32), master)
            (grads, loss_sum), _ = jax.lax.scan(
                micro, (zeros, jnp.float32(0.0)), (jnp.arange(gas), batch)
            )
            grads = jax.tree.map(lambda g: (g / gas)[None], grads)
            return grads, (loss_sum / gas)[None]

        if not manual:
            if with_delta:
                return body  # dp=1: worker dim trivially [1, ...]
            return lambda master, batch, rng: body(master, None, batch, rng)

        # pytree-prefix specs: master replicated over the manual axes,
        # batch leaves [gas|M, batch, ...] sharded on the batch dim (the
        # pipelined whole-batch layout [M, mb, S] shares the shape
        # convention), worker_delta leaves worker-major on dim 0
        wrapped = shd.shard_map_partial(
            body,
            mesh,
            in_specs=(P(), P(manual), P(None, manual), P()),
            out_specs=(P(manual), P(manual)),
            manual_axes=manual,
        )
        if with_delta:
            return wrapped
        dp = mesh.shape.get("data", 1) * mesh.shape.get("zero", 1)

        def no_delta(master, batch, rng):
            # body ignores delta when with_delta=False; the zeros tree is
            # dead code XLA eliminates — it only satisfies the in_specs
            zeros = jax.tree.map(
                lambda m: jnp.zeros((dp,) + m.shape, m.dtype), master
            )
            return wrapped(master, zeros, batch, rng)

        return no_delta

    def _build_onebit_step(self):
        """Compression-phase step for 1-bit Adam: per-worker grads →
        local momentum → error-feedback 1-bit averaged momentum → frozen-
        variance Adam update (ref: runtime/fp16/onebit/adam.py:210).

        Composes with ZeRO-1: master + nu are 'zero'-sharded while mu
        and the error memories stay replicated/worker-major (the local
        momentum accumulation needs full mu — sharding it would cost an
        fp32 allgather per step, the very traffic 1-bit removes). The
        gradient forward then runs off the replicated bf16 params, and
        the finalizer's cast-under-constraint IS the ZeRO-1 param
        allgather — independent of the compression hop, as the two paths
        never exchange full-precision gradients."""
        optimizer = self.optimizer
        schedule = self.lr_schedule
        mesh = self.mesh
        use_master = self._use_master
        zero1 = self.config.zero_stage >= 1
        seed = self._rng_seed
        worker_acc = self._make_worker_accumulator()
        finish = self._make_finalizer()

        def step_fn(state: TrainState, batch):
            master = state.master if use_master else cast_params(state.params, jnp.float32)
            # ZeRO-1: grads come from the replicated params (the sharded
            # master would allgather fp32 into the worker shard_map)
            grad_src = (
                cast_params(state.params, jnp.float32) if zero1 else master
            )
            base_rng = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
            wgrads, losses = worker_acc(grad_src, batch, base_rng)
            loss = jnp.mean(losses)
            new_step = state.step + 1
            lr = schedule(state.step)
            new_master, new_opt = optimizer.compressed_update(
                wgrads, state.opt, master, lr, new_step, mesh
            )
            metrics = {
                "loss": loss,
                # post-compression momentum norm (true grad norm would need
                # the uncompressed reduction this phase exists to avoid)
                "grad_norm": global_grad_norm(new_opt["mu"]),
                "lr": lr,
            }
            return finish(new_master, new_opt, new_step, state.loss_scale,
                          metrics)

        # donated: state leaves alias the returned TrainState (the 1-bit
        # momentum/error buffers keep their layout) — engine.sanitize()
        return jax.jit(step_fn, donate_argnums=(0,))

    def _build_zoadam_step(self, kind: str):
        """One of 0/1 Adam's four step programs (ref: onebit/zoadam.py:205
        — there one eager step with mutable flags; here one compiled SPMD
        program per schedule kind, chosen host-side)."""
        optimizer = self.optimizer
        schedule = self.lr_schedule
        mesh = self.mesh
        use_master = self._use_master
        seed = self._rng_seed
        # worker_u is identically zero through phase 1 — build full/onebit
        # without the delta input so XLA doesn't stream a dead params-sized
        # tree every step
        with_delta = kind in ("local", "sync")
        worker_acc = self._make_worker_accumulator(with_delta=with_delta)
        finish = self._make_finalizer()
        upd = {
            "full": optimizer.full_update,
            "onebit": optimizer.onebit_update,
            "local": optimizer.local_update,
            "sync": optimizer.sync_update,
        }[kind]

        def step_fn(state: TrainState, batch):
            master = state.master if use_master else cast_params(state.params, jnp.float32)
            base_rng = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
            if with_delta:
                wgrads, losses = worker_acc(
                    master, state.opt["worker_u"], batch, base_rng
                )
            else:
                wgrads, losses = worker_acc(master, batch, base_rng)
            loss = jnp.mean(losses)
            new_step = state.step + 1
            lr = schedule(state.step)
            new_master, new_opt = upd(wgrads, state.opt, master, lr, mesh)
            if kind in ("local", "sync"):
                # per-replica momentum norm: worker_mu is worker-major, so
                # normalize by sqrt(dp) to stay comparable with the
                # replicated-mu norm of the phase-1 programs
                dp = new_opt["worker_lrs"].shape[0]
                norm = global_grad_norm(new_opt["worker_mu"]) / jnp.sqrt(
                    jnp.float32(dp)
                )
            else:
                norm = global_grad_norm(new_opt["mu"])
            metrics = {
                "loss": loss,
                # momentum norm (the exact mean-grad norm would need the
                # reduction the local/1-bit phases exist to avoid)
                "grad_norm": norm,
                "lr": lr,
            }
            return finish(new_master, new_opt, new_step, state.loss_scale,
                          metrics)

        # donated: state leaves alias the returned TrainState across all
        # four 0/1-Adam step programs — engine.sanitize()
        return jax.jit(step_fn, donate_argnums=(0,))

    def _zo_transition(self):
        """Freeze-boundary bookkeeping: tile the replicated momentum into
        the worker-major copy and clear the error-feedback memories (they
        switch from logging gradient error to momentum error — ref:
        zoadam.py:305 reinitial_error_buffer)."""
        opt = self.state.opt

        def t(mu, wmu, ew, es):
            wmu2 = jax.tree.map(
                lambda m, w: jnp.broadcast_to(m[None], w.shape), mu, wmu
            )
            return (wmu2, jax.tree.map(jnp.zeros_like, ew),
                    jax.tree.map(jnp.zeros_like, es))

        shd_of = lambda tr: jax.tree.map(lambda x: x.sharding, tr)
        with use_mesh(self.mesh):
            wmu2, ew, es = jax.jit(
                t,
                out_shardings=(shd_of(opt["worker_mu"]), shd_of(opt["error_w"]),
                               shd_of(opt["error_s"])),
            )(opt["mu"], opt["worker_mu"], opt["error_w"], opt["error_s"])
        self.state = dataclasses.replace(
            self.state,
            opt={**opt, "worker_mu": wmu2, "error_w": ew, "error_s": es},
        )
        self._zo_transitioned = True

    def _dispatch_zoadam_step(self, batch) -> Dict[str, Any]:
        s = self.global_steps + 1  # 1-indexed global step
        if s > self.optimizer.var_freeze_step + 1 and not self._zo_transitioned:
            self._zo_transition()
        kind = self._zo_sched.kind(s)
        step_fn = self._zo_programs.get(kind)
        if step_fn is None:
            step_fn = self._zo_programs[kind] = self._build_zoadam_step(kind)
        batch = self._reshape_gas(batch)
        batch = self.shard_batch(batch, leading_accum_dim=True)
        with use_mesh(self.mesh):
            self.state, metrics = step_fn(self.state, batch)
        self._zo_sched.advance(s)
        return metrics

    def _build_grad_step(self):
        """Device half of the offloaded step: grads + loss + global norm.
        The optimizer update runs on the host (runtime/offload.py —
        ref: csrc/adam/cpu_adam.cpp role)."""
        seed = self._rng_seed
        accumulate = self._make_accumulator()
        fetch_params = self._make_param_fetch()

        def grad_fn(params, step, batch):
            master = cast_params(fetch_params(params), jnp.float32)
            base_rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            grads, loss = accumulate(master, batch, base_rng, jnp.float32(1.0), step)
            return grads, loss, global_grad_norm(grads)

        return jax.jit(grad_fn)

    # ------------------------------------------------------------------
    # static verification (analysis/sanitizer.py + analysis/costmodel.py)
    # ------------------------------------------------------------------
    def _cost_checks(self, compiled, label, hbm_budget_bytes=None,
                     target_devices=None, target_topology=None):
        """(CostReport | None, [SanitizerReport]) for one compiled step:
        S004 per-device HBM budget (projectable to a larger mesh), S005
        collective volume vs the live sharded state, S006 roofline (a
        train step must never compile comm-bound), S007 exposed
        collective time, S009 critical-path step-time — and, when a
        PodTopology is declared, S008 hierarchy placement of every
        replica group."""
        from ..analysis.costmodel import (
            build_cost_report,
            check_collective_volume,
            check_hbm_budget,
            check_roofline,
        )
        from ..analysis.schedule import (
            check_exposed_comm,
            check_hierarchy_placement,
            check_step_time,
        )
        from ..platform.accelerator import get_accelerator

        # overlap_comm=False analyzes the schedule in serialized-
        # execution mode (no latency-hiding credit) — the overlap-off
        # twin's S009 projection (docs/overlap.md)
        cost = build_cost_report(
            compiled, label=label,
            hide_sync_slack=self.config.zero_optimization.overlap_comm)
        if cost is None:
            return None, []
        self._overlap_schedule = getattr(cost, "_schedule", None)
        tree = self.state.master if self._use_master else self.state.params
        live = (int(sum(x.nbytes for x in jax.tree.leaves(tree)))
                if tree is not None else 0)
        # each gas microstep legitimately re-gathers the sharded params
        # (fwd + bwd under zero-3), so the accidental-replication bar
        # scales with the accumulation depth
        gas = self.config.gradient_accumulation_steps or 1
        acc = get_accelerator()
        checks = [
            check_hbm_budget(cost, budget_bytes=hbm_budget_bytes,
                             target_devices=target_devices, label=label),
            check_collective_volume(cost, live_sharded_bytes=live or None,
                                    k=2.0 * gas + 2.0, label=label),
            check_roofline(cost, peak_flops=acc.peak_flops(),
                           hbm_bandwidth=acc.hbm_bandwidth(),
                           expect="compute", comm_only=True, label=label),
        ]
        sched = getattr(cost, "_schedule", None)
        if sched is not None:
            checks.append(check_exposed_comm(sched, label=label))
            checks.append(check_step_time(sched, label=label))
            if target_topology is not None:
                checks.append(check_hierarchy_placement(
                    sched, target_topology,
                    target_devices=(
                        [target_devices] if target_devices else None),
                    label=label))
        return cost, checks

    def _compressed_kind(self) -> Optional[str]:
        if self._onebit:
            return "onebit"
        if self._zoadam:
            return "zoadam"
        if self._qgz:
            return "qgz"
        return None

    def _numerics_checks(self, compiled, lowered, label, master=None,
                         opt=None, donated=True):
        """N-series precision-flow checks for one compiled step
        (analysis/numerics.py): accumulation dtypes vs the declared
        policy (N001), fp32 master/optimizer integrity through the
        donation table (N002), loss-scale coverage (N003)."""
        from ..analysis.numerics import (
            check_program_numerics,
            grad_elem_counts,
        )
        from .precision import precision_policy

        policy = precision_policy(
            self.config, compressed=self._compressed_kind())
        tree = master if master is not None else self.state.params
        dp = int(self.mesh.shape.get("data", 1)
                 * self.mesh.shape.get("zero", 1))
        return check_program_numerics(
            compiled, policy, lowered=lowered, master=master, opt=opt,
            grad_counts=grad_elem_counts(tree, dp=dp), donated=donated,
            label=label)

    def _compressed_step_numerics(self, batch):
        """[SanitizerReport] for the COMPRESSED step programs: the
        1-bit / 0-1-Adam compressed-phase program (compiled here even
        when the engine is still in warmup — the phase switch must not
        be the first time its numerics are seen) and the qgZ fused
        step's group geometry + wire dtypes (N004)."""
        import warnings

        from ..analysis.numerics import check_quantized_groups
        from .precision import precision_policy

        kind = self._compressed_kind()
        if kind is None:
            return []
        policy = precision_policy(self.config, compressed=kind)
        dp = int(self.mesh.shape.get("data", 1)
                 * self.mesh.shape.get("zero", 1))
        reports = []
        if kind == "qgz":
            # the fused step IS the quantized-gradient program
            if self._train_step_fn is None:
                self._train_step_fn = self._build_train_step()
            fn, label = self._train_step_fn, "train_step[qgz]"
            block = 2048  # comm.compressed.quantized_mean default
        elif kind == "onebit":
            if getattr(self, "_onebit_step_fn", None) is None:
                self._onebit_step_fn = self._build_onebit_step()
            fn, label, block = self._onebit_step_fn, "train_step[onebit]", None
        else:  # zoadam: the compressed-momentum program of the schedule
            fn = self._zo_programs.get("onebit")
            if fn is None:
                fn = self._zo_programs["onebit"] = \
                    self._build_zoadam_step("onebit")
            label, block = "train_step[zoadam]", None
        with warnings.catch_warnings(), use_mesh(self.mesh):
            warnings.simplefilter("ignore")
            lowered = fn.lower(self.state, batch)
            compiled = lowered.compile()
        reports.append(self._numerics_checks(
            compiled, lowered, label,
            master=self.state.master if self._use_master else None,
            opt=self.state.opt))
        reports.append(check_quantized_groups(
            self.state.params, dp, policy, block=block,
            compiled_text=compiled.as_text(), label=label))
        return reports

    def _determinism_checks(self, lowered, compiled, label):
        """D001 on the pre-optimization HLO (rng ops and their sharding
        annotations survive there; the optimized text inlines threefry
        into anonymous shifts/xors) and D002 on the compiled text
        against the program's bitwise pin under THIS engine's mesh.
        Unregistered labels get the rerun-only fallback pin
        (varying_axes=()), so D002 stays quiet for ad-hoc programs —
        the canonical pins live in analysis.determinism.BITWISE_PINS."""
        from ..analysis.determinism import (check_reassociation,
                                            check_rng_discipline, pin_for)
        from ..profiling.hlo import preopt_hlo_text

        reports = []
        pre = preopt_hlo_text(lowered)
        if pre:
            reports.append(check_rng_discipline(pre, label=label))
        mesh_axes = tuple(
            (str(k), int(v)) for k, v in self.mesh.shape.items()
        ) if self.mesh is not None else ()
        reports.append(check_reassociation(
            compiled.as_text(), pin_for(label, mesh_axes=mesh_axes),
            label=label))
        return reports

    def sanitize(self, batch, hbm_budget_bytes=None, target_devices=None,
                 target_topology=None):
        """Statically verify this engine's compiled step against an
        example host batch: (a) every donated TrainState buffer aliases
        an output (S001), (b) the derived ZeRO/TP param specs survive
        SPMD partitioning (S002), (c) recompile hazards observed so far
        (S003), (d) the step's static cost model — peak HBM vs the
        per-device budget (S004), collective volume vs the live sharded
        state (S005), roofline balance (S006), (e) the schedule
        analyzer — exposed collective time (S007), critical-path
        step-time projection (S009), and with a declared
        `target_topology` the hierarchy placement of every replica
        group (S008), (f) the numerics sanitizer — accumulation dtypes
        vs the declared precision policy (N001), fp32
        master/optimizer-state integrity (N002), loss-scale coverage
        (N003), and on the 1-bit/0-1-Adam/qgZ compressed programs the
        quantized-collective sanity check (N004), (g) the determinism
        analyzer — layout-dependent PRNG draws (D001) and, for
        programs with a registered bitwise pin, reassociation hazards
        on fp additive reduces (D002). Compile-time only —
        no step executes, no state mutates. Returns
        analysis.SanitizerReport with `.cost` attached; `report.ok`
        gates CI.

        hbm_budget_bytes: per-device budget (default: the running
        chip's HBM from platform/accelerator.py). target_devices:
        project the footprint to a mesh of this size — catches the
        replicated-residency term that OOMs at scale.
        target_topology: analysis.schedule.PodTopology describing the
        slice layout the program is destined for — collectives whose
        replica groups straddle its DCN boundary surface as S008."""
        import warnings

        from ..analysis.report import merge_reports
        from ..analysis.sanitizer import check_donation, check_sharding

        batch = self._reshape_gas(batch)
        batch = self.shard_batch(batch, leading_accum_dim=True)
        if self._offload:
            # the fused-step donation story doesn't apply; the customer
            # is the host update's in-place donation (runtime/offload.py)
            reports = [self._recompile_tracker.report()]
            cost = None
            if not self._offload_nvme:
                # probe args pinned to the host device, exactly like
                # _dispatch_offload_step stages them
                from .offload import host_device

                cpu = host_device()
                grads = jax.tree.map(
                    lambda m: jax.device_put(jnp.zeros_like(m), cpu),
                    self.state.master)
                reports.append(check_donation(
                    self.host_optimizer._update,
                    (self.state.master, self.state.opt, grads,
                     jax.device_put(jnp.float32(1.0), cpu),
                     jax.device_put(self.state.step, cpu)),
                    donate_argnums=(0, 1),
                    argnames=("master", "opt"),
                    label="host_update",
                ))
                # the host tier's fp32 master/moments must BE fp32 —
                # tree-level N002 (no compiled program consumes them
                # on-device)
                from ..analysis.numerics import check_master_integrity

                reports.append(check_master_integrity(
                    master=self.state.master, opt=self.state.opt,
                    label="host_update"))
                # the device half of the offloaded step carries the HBM
                # footprint story (grads + params resident together)
                if self._grad_step_fn is None:
                    self._grad_step_fn = self._build_grad_step()
                with warnings.catch_warnings(), use_mesh(self.mesh):
                    warnings.simplefilter("ignore")
                    lowered_g = self._grad_step_fn.lower(
                        self._materialized_params(), self.state.step, batch
                    )
                    compiled_g = lowered_g.compile()
                cost, cost_checks = self._cost_checks(
                    compiled_g, "grad_step", hbm_budget_bytes,
                    target_devices, target_topology)
                reports.extend(cost_checks)
                reports.append(self._numerics_checks(
                    compiled_g, lowered_g, "grad_step", donated=False))
                reports.extend(self._determinism_checks(
                    lowered_g, compiled_g, "grad_step"))
            rep = merge_reports("offload_step", *reports)
            rep.cost = cost
            return rep
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        fn = self._train_step_fn
        # one lower+compile (mesh context resolves bare-P model
        # constraints; the donated-buffers-unusable warning is exactly
        # what S001 turns into structured findings)
        with warnings.catch_warnings(), self.mesh:
            warnings.simplefilter("ignore")
            lowered = fn.lower(self.state, batch)
            compiled = lowered.compile()
        don = check_donation(
            fn, (self.state, batch), donate_argnums=(0,),
            argnames=("state", "batch"), label="train_step",
            lowered=lowered, compiled=compiled,
        )
        # diff the specs of the tree the step actually CONSUMES: with a
        # master the grads flow from state.master (params are rebuilt
        # from it — DCE'd inputs), without one from state.params
        if self._use_master:
            shard = check_sharding(
                compiled, self.opt_specs, self.state.master, self.mesh,
                argname="state.master", label="train_step",
            )
        else:
            shard = check_sharding(
                compiled, self.param_specs, self.state.params, self.mesh,
                argname="state.params", label="train_step",
            )
        cost, cost_checks = self._cost_checks(
            compiled, "train_step", hbm_budget_bytes, target_devices,
            target_topology)
        num = self._numerics_checks(
            compiled, lowered, "train_step",
            master=self.state.master if self._use_master else None,
            opt=self.state.opt)
        rep = merge_reports(
            "train_step", don, shard, self._recompile_tracker.report(),
            *cost_checks, num, *self._compressed_step_numerics(batch),
            *self._determinism_checks(lowered, compiled, "train_step"))
        rep.cost = cost
        return rep

    def _zo_live_params(self):
        """0/1 Adam phase 2: TrainState.params are the last-SYNCED
        weights; local steps accumulate per-worker drift in
        opt['worker_u'] (the reference's p.data IS the live local copy).
        Eval/export therefore expose params + mean_w(worker_u) — the
        worker-mean live weights — instead of the stale sync point."""
        opt = self.state.opt or {}
        wu = opt.get("worker_u")
        if wu is None:
            return self.state.params
        if getattr(self, "_zo_live_fn", None) is None:
            self._zo_live_fn = jax.jit(
                lambda p, u: jax.tree.map(
                    lambda a, b: (
                        a.astype(jnp.float32) + jnp.mean(b, axis=0)
                    ).astype(a.dtype),
                    p, u,
                )
            )
        return self._zo_live_fn(self.state.params, wu)

    def _materialized_params(self):
        """Device-ready params; under offload_param=nvme they are read
        back from the swap files' master sections on demand. Under 0/1
        Adam phase 2 the per-worker drift is folded in (see
        _zo_live_params)."""
        if self.state.params is not None:
            if self._zoadam and getattr(self, "_zo_transitioned", False):
                return self._zo_live_params()
            return self.state.params
        lp = self.swapper.unflatten(self.swapper.read_lp_params())
        return jax.tree.map(
            lambda p, s: jax.device_put(p, NamedSharding(self.mesh, s)),
            lp,
            self.param_specs,
        )

    def _dispatch_offload_step(self, batch) -> Dict[str, Any]:
        """One global step with the optimizer tier in host DRAM:
        device grads → D2H → host update (clip+adam+cast) → H2D params.
        All stages enqueue asynchronously (ref: swap_tensor double
        buffering; here JAX async dispatch provides the overlap)."""
        if self._grad_step_fn is None:
            self._grad_step_fn = self._build_grad_step()
        batch = self._reshape_gas(batch)
        batch = self.shard_batch(batch, leading_accum_dim=True)
        with use_mesh(self.mesh):
            grads, loss, grad_norm = self._grad_step_fn(
                self._materialized_params(), self.state.step, batch
            )
        if self._offload_nvme:
            # NVMe tier: leaf-ordered swap-in → host update → swap-out
            # (ref: partitioned_optimizer_swapper.py swap-in/update/out).
            # The D2H gradient read IS the step's work product here —
            # the host optimizer consumes the bytes, not a metric.
            flat_grads = [
                np.asarray(g, np.float32)
                for g in jax.device_get(jax.tree.leaves(grads))  # ds-lint: ok R002 host tier consumes the grads
            ]
            lp_leaves, lr = self.swapper.step(
                flat_grads, jax.device_get(grad_norm),  # ds-lint: ok R002 host tier consumes the norm
                int(jax.device_get(self.state.step)),  # ds-lint: ok R002 host tier consumes the step
            )
            # the swapper's treedef, NOT state.params' (which is empty
            # under offload_param=nvme)
            params_lp = self.swapper.unflatten(lp_leaves)
            master, opt = None, None
        else:
            master, opt, params_lp, lr = self.host_optimizer.step(
                self.state.master, self.state.opt, grads, grad_norm, self.state.step
            )
        if self._offload_param_nvme:
            # params live only in the swap files between steps
            params = None
        else:
            params = jax.tree.map(
                lambda p, s: jax.device_put(p, self._param_storage_sharding(s)),
                params_lp,
                self.param_specs,
            )
        self.state = dataclasses.replace(
            self.state,
            step=self.state.step + 1,
            params=params,
            master=master,
            opt=opt,
        )
        return {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": lr,
            "skipped": jnp.zeros((), jnp.int32),
        }

    # ------------------------------------------------------------------
    # public API (the DeepSpeed train_batch contract,
    # ref: runtime/pipe/engine.py train_batch / engine fwd+bwd+step)
    # ------------------------------------------------------------------
    def shard_batch(self, batch, leading_accum_dim: bool = True):
        """Place a host batch onto the mesh: [gas, batch, seq, ...] leaves
        sharded over (data, expert) on batch and 'seq' on sequence."""
        mesh = self.mesh

        def put(x):
            x = np.asarray(x)
            spec = shd.batch_spec(x.ndim, leading_accum_dim=leading_accum_dim)
            # Drop axes that don't divide the dim (e.g. odd seq+1 token
            # buffers under a seq axis) — activations still get re-sharded
            # by in-model constraints.
            dims = []
            for i, entry in enumerate(tuple(spec) + (None,) * (x.ndim - len(spec))):
                axes = (entry,) if isinstance(entry, str) else (entry or ())
                size = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
                dims.append(entry if size > 1 and x.shape[i] % size == 0 else None)
            return jax.device_put(x, NamedSharding(mesh, P(*dims)))

        return jax.tree.map(put, batch)

    def _reshape_gas(self, batch):
        """[train_batch, ...] → [gas, train_batch/gas, ...] on each leaf."""
        gas = self.config.gradient_accumulation_steps

        def rs(x):
            x = np.asarray(x)
            if x.shape[0] == self.config.train_batch_size:
                return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])
            if x.ndim >= 1 and x.shape[0] == gas:
                return x
            raise ValueError(
                f"batch leading dim {x.shape[0]} is neither train_batch_size "
                f"{self.config.train_batch_size} nor gas {gas}"
            )

        return jax.tree.map(rs, batch)

    def drain_fault_delay(self) -> float:
        """Collect and reset injected straggler time (0.0 outside chaos
        runs) — same contract as ServingScheduler.drain_fault_delay."""
        d, self.fault_delay_s = self.fault_delay_s, 0.0
        return d

    def pipeline_schedule_stats(self) -> Optional[Dict[str, float]]:
        """Schedule accounting of THIS engine's pipeline (None when the
        loss is not pipelined): stage count P, interleave degree V,
        microbatch count M (the gradient-accumulation depth — the
        pipelined loss consumes all M in one call), the MEASURED bubble
        fraction replayed from the exact iteration counts the compiled
        scan runs (runtime/pipe.simulate_schedule), and the two closed
        forms it is gated against — (P-1)/(V*M+P-1) for this schedule
        and the non-interleaved (P-1)/(M+P-1) bound. The
        monitor.training_events pipeline feed emits these."""
        if not self.pipelined:
            return None
        from .pipe import bubble_fraction, simulate_schedule

        P = int(self.mesh.shape.get("pipe", 1))
        V = self._pipe_virtual_stages()
        M = int(self.config.gradient_accumulation_steps or 1)
        sim = simulate_schedule(M, P, V)
        return {
            "stages": float(P),
            "interleave": float(V),
            "microbatches": float(M),
            "schedule_steps": float(sim["total_steps"]),
            "bubble_fraction": float(sim["bubble_fraction"]),
            "bubble_closed_form": bubble_fraction(M, P, V),
            "bubble_noninterleaved_bound": bubble_fraction(M, P, 1),
        }

    def _dispatch_step(self, batch) -> Dict[str, Any]:
        # chaos fault point 'engine.step' fires BEFORE any dispatch: an
        # injected preemption raises with no state half-mutated (the
        # last committed TrainState is intact for peer reconstruction);
        # an injected straggler delay accrues to fault_delay_s
        act = fault_point("engine.step", rank=jax.process_index(),
                          step=self.global_steps + 1)
        if act is not None and act.kind == "delay":
            self.fault_delay_s += act.value
        if self.pipelined and self.mesh.shape.get("pipe", 1) > 1:
            # stage-boundary comm guard: the host-side representative
            # of this step's collective-permute ring (comm/comm.py
            # pipe_permute_tick) — training-chaos plans target one
            # stage's boundary; injected delays accrue per stage AND to
            # the step's fault_delay_s
            from ..comm.comm import pipe_permute_tick

            for s, d in pipe_permute_tick(
                    int(self.mesh.shape["pipe"]),
                    step=self.global_steps + 1).items():
                self.pipe_stage_delay_s[s] = (
                    self.pipe_stage_delay_s.get(s, 0.0) + d)
                self.fault_delay_s += d
        metrics = self._dispatch_step_inner(batch)
        # chaos fault point 'engine.grads' fires AFTER the compiled
        # step, BEFORE the caller can commit anything: kind='corrupt'
        # models a silent bit flip in the gradient path by flipping an
        # exponent bit of the step's grad-norm/loss readout AND of one
        # just-updated persistent-state leaf (the update that flipped
        # gradient produced). The training guardian
        # (elasticity/trainer.py) must catch it through the anomaly
        # window before the step is committed or mirrored.
        cact = fault_point("engine.grads", rank=jax.process_index(),
                           step=self.global_steps + 1)
        if cact is not None and cact.kind == "corrupt":
            metrics = self._corrupt_step_outputs(cact, metrics)
        return metrics

    def _corrupt_step_outputs(self, act, metrics) -> Dict[str, Any]:
        """The 'engine.grads' kind='corrupt' payload: seeded
        exponent-class bit flips (resilience/integrity.py) on the
        step's loss/grad_norm metrics and on one leaf of the
        just-updated persistent state (master when one exists, else
        params) — chaos-lane only; never reached disarmed."""
        from ..resilience import integrity

        out = dict(metrics)
        for name in ("grad_norm", "loss"):
            if name in out:
                host = np.asarray(jax.device_get(out[name]))
                out[name], _ = integrity.flip_bits(
                    host, act.seed, act.invocation, f"metrics.{name}",
                    bit_class="exponent")
        target = "master" if self.state.master is not None else "params"
        tree = getattr(self.state, target)
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        float_ix = [i for i, (_, leaf) in enumerate(flat)
                    if jnp.issubdtype(leaf.dtype, jnp.floating)]
        flips: list = []
        if float_ix:
            ix = float_ix[act.invocation % len(float_ix)]
            path, leaf = flat[ix]
            host = np.asarray(jax.device_get(leaf))
            flipped, flips = integrity.flip_bits(
                host, act.seed, act.invocation,
                jax.tree_util.keystr(path), bit_class="exponent")
            leaves = [leaf for _, leaf in flat]
            leaves[ix] = jax.device_put(
                flipped.astype(host.dtype), leaf.sharding)
            self.state = dataclasses.replace(
                self.state,
                **{target: jax.tree_util.tree_unflatten(treedef, leaves)})
        log_dist(
            f"chaos: injected SDC at step {self.global_steps + 1} — "
            f"flipped exponent bits in step metrics and {target} "
            f"({flips})", ranks=[0])
        return out

    def _dispatch_step_inner(self, batch) -> Dict[str, Any]:
        if self._offload:
            return self._dispatch_offload_step(batch)
        if self._zoadam:
            return self._dispatch_zoadam_step(batch)
        # 1-bit Adam: switch to the compressed-momentum program once the
        # warmup window ends (one extra compile at the phase boundary)
        compressed_phase = (
            self._onebit and self.global_steps >= self.optimizer.freeze_step
        )
        if compressed_phase:
            if getattr(self, "_onebit_step_fn", None) is None:
                self._onebit_step_fn = self._build_onebit_step()
            step_fn = self._onebit_step_fn
        else:
            if self._train_step_fn is None:
                self._train_step_fn = self._build_train_step()
            step_fn = self._train_step_fn
        batch = self._reshape_gas(batch)
        batch = self.shard_batch(batch, leading_accum_dim=True)
        # phase switches compile a DIFFERENT program by design; only
        # same-phase signature churn is a recompile hazard
        self._recompile_tracker.record(
            "train_step[onebit]" if compressed_phase else "train_step",
            (batch,),
        )
        # Mesh context makes bare-PartitionSpec constraints inside the model
        # (Ulysses/TP activation specs) resolve against our mesh.
        shape_key = (compressed_phase,) + tuple(
            (jax.tree_util.keystr(p), tuple(l.shape), str(l.dtype))
            for p, l in jax.tree_util.tree_flatten_with_path(batch)[0]
        )
        with use_mesh(self.mesh):
            compiled = self._train_compiled_cache.get(shape_key)
            if compiled is None:
                # AOT compile (per batch-shape signature, matching jit's
                # retrace-on-new-shape) so the step's HLO is inspectable:
                # flops/comm accounting reads the program actually executed.
                from ..profiling.hlo import collective_volumes

                compiled = step_fn.lower(self.state, batch).compile()
                self._train_compiled_cache[shape_key] = compiled
                comms_logger.record_compiled(collective_volumes(compiled))
            self._train_compiled = compiled
            self.state, metrics = compiled(self.state, batch)
        self.state = self._park_params(self.state)
        return metrics

    def train_batch_async(self, batch) -> Dict[str, Any]:
        """One global step, returning *device* metric arrays without a host
        sync — lets the host dispatch the next step / prefetch data while
        the device runs (the async-dispatch win over the reference's
        per-step .item() reads). Read values with float() when needed."""
        if self._health_monitor is not None:
            self._health_monitor.check()
        metrics = self._dispatch_step(batch)
        self.global_steps += 1
        if self._heartbeat is not None:
            # async path: this beat certifies host-loop liveness only —
            # a device wedged in a collective keeps the host dispatching
            # until the queue backs up, so device-side detection arrives
            # later than on the synchronous train_batch path
            self._heartbeat.beat(self.global_steps)
        return metrics

    def next_curriculum_batch(self, dataset) -> Dict[str, Any]:
        """Analyzer-metric curriculum: draw THIS step's sample ids from
        the current difficulty pool and gather the batch from `dataset`
        (indexable; dataset[i] is a per-sample dict of arrays, or a bare
        array which becomes {'tokens': ...}). ref: the reference's
        DeepSpeedDataSampler feeding its dataloader
        (data_pipeline/data_sampling/data_sampler.py:36) — here the
        engine exposes the draw so any data source plugs in."""
        if self.curriculum_sampler is None:
            raise ValueError(
                "next_curriculum_batch needs a non-seqlen "
                "curriculum_learning.curriculum_type backed by a "
                "data_efficiency metric index"
            )
        ids = self.curriculum_sampler.get_next_global_batch(
            self.global_steps + 1)
        samples = [dataset[int(i)] for i in ids]
        if isinstance(samples[0], dict):
            return {k: np.stack([s[k] for s in samples])
                    for k in samples[0]}
        return {"tokens": np.stack(samples)}

    def train_batch_with_curriculum(self, dataset) -> Dict[str, float]:
        """Curriculum-sampled train step (difficulty applies at SAMPLING
        time for analyzer metrics, unlike seqlen's truncation)."""
        return self.train_batch(self.next_curriculum_batch(dataset))

    def train_batch(self, batch) -> Dict[str, float]:
        """One full global step: GAS micro-steps + optimizer update.

        Accepts host arrays shaped [train_batch_size, ...] or
        [gas, train_batch_size/gas, ...]; returns host metrics (synced).
        """
        if self._health_monitor is not None:
            # refuse to enter a collective against a dead peer — raises
            # WorldDegradedError for the elastic supervisor to handle
            self._health_monitor.check()
        if self.curriculum is not None:
            from .data_pipeline import truncate_to_seqlen

            seqlen = self.curriculum.update_difficulty(self.global_steps + 1)
            batch = truncate_to_seqlen(batch, seqlen)
        self.tput.start()
        self.timers(BATCH_TIMER).start()
        metrics = self._dispatch_step(batch)
        # single host transfer for all metrics (device sync point) — per-key
        # float() would pay one device round trip per metric; the sync-free
        # path is train_batch_async
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}  # ds-lint: ok R002 the one deliberate per-step sync
        self.timers(BATCH_TIMER).stop(sync=False)
        step_time = self.timers(BATCH_TIMER).elapsed(reset=True)
        self.tput.stop()
        self.global_steps += 1
        if self._heartbeat is not None:
            # metrics were device_get'd above, so this beat certifies a
            # COMPLETED step, not just a dispatched one
            self._heartbeat.beat(self.global_steps)
        self._metrics_host = metrics
        if self.global_steps % self.config.steps_per_print == 0:
            log_dist(
                f"step={self.global_steps} loss={metrics['loss']:.4f} "
                f"lr={metrics['lr']:.3e} grad_norm={metrics['grad_norm']:.3f} "
                f"samples/s={self.tput.avg_samples_per_sec:.1f}",
                ranks=[0],
            )
        if self.config.wall_clock_breakdown and self.global_steps > 1:
            # per-step latency line (ref: engine.py wall_clock_breakdown
            # fwd/bwd/step timers — one fused program here, one number)
            log_dist(
                f"time: step={step_time*1e3:.1f}ms "
                f"samples/s={self.config.train_batch_size/step_time:.1f}",
                ranks=[0],
            )
        if (
            self.flops_profiler is not None
            and self.global_steps == self.config.flops_profiler.profile_step + 1
            and self._train_compiled is not None
        ):
            # profile the first post-warmup step (compile excluded)
            self.flops_profiler.profile(
                self._train_compiled, step_time, self.model_flops_per_step
            )
            self.flops_profiler.print_profile()
        self.monitor.write_events(
            [(f"Train/{k}", v, self.global_steps) for k, v in metrics.items()]
        )
        return metrics

    def eval_batch(self, batch) -> float:
        """Loss-only forward (ref: pipe engine eval_batch)."""
        if self._eval_step_fn is None:
            loss_fn, has_aux, dtype = self.loss_fn, self.has_aux, self.compute_dtype
            fetch_params = self._make_param_fetch()

            def ev(params, batch):
                # rng=None: rng-gated dropout paths disable themselves in
                # eval, matching the reference's module.eval() forward
                out = loss_fn(cast_params(fetch_params(params), dtype), batch, None)
                return out[0] if has_aux else out

            self._eval_step_fn = jax.jit(ev)
        if self.pipelined:
            # A pipelined loss wants [M, mb, ...]. Any 2-D batch (including
            # partial validation batches) runs as ONE pipeline microbatch;
            # pre-microbatched 3-D input passes through untouched.
            def add_micro_dim(x):
                x = np.asarray(x)
                return x[None] if x.ndim == 2 else x

            batch = jax.tree.map(add_micro_dim, batch)
        batch = self.shard_batch(batch, leading_accum_dim=self.pipelined)
        with use_mesh(self.mesh):
            return float(self._eval_step_fn(self._materialized_params(), batch))

    # ------------------------------------------------------------------
    # checkpointing (ref: engine.py save_checkpoint:3064 / load:2700)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state=None):
        tag = tag or f"global_step{self.global_steps}"
        state_to_save = self.state
        if self._offload_nvme:
            # gather the NVMe tier into the checkpoint so it is
            # self-contained (the swap files are scratch, not a checkpoint —
            # ref: stage3 NVMe-aware save paths)
            master, opt = self.swapper.export_state()
            state_to_save = dataclasses.replace(self.state, master=master, opt=opt)
            if state_to_save.params is None:
                # offload_param=nvme keeps no resident params — materialize
                # them into the checkpoint so ANY engine layout can load it
                state_to_save = dataclasses.replace(
                    state_to_save,
                    params=jax.tree.map(
                        lambda m: np.asarray(m).astype(self.compute_dtype),
                        master,
                    ),
                )
        meta = {
            "global_steps": self.global_steps,
            "client_state": client_state or {},
            # structure descriptor so a differently-configured engine can
            # reconcile on load (the universal-checkpoint property,
            # ref: deepspeed/checkpoint/ds_to_universal.py made native)
            "has_master": state_to_save.master is not None,
            "has_loss_scale": state_to_save.loss_scale is not None,
            "optimizer": self.optimizer.name,
            # pipeline layout of the stored layer stack — what
            # load_universal converts across (mesh changes are free)
            "pipeline_stages": int(self.mesh.shape.get("pipe", 1)),
            "pipeline_virtual_stages": self._pipe_virtual_stages(),
        }
        self.checkpoint_engine.save(save_dir, tag, state_to_save, meta)
        return tag

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        """Restore state saved under ANY precision/ZeRO/mesh layout.

        Orbax re-shards arrays to this engine's shardings; precision
        reconciliation: a checkpoint with fp32 master loads its master as
        the source of truth (params recast), one without synthesizes the
        master from params (ref: engine.py:2700 load dp/mp resize checks —
        here layout changes are free, only the master/scaler structure
        needs reconciling)."""
        # pin one (tier, version) resolution for the WHOLE fan-out —
        # including the universal-conversion peeks, which otherwise race
        # a retention sweep / async fast-tier commit between deciding the
        # layout conversion and loading the tensors (tiered engine only).
        # When conversion rewrites into a scratch dir, the subsequent
        # load keys on that dir and resolves fresh — the scratch dir is
        # immutable, so no pin is needed there.
        fanout = getattr(self.checkpoint_engine, "load_fanout", None)
        ctx = fanout(load_dir, tag) if fanout is not None \
            else contextlib.nullcontext()
        scratch = None
        self.disk_restores += 1
        try:
            with ctx:
                if self.config.checkpoint.load_universal:
                    load_dir, tag, scratch = self._maybe_convert_universal(
                        load_dir, tag)
                if self._offload_nvme:
                    return self._load_checkpoint_nvme(load_dir, tag)
                return self._load_checkpoint_fused(load_dir, tag)
        finally:
            if scratch is not None:
                import shutil

                shutil.rmtree(scratch, ignore_errors=True)

    def _load_checkpoint_fused(self, load_dir: str, tag: Optional[str]):
        meta_probe = self.checkpoint_engine.peek_meta(load_dir, tag)
        disk_has_master = meta_probe.get("has_master", self.state.master is not None)
        disk_has_ls = meta_probe.get("has_loss_scale", self.state.loss_scale is not None)

        template = self.state
        if disk_has_master and template.master is None:
            template = dataclasses.replace(
                template, master=cast_params(template.params, jnp.float32)
            )
        elif not disk_has_master and template.master is not None:
            # restore params at full precision (the checkpoint's dtype) so
            # the synthesized master isn't round-tripped through bf16
            template = dataclasses.replace(
                template, master=None, params=cast_params(template.params, jnp.float32)
            )
        if disk_has_ls and template.loss_scale is None:
            template = dataclasses.replace(template, loss_scale=init_loss_scale(self.config.fp16))
        elif not disk_has_ls and template.loss_scale is not None:
            template = dataclasses.replace(template, loss_scale=None)

        state, meta, tag = self.checkpoint_engine.load(load_dir, tag, template)

        # Reconcile back to THIS engine's structure.
        if disk_has_master and not self._use_master:
            # master is the authoritative copy; store it at THIS engine's
            # compute dtype (fp32 engine keeps fp32; a bf16 engine with
            # master_weights=False must not inflate params to fp32)
            params = jax.tree.map(
                lambda m, s: jax.device_put(
                    m.astype(self.compute_dtype), self._param_storage_sharding(s)
                ),
                state.master,
                self.param_specs,
            )
            state = dataclasses.replace(state, params=params, master=None)
        elif not disk_has_master and self._use_master:
            master = jax.tree.map(
                lambda p, s: jax.device_put(
                    p.astype(jnp.float32), NamedSharding(self.mesh, s)
                ),
                state.params,
                self.opt_specs,
            )
            state = dataclasses.replace(
                state,
                master=master,
                params=jax.tree.map(
                    lambda p, s: jax.device_put(
                        p.astype(self.compute_dtype), self._param_storage_sharding(s)
                    ),
                    state.params,
                    self.param_specs,
                ),
            )
        elif disk_has_master and self._use_master:
            # params dtype follows this engine's compute dtype
            state = dataclasses.replace(
                state,
                params=jax.tree.map(
                    lambda m, s: jax.device_put(
                        m.astype(self.compute_dtype), self._param_storage_sharding(s)
                    ),
                    state.master,
                    self.param_specs,
                ),
            )
        if not self.config.fp16.enabled and state.loss_scale is not None:
            state = dataclasses.replace(state, loss_scale=None)
        if self.config.fp16.enabled and state.loss_scale is None:
            state = dataclasses.replace(state, loss_scale=init_loss_scale(self.config.fp16))
        if self._offload and not self._offload_nvme:
            # the optimizer tier lives in host DRAM regardless of where the
            # checkpoint (or the reconciliation above) placed it
            from .offload import to_host

            state = dataclasses.replace(
                state, master=to_host(state.master), opt=to_host(state.opt)
            )

        self.state = state
        self.global_steps = meta.get("global_steps", int(jax.device_get(state.step)))
        if self._zoadam:
            # interval state is a pure function of the step count
            self._zo_sched = self.optimizer.make_schedule()
            self._zo_sched.replay(self.global_steps)
            self._zo_transitioned = (
                self.global_steps > self.optimizer.var_freeze_step + 1
            )
        return tag, meta.get("client_state", {})

    def _maybe_convert_universal(self, load_dir: str, tag: Optional[str]):
        """checkpoint.load_universal: re-partition the stored layer stack
        to THIS engine's pipeline degree before restore (the
        --universal-checkpoint load, ref: ds_to_universal.py + engine
        load_universal_checkpoint — mesh/stage/precision changes are
        already free; the pipeline degree is the one tree change)."""
        import tempfile

        from ..utils.universal_checkpoint import convert_pipeline_layout

        meta = self.checkpoint_engine.peek_meta(load_dir, tag)
        src_v = int(meta.get("pipeline_virtual_stages", 1))
        tgt_v = self._pipe_virtual_stages()
        if "pipeline_stages" in meta:
            src = int(meta["pipeline_stages"])
        else:
            if src_v > 1:
                raise ValueError(
                    "checkpoint meta records pipeline_virtual_stages but "
                    "not pipeline_stages — cannot locate the layout dims"
                )
            # pre-meta checkpoints: infer the stored degree from the saved
            # layer-leaf ranks (a stage-partitioned stack carries one extra
            # leading dim vs this engine's flat layout)
            src = self._infer_stored_pipeline_stages(load_dir, tag)
        tgt = int(self.mesh.shape.get("pipe", 1))
        if src == tgt and src_v == tgt_v:
            return load_dir, tag, None
        out_dir = tempfile.mkdtemp(prefix="ds_tpu_universal_")
        convert_pipeline_layout(load_dir, out_dir, src, tgt, tag,
                                source_virtual=src_v, target_virtual=tgt_v)
        log_dist(
            f"load_universal: converted pipeline layout {src}x{src_v}→"
            f"{tgt}x{tgt_v} stages",
            ranks=[0],
        )
        # caller deletes out_dir after restore (a converted checkpoint can
        # be model-sized; leaking one per resume would fill /tmp)
        return out_dir, tag, out_dir

    def _pipe_virtual_stages(self) -> int:
        """Interleave degree of THIS engine's layer stack. The declared
        pipeline_virtual_stages wins; otherwise fall back to shape
        inference — a circular stack is [v, P, lc, ...] (dim 1 == pipe),
        a plain one [P, L/P, ...] (dim 0 == pipe). The corner where both
        leading dims equal pipe is ambiguous (a [P, P, lc] stack could
        be v==P interleaved or plain with chunk == P); it is ASSUMED
        PLAIN with a loud warning, since plain small-chunk stacks are
        common and interleaved engines are documented to declare
        (r3 advisor finding)."""
        if self._pipe_virtual is not None:
            return self._pipe_virtual
        pipe = int(self.mesh.shape.get("pipe", 1))
        if not self.pipelined or pipe <= 1:
            return 1
        layers = (self.state.params or {}).get("layers") if isinstance(
            self.state.params, dict) else None
        if not layers:
            return 1
        leaf = next(iter(layers.values()))
        if leaf.ndim >= 2 and leaf.shape[0] == pipe and leaf.shape[1] == pipe:
            # a [P, P, ...] stack is either plain with chunk == P (the
            # common small-test shape) or an UNDECLARED v == P circular
            # stack; assume plain but say so loudly — an interleaved
            # engine must declare pipeline_virtual_stages or its
            # checkpoints convert with scrambled layer order
            log_dist(
                f"layer stack leading dims are both == pipe ({pipe}); "
                "assuming a PLAIN [P, L/P] layout. If this engine is "
                "interleaved, pass pipeline_virtual_stages to "
                "initialize() — checkpoint conversion depends on it.",
                ranks=[0], level=30,  # logging.WARNING
            )
            return 1
        if leaf.ndim >= 2 and leaf.shape[0] != pipe and leaf.shape[1] == pipe:
            return int(leaf.shape[0])
        return 1

    def _infer_stored_pipeline_stages(self, load_dir: str, tag: Optional[str]) -> int:
        """Stored pipeline degree of a checkpoint without pipeline_stages
        meta, read from orbax array metadata (no tensor data touched).
        Returns 1 when the layout matches this engine's (or when the
        params tree has no 'layers' stack to compare)."""
        import os as _os

        import orbax.checkpoint as ocp

        tpl_layers = (
            self.state.params.get("layers")
            if isinstance(self.state.params, dict) else None
        )
        if not tpl_layers:
            return 1
        try:
            resolved = self.checkpoint_engine.resolve_tag(load_dir, tag)
            md = ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).metadata(
                _os.path.join(_os.path.abspath(load_dir), resolved, "state")
            )
            # StepMetadata -> item_metadata.tree (plain dict of ArrayMetadata)
            tree = getattr(getattr(md, "item_metadata", md), "tree", md)
            stored_layers = tree["params"]["layers"]
        except Exception:
            return 1
        # rank of a FLAT layer stack for this model ([L, ...])
        flat_extra = 1 if self.mesh.shape.get("pipe", 1) > 1 else 0
        for k, tpl in tpl_layers.items():
            stored = stored_layers.get(k)
            if stored is None:
                continue
            flat_rank = tpl.ndim - flat_extra
            stored_rank = len(tuple(stored.shape))
            if stored_rank == flat_rank + 1:
                return int(stored.shape[0])  # [P, L/P, ...]
            if stored_rank == flat_rank:
                return 1
        return 1

    def _load_checkpoint_nvme(self, load_dir: str, tag: Optional[str]):
        """Restore into the NVMe tier: checkpointed master+moments go back
        to swap files; only compute-dtype params return to the mesh."""
        meta_probe = self.checkpoint_engine.peek_meta(load_dir, tag)
        disk_has_master = meta_probe.get("has_master", True)
        # current swap contents provide the host-resident template shapes
        tmpl_master, tmpl_opt = self.swapper.export_state()
        params_tmpl = self.state.params
        if params_tmpl is None:
            # offload_param=nvme engine: the checkpoint still carries a
            # params subtree (see save_checkpoint) — template it from the
            # swap masters
            params_tmpl = jax.tree.map(
                lambda m: np.asarray(m).astype(self.compute_dtype), tmpl_master
            )
        template = dataclasses.replace(
            self.state,
            params=params_tmpl,
            master=tmpl_master if disk_has_master else None,
            opt=tmpl_opt,
            loss_scale=None,
        )
        state, meta, tag = self.checkpoint_engine.load(load_dir, tag, template)
        if disk_has_master:
            master = state.master
        else:
            master = jax.tree.map(
                lambda p: np.asarray(jax.device_get(p), np.float32), state.params
            )
        self.swapper.import_state(master, state.opt)
        if self._offload_param_nvme:
            params = None  # the freshly-imported swap files are the copy
        else:
            params = jax.tree.map(
                lambda m, s: jax.device_put(
                    np.asarray(jax.device_get(m)).astype(self.compute_dtype),
                    self._param_storage_sharding(s),
                ),
                master,
                self.param_specs,
            )
        self.state = dataclasses.replace(
            state, params=params, master=None, opt=None, loss_scale=None
        )
        self.global_steps = meta.get("global_steps", int(jax.device_get(state.step)))
        return tag, meta.get("client_state", {})

    # ------------------------------------------------------------------
    @property
    def params(self):
        return self._materialized_params()

    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def get_lr(self) -> float:
        return float(jax.device_get(self.lr_schedule(self.state.step)))

    def get_global_grad_norm(self) -> Optional[float]:
        return self._metrics_host.get("grad_norm")
