"""Device-mesh construction and topology introspection.

TPU-native replacement for the reference's process-group topology
machinery (ref: deepspeed/utils/groups.py, runtime/pipe/topology.py —
ProcessTopology:12, PipeModelDataParallelTopology:244). Where the
reference builds cartesian rank grids plus torch ProcessGroups, here the
whole cluster is one `jax.sharding.Mesh` with named axes; "groups" are
mesh axes and collectives ride ICI/DCN as XLA chooses.

Axis names (fixed vocabulary, any may be size 1):
  pipe    — pipeline stages           (ref: runtime/pipe/)
  data    — data parallel / ZeRO      (ref: groups.py:385)
  zero    — ZeRO sub-group (MiCS/hpZ) (ref: runtime/zero/mics.py:64,
            zero_hpz_partition_size config.py:264): when >1, the data
            dimension is factored data×zero and ZeRO state shards over
            'zero' only, replicating across 'data' groups — sharding
            collectives stay on the fast intra-group links
  expert  — expert parallel for MoE   (ref: groups.py:113-290)
  seq     — Ulysses sequence parallel (ref: deepspeed/sequence/layer.py)
  model   — tensor parallel           (ref: module_inject AutoTP)

Order is outermost→innermost: 'model' is fastest-varying so TP
collectives ride the highest-bandwidth ICI links; 'pipe' is outermost so
stage boundaries may cross DCN; 'zero' sits inside 'data' so sub-group
gathers ride shorter paths than cross-group traffic.
"""

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.logging import logger

MESH_AXES = ("pipe", "data", "zero", "expert", "seq", "model")

# Axes over which a batch is sharded (data-parallel-like axes).
BATCH_AXES = ("data", "zero", "expert")


def resolve_axis_sizes(
    axis_sizes: Dict[str, int], n_devices: Optional[int] = None
) -> Dict[str, int]:
    """Fill in a single -1 axis from the device count and validate the product."""
    if n_devices is None:
        n_devices = len(jax.devices())
    sizes = {ax: int(axis_sizes.get(ax, 1)) for ax in MESH_AXES}
    wildcard = [ax for ax, s in sizes.items() if s == -1]
    if len(wildcard) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wildcard:
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes product {fixed}"
            )
        sizes[wildcard[0]] = n_devices // fixed
        fixed = n_devices
    if fixed != n_devices:
        raise ValueError(
            f"mesh axes {sizes} multiply to {fixed} but there are {n_devices} devices"
        )
    return sizes


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global Mesh.

    On real TPU slices this uses `mesh_utils.create_device_mesh` so axis
    adjacency maps onto the physical ICI torus; on CPU/fake platforms a
    plain reshape of the device list is used.
    """
    if devices is None:
        devices = jax.devices()
    sizes = resolve_axis_sizes(axis_sizes or {}, n_devices=len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    if devices[0].platform in ("tpu",) and len(devices) > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
            return Mesh(dev_array, MESH_AXES)
        except Exception as e:  # pragma: no cover - topology-dependent
            logger.warning(f"mesh_utils.create_device_mesh failed ({e}); using reshape order")
    dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def use_mesh(mesh: Mesh):
    """Ambient-mesh context, version-portable: `jax.sharding.set_mesh`
    where it exists (newer jax), else the legacy Mesh context manager —
    both make bare-PartitionSpec constraints inside jitted bodies resolve
    against `mesh`. Every engine dispatch path routes through this one
    helper instead of calling set_mesh directly."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh  # Mesh is itself a context manager on older jax


def ambient_mesh():
    """The mesh bare-P constraints resolve against: the abstract mesh on
    newer jax (set via use_mesh -> set_mesh), else the legacy context
    mesh (`with mesh:`, what use_mesh enters on jax 0.4.x). Returns an
    EMPTY mesh (`.empty` is True) when no context is active — callers
    test `mesh is None or mesh.empty`."""
    get_abs = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abs is not None:
        return get_abs()
    from jax._src import mesh as _mesh_lib

    return _mesh_lib.thread_resources.env.physical_mesh


# Manual-axes bookkeeping for the LEGACY shard_map path: new jax exposes
# the mapped axes on the ambient abstract mesh (mesh.manual_axes); old
# jax has no equivalent, so shard_map_partial records them around the
# body's trace and current_manual_axes() surfaces them to the in-model
# constraint helpers (_shard / _constraint_auto_only).
_LEGACY_MANUAL_AXES: List[frozenset] = []


def current_manual_axes() -> frozenset:
    """Mesh axes the innermost shard_map already maps over (legacy-jax
    bookkeeping; on new jax prefer the ambient mesh's manual_axes)."""
    return _LEGACY_MANUAL_AXES[-1] if _LEGACY_MANUAL_AXES else frozenset()


def manual_axes_of(mesh) -> frozenset:
    """Manual axes visible right now: the ambient mesh's own annotation
    (new jax) unioned with the legacy shard_map bookkeeping."""
    own = frozenset(getattr(mesh, "manual_axes", ()) or ())
    return own | current_manual_axes()


def shard_map_partial(f, mesh: Mesh, in_specs, out_specs, manual_axes,
                      check: bool = False):
    """Partial-manual shard_map, version-portable: the new `jax.shard_map`
    (axis_names = the MANUAL axes) where it exists, else the legacy
    experimental API (auto = every OTHER mesh axis). check maps to
    check_vma/check_rep respectively."""
    manual = set(manual_axes)
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  axis_names=manual, check_vma=check)
    from jax.experimental.shard_map import shard_map as legacy

    def f_recording(*a, **k):
        _LEGACY_MANUAL_AXES.append(frozenset(manual))
        try:
            return f(*a, **k)
        finally:
            _LEGACY_MANUAL_AXES.pop()

    return legacy(f_recording, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=check,
                  auto=frozenset(mesh.axis_names) - manual)


def single_device_mesh() -> Mesh:
    return build_mesh({ax: 1 for ax in MESH_AXES})


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name]


def data_parallel_size(mesh: Mesh) -> int:
    """World size of the batch-sharded axes (data × expert).

    Mirrors the reference notion that the expert-parallel group is carved
    out of the data-parallel world (ref: groups.py:113
    _create_expert_and_data_parallel).
    """
    return int(np.prod([mesh.shape[ax] for ax in BATCH_AXES]))


def describe(mesh: Mesh) -> str:
    parts = [f"{ax}={mesh.shape[ax]}" for ax in mesh.axis_names if mesh.shape[ax] > 1]
    return "Mesh(" + (", ".join(parts) or "1 device") + f", {mesh.size} devices)"
