"""Determinism analyzer: RNG discipline, reassociation, and ordering.

Every gate in this tree rests on bitwise loss identity, token-identical
serving outputs, or byte-identical ledgers — yet until this pass
nothing *statically* proved the properties those pins depend on. PR 14
only caught the layout-dependent router-RNG bug (EP=1 != EP=N by
~1e-3: threefry is not partitionable, so a draw laid out across the
'expert' mesh axis computes DIFFERENT BITS per layout) because a
bitwise test happened to cover it. These checks fence that bug class —
and its host-side and serving-side siblings — at compile/lint time.

Rules
  D001  layout-dependent PRNG: a draw op (rng-bit-generator, threefry
        custom-call, or a call into jax's lowered rng helpers) in the
        PRE-OPT HLO whose result carries a mesh-tiled sharding, whose
        seed operand arrives mesh-tiled (provenance resolved through
        tuple packaging), or which sits inside a shard_map manual
        context — without a replicated pin on the draw (the
        `_replicated_draw` idiom: `with_sharding_constraint(x, P())`,
        moe/sharded_moe.py). The PR-14 bug class, caught before any
        step runs.
  D002  reassociation hazard on a bitwise-pinned program: a cross-shard
        floating-point ADDITIVE reduce collective (all-reduce /
        reduce-scatter with an `add` combiner) whose replica groups
        span a mesh axis the program's bitwise pin declares
        LAYOUT-VARYING — re-laying-out that axis changes the partial-
        sum order, so the pinned identity holds only by accident.
        Flagged ONLY for programs registered in the bitwise-pin
        registry (BITWISE_PINS); a registered program may WAIVE a
        specific reduce class with a committed reason (the waiver is
        the reviewed acceptance of the hazard, usually because a
        dynamic gate pins the identity empirically).
  D003  host-side ordering nondeterminism (AST): unsorted
        `os.listdir`/`glob`/`iterdir`/`scandir` enumeration, sorts
        keyed on mtime alone (ties fall back to enumeration order),
        `json.dump` without `sort_keys=True` (committed-artifact
        byte stability), iteration over a set, and — in
        `scripts/ds_*.py` capture paths — `time.time()`/unseeded
        `random`/`np.random.default_rng()`.
  D004  serving draw-key discipline (AST): a sampled draw in the
        scheduler/router/sampling/engine serving paths must key on
        (seed, stream, position) — concretely, its key expression must
        derive through `jax.random.fold_in` (the position term; the
        stream term is the per-slot key fan-out) — and must never fall
        back to process-global or wall-clock entropy. The invariant
        every requeue-for-recompute fallback silently assumes.

D003/D004 honor the ds-lint pragma syntax (`# ds-lint: ok D003 <why>`
on the offending line or the line above); D001/D002 have no source
anchor, so their override story is the registry: `allow_manual` for
deliberate per-shard draws, `waived` reduce classes for accepted
reassociation. Gate: `scripts/ds_determinism.py` against the committed
DETERMINISM.json — D findings have NO baseline (any active finding is
red in every mode); only the per-program rng-op/reduce-class ledger is
pinned.
"""

import ast
import dataclasses
import itertools
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .report import Finding, LintReport, SanitizerReport

__all__ = [
    "D_RULES",
    "BitwisePin",
    "BITWISE_PINS",
    "pin_for",
    "check_rng_discipline",
    "check_reassociation",
    "check_host_ordering",
    "check_draw_keys",
    "program_determinism",
    "rng_ledger",
    "reduce_ledger",
    "ORDERING_SCOPE",
    "DRAW_KEY_SCOPE",
]

D_RULES = {
    "D001": "layout-dependent PRNG: mesh-sharded threefry draw without "
            "a replicated pin",
    "D002": "fp additive reduce over a layout-varying mesh axis on a "
            "bitwise-pinned program",
    "D003": "host-side ordering nondeterminism feeding a committed "
            "artifact",
    "D004": "serving draw not keyed on (seed, stream, position), or "
            "wall-clock/global entropy in a serving path",
}

# repo-relative D003 scope: every committed-artifact emitter — the
# capture scripts, the analyzers that write baselines, the checkpoint
# tag machinery, and the trace-artifact reader
ORDERING_SCOPE = (
    "scripts",
    "deepspeed_tpu/analysis",
    "deepspeed_tpu/runtime/checkpoint.py",
    "deepspeed_tpu/profiling/latency.py",
)

# repo-relative D004 scope: the serving paths whose draws the
# requeue-for-recompute fallback replays
DRAW_KEY_SCOPE = (
    "deepspeed_tpu/inference/sampling.py",
    "deepspeed_tpu/inference/engine.py",
    "deepspeed_tpu/inference/scheduler.py",
    "deepspeed_tpu/inference/router.py",
)


# ----------------------------------------------------------------------
# bitwise-pin registry (D002 input)
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BitwisePin:
    """What bitwise identity one canonical program declares, and which
    mesh axes that identity re-lays-out.

    program: ledger key (ds_budget canonical-program naming)
    pins: human-readable identity names (doc + ledger, not semantics)
    mesh_axes: ordered (name, size) pairs — row-major device order,
        the layout replica groups are matched against
    varying_axes: axes the pinned identity changes across (EP=1 vs
        EP=N varies 'expert'; the P/V pipeline pin varies 'pipe').
        A fp additive reduce spanning one of these is a D002 hazard.
    waived: ((reduce-class key, reason), ...) — reviewed acceptances;
        the class key is `op:kind:dtype:axes=a|b` as reduce_ledger
        spells it. Waivers are committed in DETERMINISM.json, so
        growing one is a reviewed diff, never a silent drift."""

    program: str
    pins: Tuple[str, ...] = ("rerun_bitwise",)
    mesh_axes: Tuple[Tuple[str, int], ...] = ()
    varying_axes: Tuple[str, ...] = ()
    waived: Tuple[Tuple[str, str], ...] = ()

    def as_ledger(self) -> Dict:
        return {
            "pins": list(self.pins),
            "mesh_axes": [[n, s] for n, s in self.mesh_axes],
            "varying_axes": list(self.varying_axes),
            "waived": [[k, r] for k, r in self.waived],
        }


# The canonical programs' declared identities (docs/determinism.md).
# Waivers name the accepted hazard AND the dynamic gate that pins the
# identity empirically — the capture -> check -> override workflow.
BITWISE_PINS: Dict[str, BitwisePin] = {
    "train_step": BitwisePin(
        program="train_step",
        pins=("rerun_bitwise",),
        mesh_axes=(("data", 4), ("model", 2)),
        varying_axes=(),
    ),
    "train_step_moe": BitwisePin(
        program="train_step_moe",
        pins=("loss_bitwise_across_ep",),
        mesh_axes=(("data", 2), ("expert", 2), ("model", 2)),
        varying_axes=("expert",),
        waived=(
            ("all-reduce:add:f32:axes=expert",
             "shared (non-expert) params are replicated over the "
             "expert axis, so their grad reduce treats it as extra "
             "data parallelism; the EP=1 == EP=N loss identity these "
             "sums feed is pinned dynamically (tests/test_moe.py "
             "ep-vs-dp bitwise parity)"),
            ("all-reduce:add:f32:axes=data|expert",
             "fused data+expert grad reduce for shared params — same "
             "class as axes=expert, same dynamic pin"),
        ),
    ),
    "train_step_pipe3d": BitwisePin(
        program="train_step_pipe3d",
        pins=("loss_bitwise_across_pv",),
        mesh_axes=(("pipe", 2), ("data", 2), ("model", 2)),
        varying_axes=("pipe",),
        waived=(
            ("all-reduce:add:f32:axes=pipe",
             "stage-replicated grads and the microbatch loss "
             "accumulator reduce over the pipe axis; the V-schedule "
             "loss parity these sums feed is pinned dynamically "
             "(tests/test_pipeline.py interleave-vs-flat parity)"),
            ("all-reduce:add:f32:axes=pipe|model",
             "fused pipe+model reduce of the scalar loss/z-stat term "
             "— same class as axes=pipe, same dynamic pin"),
        ),
    ),
    "serving_decode_w8": BitwisePin(
        program="serving_decode_w8",
        pins=("token_identity_across_tp",),
        mesh_axes=(("model", 8),),
        varying_axes=("model",),
    ),
    "serving_sample_w8": BitwisePin(
        program="serving_sample_w8",
        pins=("replay_bitwise",),
        mesh_axes=(),
        varying_axes=(),
    ),
}


def pin_for(label: str,
            mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
            ) -> BitwisePin:
    """The registered pin for `label`, or a default rerun-only pin
    (varying_axes=() — D002 stays quiet on unregistered programs, per
    the registry contract). `mesh_axes` overrides the registered
    layout with the program's ACTUAL mesh (engine.sanitize passes its
    own — a user mesh need not match the canonical one)."""
    pin = BITWISE_PINS.get(label)
    if pin is None:
        pin = BitwisePin(program=label,
                         mesh_axes=tuple(mesh_axes or ()))
    elif mesh_axes is not None:
        pin = dataclasses.replace(pin, mesh_axes=tuple(mesh_axes))
    return pin


# ----------------------------------------------------------------------
# D001: layout-dependent PRNG (pre-opt HLO level)
# ----------------------------------------------------------------------

def check_rng_discipline(hlo_text: str, label: str = "program",
                         allow_manual: bool = False) -> SanitizerReport:
    """D001 over one program's (preferably pre-opt) HLO text.

    A DRAW (key-derivation ops — split/fold_in — compute the same bits
    on every layout; only draws consume the non-partitionable threefry
    counter) is a finding when its result is pinned to a mesh-TILED
    sharding, its seed operand arrives tiled, or it executes inside a
    shard_map manual context (unless `allow_manual` — deliberate
    per-shard draws whose keys are per-shard by construction). A
    replicated pin on the draw (`_replicated_draw` /
    `with_sharding_constraint(x, P())`) is the fix and the
    all-clear."""
    from ..profiling.hlo import parse_hlo_rng_ops

    rep = SanitizerReport(label=label)
    for rec in parse_hlo_rng_ops(hlo_text):
        if rec["kind"] != "draw":
            continue
        where = f"{rec['computation']}/{rec['name']} ({rec['algo']})"
        if rec["manual"] and not allow_manual:
            rep.findings.append(Finding(
                rule="D001", path=label, line=0, severity="error",
                message=f"rng draw {where} inside a shard_map manual "
                        "context: each shard advances its own threefry "
                        "counter, so the bits depend on the mesh layout",
                fix_hint="hoist the draw above the shard_map (replicated"
                         " key, broadcast the bits), or register the "
                         "program with allow_manual=True if per-shard "
                         "draws are the design (document WHY the keys "
                         "are layout-stable)"))
        elif rec["sharding_class"] == "tiled":
            rep.findings.append(Finding(
                rule="D001", path=label, line=0, severity="error",
                message=f"rng draw {where} result constrained to mesh-"
                        f"tiled sharding {{{rec['sharding']}}}: threefry"
                        " is not partitionable — each layout computes "
                        "different bits (the PR-14 EP=1 != EP=N class)",
                fix_hint="pin the draw replicated: wrap it in the "
                         "_replicated_draw idiom (jax.lax."
                         "with_sharding_constraint(draw, P()))"))
        elif rec["sharding_class"] in ("replicated", "maximal"):
            continue
        elif rec["seed_sharding_class"] == "tiled":
            rep.findings.append(Finding(
                rule="D001", path=label, line=0, severity="error",
                message=f"rng draw {where} seed operand "
                        f"({rec['seed']}) arrives mesh-tiled "
                        f"{{{rec['seed_sharding']}}}: per-shard key "
                        "slices make the draw layout-dependent",
                fix_hint="replicate the key before drawing "
                         "(with_sharding_constraint(key, P())), then "
                         "pin the draw result replicated too"))
    return rep


def rng_ledger(hlo_text: str) -> Dict[str, int]:
    """Per-class rng-op counts for one program's HLO text — the D001
    half of the committed DETERMINISM.json ledger. Class key:
    `form:algo:kind:sharding_class[:manual]`."""
    from ..profiling.hlo import parse_hlo_rng_ops

    counts: Dict[str, int] = {}
    for rec in parse_hlo_rng_ops(hlo_text):
        key = (f"{rec['form']}:{rec['algo']}:{rec['kind']}:"
               f"{rec['sharding_class']}")
        if rec["manual"]:
            key += ":manual"
        counts[key] = counts.get(key, 0) + 1
    return dict(sorted(counts.items()))


# ----------------------------------------------------------------------
# D002: reassociation hazards on bitwise-pinned programs
# ----------------------------------------------------------------------

def _axis_group_set(mesh_axes: Sequence[Tuple[str, int]],
                    subset: Sequence[str]) -> frozenset:
    """The replica groups (as a frozenset of frozensets of device ids)
    of a collective spanning exactly `subset` of `mesh_axes`, under
    row-major device ordering."""
    names = [n for n, _ in mesh_axes]
    sizes = [s for _, s in mesh_axes]
    groups: Dict[tuple, List[int]] = {}
    total = 1
    for s in sizes:
        total *= s
    for dev in range(total):
        coords, rem = [], dev
        for s in reversed(sizes):
            coords.append(rem % s)
            rem //= s
        coords.reverse()
        fixed = tuple(c for n, c in zip(names, coords) if n not in subset)
        groups.setdefault(fixed, []).append(dev)
    return frozenset(frozenset(g) for g in groups.values())


def match_group_axes(groups: List[List[int]],
                     mesh_axes: Sequence[Tuple[str, int]],
                     ) -> Optional[Tuple[str, ...]]:
    """Which mesh axes one collective's replica groups span: the
    (unique, order-preserved) axis subset whose row-major groups equal
    `groups` as sets. () for unstated/flat groups (spans the world);
    None when no subset matches (a layout the registry's mesh cannot
    express — treated as spanning everything)."""
    if not groups:
        return ()
    if not mesh_axes:
        return None
    names = [n for n, _ in mesh_axes]
    gset = frozenset(frozenset(g) for g in groups)
    for r in range(1, len(names) + 1):
        for subset in itertools.combinations(names, r):
            if _axis_group_set(mesh_axes, subset) == gset:
                return subset
    return None


def _reduce_class(rec: Dict, axes: Optional[Tuple[str, ...]],
                  world: Sequence[str]) -> str:
    if axes is None:
        spelled = "unmatched"
    elif axes == ():
        spelled = "|".join(world) if world else "world"
    else:
        spelled = "|".join(axes)
    return f"{rec['op']}:{rec['reduce_kind']}:{rec['dtype']}:axes={spelled}"


def check_reassociation(compiled_text: str, pin: BitwisePin,
                        label: str = "program") -> SanitizerReport:
    """D002 over one COMPILED program (post-partitioning text — where
    the SPMD partitioner's collectives and replica groups live),
    against the program's bitwise pin.

    Only fp ADDITIVE reduce collectives can reassociate; max/min/and/or
    select and integer adds are exact. A hazard needs its groups to
    span a pin-declared varying axis (or to fail to match the
    registered mesh at all — conservatively treated as spanning
    everything) and to not carry a committed waiver."""
    from ..profiling.hlo import FLOAT_DTYPES, parse_hlo_reduce_collectives

    rep = SanitizerReport(label=label)
    if not pin.varying_axes:
        return rep  # unpinned-across-layouts: nothing to protect
    world = [n for n, _ in pin.mesh_axes]
    waived = {k for k, _ in pin.waived}
    for rec in parse_hlo_reduce_collectives(compiled_text):
        if rec["reduce_kind"] not in ("add",) or \
                rec["dtype"] not in FLOAT_DTYPES:
            continue
        axes = match_group_axes(rec["groups"], pin.mesh_axes)
        spanned = set(world if axes in (None, ()) else axes)
        if not (spanned & set(pin.varying_axes)):
            continue
        key = _reduce_class(rec, axes, world)
        if key in waived:
            continue
        rep.findings.append(Finding(
            rule="D002", path=label, line=0, severity="error",
            message=f"{rec['name']}: {key} — a floating-point additive "
                    f"reduce spanning layout-varying axis(es) "
                    f"{sorted(spanned & set(pin.varying_axes))} on a "
                    f"program that pins {list(pin.pins)}: re-laying-out "
                    "that axis reorders the partial sums, so the "
                    "pinned bitwise identity holds only by accident",
            fix_hint="make the reduction layout-invariant (fixed tree "
                     "order / integer or compensated accumulation), "
                     "drop the varying axis from the pin, or commit a "
                     "waiver for this reduce class in BITWISE_PINS "
                     "with the dynamic gate that covers it"))
    return rep


def reduce_ledger(compiled_text: str, pin: BitwisePin) -> Dict[str, int]:
    """Per-class fp-additive-reduce counts for one compiled program —
    the D002 half of the DETERMINISM.json ledger (every class is
    recorded, hazardous or not: a class APPEARING is a reviewed
    diff)."""
    from ..profiling.hlo import FLOAT_DTYPES, parse_hlo_reduce_collectives

    world = [n for n, _ in pin.mesh_axes]
    counts: Dict[str, int] = {}
    for rec in parse_hlo_reduce_collectives(compiled_text):
        if rec["reduce_kind"] not in ("add",) or \
                rec["dtype"] not in FLOAT_DTYPES:
            continue
        key = _reduce_class(
            rec, match_group_axes(rec["groups"], pin.mesh_axes), world)
        counts[key] = counts.get(key, 0) + 1
    return dict(sorted(counts.items()))


def program_determinism(preopt_text: Optional[str],
                        compiled_text: Optional[str],
                        label: str,
                        pin: Optional[BitwisePin] = None,
                        allow_manual: bool = False,
                        ) -> Tuple[SanitizerReport, Dict]:
    """(merged D001+D002 report, ledger entry) for one program — the
    unit the ds_determinism gate captures per canonical program and
    engine.sanitize() folds into its report."""
    from .report import merge_reports

    pin = pin or pin_for(label)
    reports, entry = [], {"pin": pin.as_ledger()}
    if preopt_text:
        reports.append(check_rng_discipline(
            preopt_text, label=label, allow_manual=allow_manual))
        entry["rng_ops"] = rng_ledger(preopt_text)
    if compiled_text:
        reports.append(check_reassociation(compiled_text, pin,
                                           label=label))
        entry["reduce_classes"] = reduce_ledger(compiled_text, pin)
    return merge_reports(label, *reports), entry


# ----------------------------------------------------------------------
# D003: host-side ordering nondeterminism (AST level)
# ----------------------------------------------------------------------

_ENUM_CALLS = ("listdir", "scandir", "glob", "iglob", "iterdir",
               "rglob")
_WALLCLOCK_CALLS = ("time.time", "datetime.now", "datetime.utcnow",
                    "datetime.today", "datetime.datetime.now",
                    "datetime.datetime.utcnow")
_GLOBAL_RANDOM_FNS = ("random", "randint", "randrange", "shuffle",
                      "choice", "choices", "sample", "uniform",
                      "gauss")


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_capture_file(relpath: str) -> bool:
    return os.path.basename(relpath).startswith("ds_") and \
        relpath.replace(os.sep, "/").startswith("scripts/")


def _mtime_only_key(key: ast.AST) -> bool:
    """A sort key that is getmtime (or st_mtime) ALONE — ties fall
    back to enumeration order. A lambda returning a tuple with a
    filename tie-break is the fix and does not match."""
    if _dotted(key).endswith(("getmtime", "getctime", "getatime")):
        return True
    if isinstance(key, ast.Lambda):
        body = key.body
        if isinstance(body, ast.Call) and \
                _dotted(body.func).endswith(
                    ("getmtime", "getctime", "getatime")):
            return True
        if isinstance(body, ast.Attribute) and \
                body.attr in ("st_mtime", "st_ctime", "st_atime"):
            return True
    return False


def _d003_findings(tree: ast.Module, relpath: str) -> List[Finding]:
    findings: List[Finding] = []
    # every node textually inside a sorted(...) call is order-safe
    inside_sorted: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _dotted(node.func).split(".")[-1] == "sorted":
            for a in node.args:
                inside_sorted.update(id(n) for n in ast.walk(a))
    capture = _is_capture_file(relpath)

    def emit(rule_msg: str, node: ast.AST, hint: str) -> None:
        findings.append(Finding(
            rule="D003", path=relpath,
            line=getattr(node, "lineno", 0), severity="error",
            message=rule_msg, fix_hint=hint))

    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            if isinstance(it, (ast.Set, ast.SetComp)) or (
                    isinstance(it, ast.Call)
                    and _dotted(it.func) == "set"):
                emit("iteration over a set: element order follows the "
                     "hash seed, so anything it feeds (committed JSON, "
                     "ledger rows) differs across interpreter runs",
                     it, "iterate sorted(...) over the set")
            continue
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        short = callee.split(".")[-1]
        if short in _ENUM_CALLS and id(node) not in inside_sorted:
            emit(f"{callee}() without sorted(): filesystem enumeration "
                 "order is kernel/filesystem-dependent — any artifact "
                 "or tag decision it feeds differs across runs",
                 node, "wrap the enumeration in sorted(...)")
        if short in ("sort", "sorted"):
            for kw in node.keywords:
                if kw.arg == "key" and _mtime_only_key(kw.value):
                    emit("sort keyed on mtime alone: equal timestamps "
                         "(same-second saves, copied trees) leave the "
                         "order to the underlying enumeration",
                         node, "tie-break deterministically: key=lambda "
                               "p: (os.path.getmtime(p), p)")
        if callee == "json.dump" and not any(
                kw.arg == "sort_keys" for kw in node.keywords):
            emit("json.dump without sort_keys=True: dict order follows "
                 "insertion (and any set/hash influence upstream), so "
                 "the committed artifact is not byte-stable",
                 node, "pass sort_keys=True")
        if capture:
            if callee in _WALLCLOCK_CALLS:
                emit(f"{callee}() in a capture path: wall-clock values "
                     "in a committed artifact make every capture a "
                     "diff", node,
                     "drop the timestamp from the artifact, or move it "
                     "to stderr logging")
            if callee in ("random.Random", "np.random.default_rng",
                          "numpy.random.default_rng") and not node.args:
                emit(f"unseeded {callee}() in a capture path: the "
                     "ledger inherits process entropy", node,
                     "pass an explicit seed")
            if callee.startswith("random.") and \
                    short in _GLOBAL_RANDOM_FNS:
                emit(f"{callee}() uses the process-global RNG in a "
                     "capture path", node,
                     "draw from a seeded random.Random(seed) instance")
    return findings


# ----------------------------------------------------------------------
# D004: serving draw-key discipline (AST level)
# ----------------------------------------------------------------------

_JAX_DRAW_FNS = ("uniform", "normal", "truncated_normal", "gumbel",
                 "categorical", "bernoulli", "randint", "choice",
                 "exponential", "laplace", "poisson", "gamma", "beta")
_NP_GLOBAL_DRAWS = ("normal", "uniform", "randint", "random", "choice",
                    "shuffle", "permutation", "rand", "randn")


def _enclosing_env(tree: ast.Module) -> Dict[int, Dict[str, ast.AST]]:
    """{id(function node): {name: value expr}} for simple assignments —
    the one-hop resolution environment the fold_in search walks."""
    envs: Dict[int, Dict[str, ast.AST]] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            continue
        env: Dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = node.value
        envs[id(fn)] = env
    return envs


def _derives_from_fold_in(expr: ast.AST, env: Dict[str, ast.AST],
                          depth: int = 8) -> bool:
    if depth <= 0:
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and \
                _dotted(node.func).split(".")[-1] in ("fold_in",
                                                      "fold_in_key"):
            return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in env:
            nxt = env[node.id]
            if nxt is not expr and _derives_from_fold_in(
                    nxt, {k: v for k, v in env.items()
                          if k != node.id}, depth - 1):
                return True
    return False


def _d004_findings(tree: ast.Module, relpath: str) -> List[Finding]:
    findings: List[Finding] = []
    envs = _enclosing_env(tree)
    # map each call to its nearest enclosing function's env
    stack: List[ast.AST] = []

    def emit(node: ast.AST, msg: str, hint: str) -> None:
        findings.append(Finding(
            rule="D004", path=relpath,
            line=getattr(node, "lineno", 0), severity="error",
            message=msg, fix_hint=hint))

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()
            return
        if isinstance(node, ast.Call):
            callee = _dotted(node.func)
            short = callee.split(".")[-1]
            env = envs.get(id(stack[-1]), {}) if stack else {}
            if "random." in callee and short in _JAX_DRAW_FNS and \
                    not callee.startswith(("np.", "numpy.")):
                key = node.args[0] if node.args else None
                if isinstance(key, ast.Call) and _dotted(
                        key.func).split(".")[-1] == "PRNGKey" and \
                        key.args and isinstance(key.args[0],
                                                ast.Constant):
                    emit(node,
                         f"{callee}() keyed on a literal PRNGKey: every"
                         " request and every position draws the same "
                         "bits — neither seed, stream, nor position "
                         "reaches the key",
                         "derive the key from the request seed, "
                         "fold_in the stream id and the position")
                elif key is not None and not _derives_from_fold_in(
                        key, env):
                    emit(node,
                         f"{callee}() key does not derive through "
                         "fold_in: the draw is position-independent, "
                         "so a requeue-for-recompute replays DIFFERENT "
                         "bits than the original decode step",
                         "key each draw as fold_in(stream_key, "
                         "position) — sampling.sample_tokens is the "
                         "reference shape")
            if (callee.startswith(("np.random.", "numpy.random."))
                    and short in _NP_GLOBAL_DRAWS):
                emit(node,
                     f"{callee}() draws from numpy's process-global "
                     "RNG in a serving path: replays and reruns "
                     "diverge",
                     "thread a seeded np.random.Generator (or derive "
                     "from the request seed)")
            if callee in ("np.random.default_rng",
                          "numpy.random.default_rng") and not node.args:
                emit(node,
                     f"unseeded {callee}() in a serving path: draw "
                     "streams are not replayable",
                     "seed from the request (seed, stream) pair")
            if callee == "random.Random" and not node.args:
                emit(node,
                     "unseeded random.Random() in a serving path",
                     "seed from the request (seed, stream) pair")
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(tree)
    return findings


# ----------------------------------------------------------------------
# AST drivers (shared pragma machinery with ds-lint)
# ----------------------------------------------------------------------

def _scan_sources(sources: Iterable[Tuple[str, str]],
                  findings_fn) -> LintReport:
    from .lint import _split_suppressed

    report = LintReport()
    for relpath, src in sources:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            report.findings.append(Finding(
                rule="D000", path=relpath, line=e.lineno or 0,
                severity="error", message=f"syntax error: {e.msg}",
                fix_hint=""))
            report.files_checked += 1
            continue
        found = findings_fn(tree, relpath)
        found.sort(key=lambda f: (f.path, f.line, f.rule))
        active, suppressed = _split_suppressed(found, src.splitlines())
        report.findings.extend(active)
        report.suppressed.extend(suppressed)
        report.files_checked += 1
    return report


def _iter_scope(scope: Sequence[str], base: str,
                ) -> Iterable[Tuple[str, str]]:
    for entry in scope:
        path = os.path.join(base, entry)
        if os.path.isfile(path):
            files = [path]
        else:
            files = []
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        for f in files:
            with open(f, "r", encoding="utf-8") as fh:
                yield os.path.relpath(f, base), fh.read()


def check_host_ordering(base: str,
                        scope: Sequence[str] = ORDERING_SCOPE,
                        sources: Optional[Iterable[Tuple[str, str]]]
                        = None) -> LintReport:
    """D003 over the committed-artifact emitters (`scope` is repo-
    relative, resolved against `base`; pass `sources` as
    (relpath, source) pairs to scan in-memory instead)."""
    return _scan_sources(sources if sources is not None
                         else _iter_scope(scope, base), _d003_findings)


def check_draw_keys(base: str,
                    scope: Sequence[str] = DRAW_KEY_SCOPE,
                    sources: Optional[Iterable[Tuple[str, str]]]
                    = None) -> LintReport:
    """D004 over the serving draw paths (same calling convention as
    check_host_ordering)."""
    return _scan_sources(sources if sources is not None
                         else _iter_scope(scope, base), _d004_findings)
