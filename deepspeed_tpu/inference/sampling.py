"""On-device token sampling for the serving engine.

TPU-native redesign of the reference's sampling story: FastGen gathers
last-token logits on device (ref: inference/v2/kernels/ragged_ops/
logits_gather/) and MII applies the HF LogitsProcessor chain GPU-side;
the v1 engine inherits HF `generate` sampling (ref:
inference/engine.py:613). Here the whole chain — repetition penalty,
temperature, top-k, top-p, and the categorical draw — runs INSIDE the
compiled decode program, so a decode step returns token ids ([S] int32)
instead of shipping [S, vocab] fp32 logits to the host (8-13 MB/step at
batch 64 — round 3's structural serving-latency tax).

Design notes (XLA-first):
- the categorical draw is GUMBEL-MAX: argmax(logits/T + G),
  G = -log(-log(U)). Exact for categoricals, needs no cumsum/sort, and
  is replayable: the same threefry key on any backend yields the same
  U, so a host oracle given the same logits and key reproduces the
  token bit-exactly (tested in tests/test_sampling.py).
- top-p needs sorted cumulative mass; sorting 32k logits per step is
  VPU-hostile, so the CANDIDATES come from lax.top_k (width
  cand_width, default 256) while their masses come from the full
  softmax (or, after top-k, the k survivors — the HF processor-chain
  order). Exact whenever the nucleus fits in the candidate width; the
  host oracle applies the same truncation. The reference's sampler
  post-processes on full vocab — document the difference, don't hide
  it.
- repetition penalty needs the seen-token set; a [S, vocab] presence
  bitmap rides the decode scan and is updated with max(presence,
  one_hot(token)) — no scatter (XLA scatter carries a fixed multi-ms
  cost on TPU, docs/PROFILE_r02.md).
- per-sequence PRNG streams: key_i = fold_in(base, slot_i), step t uses
  fold_in(key_i, t) — batch composition never changes a sequence's
  stream (the host sampler had the same property via per-uid
  np.random.Generator).
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """STATIC sampling knobs (compiled into the decode program; the
    engine caches one program per distinct config). Scalar knobs that
    could be traced (temperature, top_p, penalty) are still static
    here: serving configs change rarely and static values let XLA fold
    the filter chain."""

    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    cand_width: int = 256  # top-p candidate pool (exactness bound)

    @property
    def greedy(self) -> bool:
        return (not self.do_sample) or self.temperature <= 0.0

    @property
    def needs_presence(self) -> bool:
        return self.repetition_penalty != 1.0

    def key(self):
        return dataclasses.astuple(self)


def apply_penalty_and_filters(logits, cfg: SamplingConfig,
                              presence: Optional[Any] = None):
    """[S, V] f32 logits -> filtered logits (still [S, V]; filtered-out
    entries at -inf). CTRL repetition-penalty rule (divide positive
    seen logits, multiply negative — ref HF RepetitionPenaltyLogitsProcessor,
    which the reference engine inherits), then temperature, then top-k,
    then top-p."""
    logits = logits.astype(jnp.float32)
    if cfg.needs_presence and presence is not None:
        seen = presence.astype(jnp.bool_)
        pen = jnp.float32(cfg.repetition_penalty)
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / pen, logits * pen), logits)
    if cfg.greedy:
        return logits
    logits = logits / jnp.float32(max(cfg.temperature, 1e-6))
    V = logits.shape[-1]
    k_eff = 0
    if cfg.top_k and 0 < cfg.top_k < V:
        k_eff = cfg.top_k
    need_pool = k_eff or (0.0 < cfg.top_p < 1.0)
    if need_pool:
        width = min(V, max(k_eff or 1, cfg.cand_width
                           if 0.0 < cfg.top_p < 1.0 else (k_eff or 1)))
        vals = jax.lax.top_k(logits, width)[0]  # [S, width] descending
        if k_eff:
            kth = vals[:, k_eff - 1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if 0.0 < cfg.top_p < 1.0:
            # HF chain order: TopP sees the TOP-K-FILTERED distribution
            # (renormalized over the k survivors); without top-k, masses
            # come from the FULL softmax (exp(v - lse(all logits))), not
            # a pool-renormalized one — pool renormalization would
            # inflate every cumulative mass by 1/pool_mass and push the
            # nucleus cutoff too deep (r4 review finding).
            if k_eff:
                pool = vals[:, :k_eff]
                lse = jax.scipy.special.logsumexp(pool, axis=-1,
                                                  keepdims=True)
            else:
                pool = vals
                lse = jax.scipy.special.logsumexp(logits, axis=-1,
                                                  keepdims=True)
            probs = jnp.exp(pool - lse)  # true masses, descending order
            csum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix reaching top_p (always the top-1)
            keep = (csum - probs) < jnp.float32(cfg.top_p)
            thr = jnp.min(jnp.where(keep, pool, jnp.inf), axis=-1)[:, None]
            logits = jnp.where(logits < thr, -jnp.inf, logits)
    return logits


def sample_tokens(logits, cfg: SamplingConfig, keys=None, step=None,
                  presence: Optional[Any] = None):
    """[S, V] logits -> [S] int32 tokens.

    keys: [S] per-sequence PRNG keys (jax.random key array); step: [S]
    int32 per-sequence draw counters (folded into the key so fused
    multi-step decode advances each stream exactly like stepwise)."""
    filtered = apply_penalty_and_filters(logits, cfg, presence)
    if cfg.greedy:
        return jnp.argmax(filtered, axis=-1).astype(jnp.int32)

    def draw(key, t, row):
        u = jax.random.uniform(
            jax.random.fold_in(key, t), row.shape,
            minval=jnp.float32(1e-20), maxval=1.0)
        g = -jnp.log(-jnp.log(u))
        return jnp.argmax(row + g).astype(jnp.int32)

    return jax.vmap(draw)(keys, step, filtered)


def update_presence(presence, tokens):
    """presence [S, V] uint8 | tokens [S] -> updated presence (one_hot
    max, not scatter)."""
    oh = jax.nn.one_hot(tokens, presence.shape[-1], dtype=presence.dtype)
    return jnp.maximum(presence, oh)


def presence_from_prompts(prompts, vocab: int, width: int):
    """Host-side initial presence for `width` slots from python/numpy
    token lists (rows beyond len(prompts) stay empty)."""
    import numpy as np

    out = np.zeros((width, vocab), np.uint8)
    for i, p in enumerate(prompts):
        toks = np.asarray(p, np.int64).ravel()
        toks = toks[(toks >= 0) & (toks < vocab)]
        out[i, toks] = 1
    return out


def host_oracle_token(logits, cfg: SamplingConfig, key, t,
                      presence_row=None) -> int:
    """Replay one draw host-side (numpy logits + the same key/step):
    must reproduce sample_tokens bit-exactly — the parity contract the
    tests pin down."""
    import numpy as np

    row = jnp.asarray(np.asarray(logits, np.float32))[None]
    pres = (jnp.asarray(np.asarray(presence_row, np.uint8))[None]
            if presence_row is not None else None)
    filtered = apply_penalty_and_filters(row, cfg, pres)
    if cfg.greedy:
        return int(jnp.argmax(filtered[0]))
    u = jax.random.uniform(jax.random.fold_in(key, t), filtered[0].shape,
                           minval=jnp.float32(1e-20), maxval=1.0)
    g = -jnp.log(-jnp.log(u))
    return int(jnp.argmax(filtered[0] + g))
