"""ZeRO++ (qwZ quantized weight gather) and MiCS/hpZ sub-group tests.

Ref model: tests/unit/runtime/zero/test_zeropp.py — the reference trains
tiny models with qwZ/hpZ on and checks convergence; here additionally
the sub-group sharding layout is asserted directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.ops import quantization as Q

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow

VOCAB = 128


def model_cfg(**kw):
    base = dict(vocab_size=VOCAB, n_layers=2, n_heads=4, d_model=64, max_seq=32,
                variant="llama", use_flash=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def ds_config(**kw):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "seed": 7,
        "steps_per_print": 1000,
    }
    base.update(kw)
    return base


def build_engine(**cfg_kw):
    mcfg = model_cfg()
    return ds.initialize(
        ds_config(**cfg_kw),
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
    )


def data(n=4, batch=16, seq=33, seed=0):
    r = np.random.default_rng(seed)
    return [{"tokens": r.integers(0, VOCAB, (batch, seq)).astype(np.int32)} for _ in range(n)]


def losses(engine, batches):
    return [engine.train_batch(b)["loss"] for b in batches]


class TestQuantizationKernels:
    def test_blockwise_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1000,)) * 3.0
        q, s = Q.quantize_blockwise(x, block=128)
        y = Q.dequantize_blockwise(q, s, x.shape)
        assert float(jnp.max(jnp.abs(x - y))) <= float(jnp.max(s)) / 2 + 1e-6

    def test_per_axis_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 64))
        q, s = Q.quantize_per_axis(x, 0)
        y = Q.dequantize_per_axis(q, s, 0)
        # per-channel int8: max error is half a quantization step per row
        err = jnp.max(jnp.abs(x - y), axis=1)
        assert (np.asarray(err) <= np.asarray(s) * 0.5 + 1e-6).all()

    def test_int4_pack_roundtrip(self):
        q = jnp.array([[-7, 3, 0, 7, -1, 5]], jnp.int8)
        assert (Q.unpack_int4(Q.pack_int4(q)) == q).all()

    def test_zero_block_stays_zero(self):
        x = jnp.zeros((256,))
        q, s = Q.quantize_blockwise(x, block=64)
        assert (Q.dequantize_blockwise(q, s, x.shape) == 0).all()


class TestHpZ:
    """zero_hpz_partition_size=k → data factored into data×zero."""

    @pytest.fixture(scope="class")
    def baseline(self):
        engine = build_engine(
            zero_optimization={"stage": 3, "param_persistence_threshold": 64})
        return losses(engine, data())

    def test_hpz_matches_full_sharding_trajectory(self, baseline):
        engine = build_engine(zero_optimization={
            "stage": 3, "param_persistence_threshold": 64,
            "zero_hpz_partition_size": 2,
        })
        assert engine.mesh.shape["zero"] == 2
        assert engine.mesh.shape["data"] == 4
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_hpz_shards_within_subgroup_only(self):
        engine = build_engine(zero_optimization={
            "stage": 3, "param_persistence_threshold": 64,
            "zero_hpz_partition_size": 2,
        })
        spec = str(engine.state.params["layers"]["w_in"].sharding.spec)
        assert "zero" in spec and "data" not in spec
        # replicated across the 2 groups of 4: each device holds 1/2, not 1/8
        full = build_engine(zero_optimization={
            "stage": 3, "param_persistence_threshold": 64})
        w_h = engine.state.params["layers"]["w_in"]
        w_f = full.state.params["layers"]["w_in"]
        assert (w_h.addressable_shards[0].data.size
                == 4 * w_f.addressable_shards[0].data.size)

    def test_explicit_mesh_zero_axis(self):
        """MiCS style: user sets mesh.zero directly."""
        engine = build_engine(
            mesh={"data": 4, "zero": 2},
            zero_optimization={"stage": 3, "param_persistence_threshold": 64})
        spec = str(engine.state.params["layers"]["w_in"].sharding.spec)
        assert "zero" in spec and "data" not in spec

    def test_hpz_indivisible_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            build_engine(mesh={"data": 3},
                         zero_optimization={"stage": 3,
                                            "zero_hpz_partition_size": 2})


class TestQwZ:
    """zero_quantized_weights: int8 weight gather, convergence parity."""

    def test_qwz_converges_with_parity(self):
        batches = data(8)
        base = build_engine(
            bf16={"enabled": True},
            zero_optimization={"stage": 3, "param_persistence_threshold": 64})
        qwz = build_engine(
            bf16={"enabled": True},
            zero_optimization={"stage": 3, "param_persistence_threshold": 64,
                               "zero_quantized_weights": True})
        lb = losses(base, batches)
        lq = losses(qwz, batches)
        assert lq[-1] < lq[0]  # training works
        # ≤1% loss delta over the run (the ZeRO++ convergence-parity bar)
        for a, b in zip(lb, lq):
            assert abs(a - b) / a < 0.01, (lb, lq)

    def test_qwz_with_hpz(self):
        batches = data(6)
        engine = build_engine(
            bf16={"enabled": True},
            zero_optimization={"stage": 3, "param_persistence_threshold": 64,
                               "zero_quantized_weights": True,
                               "zero_hpz_partition_size": 2})
        ls = losses(engine, batches)
        assert ls[-1] < ls[0]

    def test_qwz_reduces_allgather_bytes(self):
        """Comm-volume accounting: the compiled step's weight all-gathers
        move fewer bytes with qwZ (the ZeRO++ claim, measured from HLO)."""
        from deepspeed_tpu.profiling import collective_volumes

        def gather_bytes(**zkw):
            engine = build_engine(
                bf16={"enabled": True},
                zero_optimization={"stage": 3, "param_persistence_threshold": 64,
                                   **zkw})
            engine.train_batch(data(1)[0])
            vols = collective_volumes(engine._train_compiled)
            return vols.get("all-gather", {"bytes": 0})["bytes"]

        base = gather_bytes()
        qwz = gather_bytes(zero_quantized_weights=True)
        assert qwz < base, (qwz, base)

    def test_qgz_converges_with_parity(self):
        """zero_quantized_gradients: int8 two-hop grad reduce, ≤1% loss
        delta vs exact reduction (the ZeRO++ qgZ bar)."""
        batches = data(8)
        base = build_engine(zero_optimization={"stage": 2})
        qgz = build_engine(zero_optimization={"stage": 2,
                                              "zero_quantized_gradients": True})
        lb = losses(base, batches)
        lq = losses(qgz, batches)
        assert lq[-1] < lq[0]
        for a, b in zip(lb, lq):
            assert abs(a - b) / a < 0.01, (lb, lq)

    def test_qgz_int8_on_wire(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        engine = build_engine(zero_optimization={"stage": 2,
                                                 "zero_quantized_gradients": True})
        engine.train_batch(data(1)[0])
        recs = parse_hlo_collectives(engine._train_compiled.as_text())
        assert any(
            r["op"] in ("all-to-all", "all-gather", "collective-permute")
            and ("s8" in r["dtypes"] or "u8" in r["dtypes"])
            for r in recs
        ), recs

    def test_qgz_stage3_raises(self):
        with pytest.raises(NotImplementedError, match="stage"):
            build_engine(zero_optimization={"stage": 3,
                                            "zero_quantized_gradients": True})

    def test_qwz_noop_without_sharded_leaves(self):
        """stage<3 has no zero-sharded params → qwZ is an exact no-op."""
        batches = data(3)
        base = build_engine(zero_optimization={"stage": 1})
        qwz = build_engine(zero_optimization={"stage": 1,
                                              "zero_quantized_weights": True})
        np.testing.assert_allclose(losses(qwz, batches), losses(base, batches),
                                   rtol=1e-6)


class TestQgzCompositions:
    """qgZ x expert / pipeline (r3 VERDICT item 6): the guards are gone;
    the expert reduction happens natively inside the worker shard, the
    pipelined loss runs whole-batch in the worker accumulator."""

    def test_qgz_expert_axis_parity(self):
        """MoE + qgZ (expert=2 x data=2) tracks the UNquantized MoE
    engine within the block-quantization tolerance."""
        mcfg = model_cfg(n_experts=2, moe_top_k=1)
        mk = lambda **z: ds.initialize(
            ds_config(gradient_clipping=0,
                      mesh={"expert": 2, "data": 4},
                      zero_optimization=z or {"stage": 0}),
            loss_fn=T.make_loss_fn(mcfg, loss_chunks=1),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg))
        e0 = mk()
        batches = data(8, batch=e0.config.train_batch_size)
        base = losses(e0, batches)
        lq = losses(mk(stage=2, zero_quantized_gradients=True), batches)
        assert all(np.isfinite(l) for l in lq)
        np.testing.assert_allclose(lq, base, rtol=0.02)

    def test_qgz_pipeline_trains(self):
        mcfg = model_cfg(n_layers=4, pipeline_stages=2)
        eng = ds.initialize(
            ds_config(gradient_clipping=0,
                      train_micro_batch_size_per_gpu=1,
                      gradient_accumulation_steps=4,
                      mesh={"pipe": 2, "data": 4},
                      zero_optimization={"stage": 1,
                                         "zero_quantized_gradients": True}),
            loss_fn=T.make_pipelined_loss_fn(mcfg),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg),
            pipelined=True)
        b = data(1, batch=eng.config.train_batch_size)[0]
        ls = [eng.train_batch(b)["loss"] for _ in range(8)]
        assert all(np.isfinite(l) for l in ls)
        assert min(ls[4:]) < ls[0]
