"""Flops profiler from XLA cost analysis.

TPU-native redesign of the reference flops profiler
(ref: deepspeed/profiling/flops_profiler/profiler.py FlopsProfiler:28 —
module hooks + patched torch functionals counting MACs per call, tree
report print_model_profile:282). Under jit there are no module
boundaries to hook; the compiled program itself carries exact counts:
XLA cost analysis gives flops/bytes for the WHOLE optimized step —
including backward, optimizer math, and rematerialization — which the
hook-based reference approximates with a 3x fwd-flops heuristic.

The report combines:
  - compiled-step flops + memory traffic    (XLA cost_analysis)
  - per-collective comm volumes             (profiling/hlo.py)
  - measured step latency                   (engine ThroughputTimer)
  - device peak flops                       (platform/accelerator.py)
into achieved TFLOPs / MFU / bytes-per-step — the print_model_profile
summary block, minus the per-module tree (no modules under jit; use
jax.profiler traces for op-level timing).
"""

import sys
from typing import Any, Dict, Optional

from ..platform.accelerator import get_accelerator
from ..utils.logging import logger
from .hlo import collective_volumes


def get_step_profile(compiled) -> Dict[str, Any]:
    """Raw numbers for one compiled step (per device)."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    return {
        "flops_per_step": flops,
        "bytes_accessed": bytes_accessed,
        "collectives": collective_volumes(compiled),
    }


class FlopsProfiler:
    """Engine-facing profiler (ref: profiler.py FlopsProfiler API —
    start_profile/stop_profile/print_model_profile collapsed into
    profile(compiled, step_time_s) since counting is free here)."""

    def __init__(self, config, batch_size: Optional[int] = None):
        self.config = config
        self.batch_size = batch_size
        self._last: Optional[Dict[str, Any]] = None
        self._measured: Optional[Dict[str, Any]] = None

    def profile(self, compiled, step_time_s: Optional[float] = None,
                model_flops_per_step: Optional[float] = None) -> Dict[str, Any]:
        acc = get_accelerator()
        prof = get_step_profile(compiled)
        peak = acc.peak_flops()
        if step_time_s and step_time_s > 0:
            achieved = prof["flops_per_step"] / step_time_s
            prof["step_time_s"] = step_time_s
            prof["achieved_tflops"] = achieved / 1e12
            prof["hw_utilization"] = achieved / peak if peak else 0.0
            if model_flops_per_step:
                # MFU uses *model* flops (6ND), not XLA's count which
                # includes remat recompute — the standard definition.
                prof["model_flops_per_step"] = model_flops_per_step
                prof["mfu"] = model_flops_per_step / step_time_s / peak if peak else 0.0
            if self.batch_size:
                prof["samples_per_sec"] = self.batch_size / step_time_s
        self._last = prof
        return prof

    def print_profile(self, file=None) -> None:
        """ref: profiler.py print_model_profile:282 summary block."""
        if self._last is None:
            return
        p = self._last
        f = file or sys.stdout
        lines = [
            "-" * 62,
            "DeepSpeed-TPU Flops Profiler (XLA cost analysis)",
            f"  flops per step (XLA, incl. remat): {p['flops_per_step']:.3e}",
            f"  HBM bytes per step:                {p['bytes_accessed']:.3e}",
        ]
        if "achieved_tflops" in p:
            lines += [
                f"  step latency:                      {p['step_time_s']*1e3:.1f} ms",
                f"  achieved TFLOPs/device:            {p['achieved_tflops']:.1f}",
                f"  hardware utilization:              {p['hw_utilization']*100:.1f}%",
            ]
        if "mfu" in p:
            lines.append(
                f"  model flops utilization (MFU):     {p['mfu']*100:.1f}%")
        if "samples_per_sec" in p:
            lines.append(
                f"  samples/sec:                       {p['samples_per_sec']:.1f}")
        if p["collectives"]:
            lines.append("  collectives per step:")
            for op, v in sorted(p["collectives"].items()):
                lines.append(
                    f"    {op:<22} x{int(v['count']):<4} {v['bytes']/1e6:8.2f} MB")
        else:
            lines.append("  collectives per step: none (single shard)")
        lines.append("-" * 62)
        print("\n".join(lines), file=f)
        if self.config.output_file:
            with open(self.config.output_file, "a") as fh:
                print("\n".join(lines), file=fh)

    @property
    def last(self) -> Optional[Dict[str, Any]]:
        return self._last

    def print_model_profile(self, model_config, seq_len: int,
                            batch_size: Optional[int] = None,
                            module_depth: int = -1, top_modules: int = 0,
                            file=None) -> None:
        """Reference-style per-module tree (ref: profiler.py
        print_model_profile:282) — see module_profile_tree for how the
        numbers are derived under jit. When measure_module_latency ran,
        the MEASURED per-module device-time table follows the analytic
        tree (the reference's hook-timed latency column)."""
        step_t = (self._last or {}).get("step_time_s")
        print_model_profile(
            model_config, seq_len,
            batch_size=batch_size or self.batch_size or 1,
            step_time_s=step_t, module_depth=module_depth,
            top_modules=top_modules, file=file,
            output_file=self.config.output_file,
        )
        if self._measured is not None:
            from .latency import print_measured_profile

            print_measured_profile(self._measured, file=file)

    def measure_module_latency(self, engine, batch,
                               trace_dir: str = "/tmp/ds_module_trace",
                               steps: int = 3):
        """Trace real engine steps and attribute measured device time to
        the model's named-scope modules (profiling/latency.py); the
        result also feeds print_model_profile's measured table."""
        from .latency import measure_module_latency as _measure

        self._measured = _measure(engine, batch, trace_dir, steps=steps)
        return self._measured


# ---------------------------------------------------------------------------
# per-module tree (ref: profiler.py print_model_profile:282)
# ---------------------------------------------------------------------------

def module_profile_tree(cfg, seq_len: int, batch_size: int = 1
                        ) -> Dict[str, Any]:
    """Analytic per-module profile of one FORWARD pass of the in-tree
    transformer family: params / MACs-derived flops per module, nested
    like the reference's module tree.

    The reference counts these numbers with forward hooks + patched
    functionals per nn.Module call; under jit there are no module
    boundaries at runtime, but the model family's structure is known
    exactly, so the same counts come from the config in closed form
    (per-layer latency below is flops-proportional attribution of the
    measured step time — an estimate, clearly labeled; op-exact timing
    lives in the xplane traces, utils/profiler.py)."""
    E, H, KV, D = cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim
    F, V, L, S, B = cfg.ff_dim, cfg.vocab_size, cfg.n_layers, seq_len, \
        batch_size
    T = B * S  # tokens per step

    def mod(params, flops, children=None):
        d = {"params": int(params), "flops": float(flops)}
        if children:
            d["children"] = children
            d["params"] = int(sum(c["params"] for c in children.values())
                              + params)
            d["flops"] = float(sum(c["flops"] for c in children.values())
                               + flops)
        return d

    qkv_params = E * (H + 2 * KV) * D + (
        (H + 2 * KV) * D if cfg.has_qkv_bias else 0)
    attn = mod(0, 0, {
        "qkv_proj": mod(qkv_params, 2 * T * E * (H + 2 * KV) * D),
        # causal: ~S/2 keys per query
        "attn_scores": mod(0, 2 * T * H * D * S / 2),
        "attn_context": mod(0, 2 * T * H * D * S / 2),
        "out_proj": mod(H * D * E + (E if cfg.has_attn_out_bias else 0),
                        2 * T * H * D * E),
    })
    n_mats = (2 if cfg.is_gated else 1)
    mlp_in_p = n_mats * E * F + (F if cfg.has_mlp_bias else 0)
    X = max(cfg.n_experts, 1)
    # MoE: every token runs top_k experts' FFNs (capacity-free count)
    fan = cfg.moe_top_k if cfg.n_experts > 0 else 1
    mlp_children = {
        "in_proj" + ("_gate_up" if cfg.is_gated else ""):
            mod(mlp_in_p * X, fan * n_mats * 2 * T * E * F),
        "out_proj": mod((F * E + (E if cfg.has_mlp_bias else 0)) * X,
                        fan * 2 * T * F * E),
    }
    if cfg.n_experts > 0:
        mlp_children["router"] = mod(E * X, 2 * T * E * X)
    mlp = mod(0, 0, mlp_children)
    n_ln = 1 if cfg.shared_ln else 2
    layer = mod(0, 0, {
        "attention": attn,
        "mlp" if cfg.n_experts == 0 else "moe_mlp": mlp,
        "norms": mod(n_ln * E * (2 if cfg.norm_has_bias else 1),
                     n_ln * 5 * T * E),
    })
    top = {
        "embed": mod(V * E + (cfg.max_seq * E if cfg.use_learned_pos
                              else 0), 0),
        "layers": mod(0, 0, {f"layer_{i}": layer for i in range(L)}),
        "final_norm": mod(E * (2 if cfg.norm_has_bias else 1), 5 * T * E),
        "lm_head": mod(
            0 if cfg.tie_embeddings
            else E * V + (V if cfg.lm_head_bias else 0),
            2 * T * E * V),
    }
    return mod(0, 0, top)


def print_model_profile(cfg, seq_len: int, batch_size: int = 1,
                        step_time_s: Optional[float] = None,
                        module_depth: int = -1, top_modules: int = 0,
                        file=None, output_file: Optional[str] = None) -> None:
    """Depth-controlled per-module tree: params / fwd flops / % of model
    flops / (optional) flops-proportional share of the measured step
    time. module_depth=-1 prints everything; top_modules=k keeps only
    the k most expensive children per level (both knobs mirror the
    reference's print_model_profile)."""
    tree = module_profile_tree(cfg, seq_len, batch_size)
    total = tree["flops"] or 1.0
    lines = [
        "-" * 72,
        "DeepSpeed-TPU per-module profile "
        f"(fwd, batch {batch_size} x seq {seq_len})",
        f"{'module':<40}{'params':>10}{'fwd flops':>12}{'%':>6}"
        + (f"{'est ms':>8}" if step_time_s else ""),
    ]

    def fmt_n(n):
        for u, s in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
            if abs(n) >= u:
                return f"{n/u:.2f}{s}"
        return str(int(n))

    def walk(name, node, depth, indent):
        pct = node["flops"] / total * 100
        row = f"{'  '*indent + name:<40}{fmt_n(node['params']):>10}" \
              f"{fmt_n(node['flops']):>12}{pct:>5.1f}%"
        if step_time_s:
            row += f"{node['flops']/total*step_time_s*1e3:>8.2f}"
        lines.append(row)
        if module_depth != -1 and depth >= module_depth:
            return
        kids = list((node.get("children") or {}).items())
        kids.sort(key=lambda kv: -kv[1]["flops"])
        if top_modules:
            kids = kids[:top_modules]
        # identical repeated layers print once with a multiplier
        if name == "layers" and kids:
            k0_name, k0 = kids[0]
            lines.append(f"{'  '*(indent+1)}[x{len(kids)} identical layers"
                         f" — expanding {k0_name}]")
            walk(k0_name, k0, depth + 1, indent + 1)
            return
        for kname, kid in kids:
            walk(kname, kid, depth + 1, indent + 1)

    walk("model", tree, 0, 0)
    lines.append("-" * 72)
    out = "\n".join(lines)
    print(out, file=file or sys.stdout)
    if output_file:
        with open(output_file, "a") as fh:
            print(out, file=fh)
