"""Checkpoint save/load tests (ref model: tests/unit/checkpoint —
zero-sharded save/restore correctness incl. resharding)."""

import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
import pytest  # noqa: E402

pytestmark = pytest.mark.slow

VOCAB = 64


def build_engine(stage=2, mesh=None, **extra):
    mcfg = T.TransformerConfig(vocab_size=VOCAB, n_layers=2, n_heads=2, d_model=32,
                               max_seq=16, variant="llama", use_flash=False)
    return ds.initialize(
        {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": stage, "param_persistence_threshold": 32},
            "mesh": mesh or {"data": -1},
            "steps_per_print": 1000,
            **extra,
        },
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
    )


def batch(seed=0):
    r = np.random.default_rng(seed)
    return {"tokens": r.integers(0, VOCAB, (8, 17)).astype(np.int32)}


def test_save_load_roundtrip(tmp_path):
    e1 = build_engine()
    b = batch()
    for _ in range(3):
        e1.train_batch(b)
    tag = e1.save_checkpoint(str(tmp_path), client_state={"note": "hello"})
    loss_before = e1.train_batch(b)["loss"]

    e2 = build_engine()
    loaded_tag, client = e2.load_checkpoint(str(tmp_path))
    assert loaded_tag == tag
    assert client["note"] == "hello"
    assert e2.global_steps == 3
    loss_after = e2.train_batch(b)["loss"]
    np.testing.assert_allclose(loss_after, loss_before, rtol=1e-5)


def test_latest_tag_written(tmp_path):
    e = build_engine()
    e.train_batch(batch())
    e.save_checkpoint(str(tmp_path), tag="mytag")
    assert (tmp_path / "latest").read_text() == "mytag"


def test_cross_precision_load_bf16_to_fp32(tmp_path):
    """bf16 checkpoint (has fp32 master) → fp32 engine (no master): the
    master is the authoritative fp32 copy."""
    e1 = build_engine(bf16={"enabled": True})
    b = batch()
    for _ in range(2):
        e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path))

    e2 = build_engine()  # fp32
    e2.load_checkpoint(str(tmp_path))
    assert e2.state.master is None
    import jax.numpy as jnp

    assert e2.state.params["embed"].dtype == jnp.float32
    m1 = np.asarray(e1.state.master["embed"])
    m2 = np.asarray(e2.state.params["embed"])
    np.testing.assert_allclose(m1, m2, rtol=1e-6)


def test_cross_precision_load_fp32_to_bf16(tmp_path):
    e1 = build_engine()
    b = batch()
    e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path))

    e2 = build_engine(bf16={"enabled": True})
    e2.load_checkpoint(str(tmp_path))
    import jax.numpy as jnp

    assert e2.state.master is not None
    assert e2.state.params["embed"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(e1.state.params["embed"]), np.asarray(e2.state.master["embed"]), rtol=1e-6
    )


def test_fp16_checkpoint_into_fp32_engine(tmp_path):
    e1 = build_engine(fp16={"enabled": True})
    e1.train_batch(batch())
    e1.save_checkpoint(str(tmp_path))
    e2 = build_engine()
    e2.load_checkpoint(str(tmp_path))
    assert e2.state.loss_scale is None


def test_corrupt_latest_falls_back_to_verified_tag(tmp_path):
    """The crash-consistent resume path the elastic agent rides
    (docs/fault_tolerance.md): an engine whose newest checkpoint is
    corrupt (injected bitrot) must resume from the previous VERIFIED
    tag instead of wedging — engine.load_checkpoint goes through
    CheckpointEngine.resolve_verified_tag."""
    import os

    from deepspeed_tpu.resilience import corrupt_file

    e1 = build_engine()
    b = batch()
    e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path), tag="step1")
    e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path), tag="step2")
    state_dir = tmp_path / "step2" / "state"
    victims = [os.path.join(r, n)
               for r, _, ns in os.walk(state_dir) for n in ns]
    corrupt_file(max(victims, key=os.path.getsize))

    e2 = build_engine()
    tag, _ = e2.load_checkpoint(str(tmp_path))
    assert tag == "step1"
    assert e2.global_steps == e1.global_steps - 1


def test_injected_commit_crash_resumes_from_previous(tmp_path):
    """PR-7 satellite regression: a crash in the async-save commit
    window (state durable, markers unwritten) must leave 'latest' on
    the previous tag and resume from it."""
    import pytest as _pytest

    from deepspeed_tpu.resilience import (
        CheckpointCrashError, FaultPlan, armed)

    e1 = build_engine(checkpoint={"async_save": True})
    b = batch()
    e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path), tag="step1")
    e1.checkpoint_engine.wait()
    e1.train_batch(b)
    plan = FaultPlan([{"point": "checkpoint.commit", "kind": "raise",
                       "error": "ckpt_crash", "where": {"tag": "step2"}}])
    with armed(plan):
        with _pytest.raises(CheckpointCrashError):
            e1.save_checkpoint(str(tmp_path), tag="step2")
            e1.checkpoint_engine.wait()
    assert (tmp_path / "latest").read_text() == "step1"

    e2 = build_engine()
    tag, _ = e2.load_checkpoint(str(tmp_path))
    assert tag == "step1"


def test_reshard_zero_stage_across_load(tmp_path):
    """Save under ZeRO-2, load under ZeRO-3 with a different layout —
    the universal-checkpoint property (ref: deepspeed/checkpoint
    ds_to_universal.py) is native here because saved arrays are logical."""
    e1 = build_engine(stage=2)
    b = batch()
    for _ in range(2):
        e1.train_batch(b)
    e1.save_checkpoint(str(tmp_path))
    losses_src = e1.train_batch(b)["loss"]

    e2 = build_engine(stage=3)
    e2.load_checkpoint(str(tmp_path))
    losses_dst = e2.train_batch(b)["loss"]
    np.testing.assert_allclose(losses_dst, losses_src, rtol=1e-4)
