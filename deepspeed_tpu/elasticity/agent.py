"""Runtime failure detection + elastic restart.

TPU-native redesign of the reference's elastic agent
(ref: elasticity/elastic_agent.py:28 DSElasticAgent — a torchelastic
agent whose `_invoke_run` monitor loop (:121) polls worker health every
monitor_interval and tears down / restarts the world on failure).

The TPU shape (SURVEY §5): one controller process per host; XLA
collectives have NO timeout, so a dead or hung host leaves every
survivor blocked inside the next collective forever. Detection must
therefore happen OUTSIDE the compiled step, on the host control plane:

- every controller writes a monotonic **heartbeat file** around its
  step loop (`Heartbeat.beat`, wired into engine.train_batch when
  `DS_ELASTIC_HEARTBEAT_DIR` is set). The medium is a shared
  filesystem — the same medium the checkpoint engine already requires
  on a pod (GCS/NFS fuse) — so no extra service and no rank-0 single
  point of failure.
- a **HealthMonitor** thread on each controller scans peers'
  heartbeats; when one goes stale the monitor flips `degraded`, and
  the training loop's next `check()` raises WorldDegradedError BEFORE
  issuing another collective (survivors exit cleanly instead of
  hanging; their state is at the last committed checkpoint).
- a per-host **supervisor** (`run_elastic`) owns the worker process:
  it relaunches the world at the surviving size with a bumped
  generation, exactly DSElasticAgent's restart-and-continue journey.
  Workers resume from the last committed checkpoint; the elastic batch
  arithmetic (elasticity.compute_elastic_config, already enforced by
  the engine config) re-derives the SAME global batch at the new world
  size, and universal/orbax checkpoints make the resharded load legal
  (tests/test_elastic_autotune.py::TestElasticResume proves the
  trajectory continues).

Worker-side env contract (set by run_elastic):
  DS_ELASTIC_HEARTBEAT_DIR  — heartbeat directory (shared fs)
  DS_ELASTIC_GENERATION     — restart generation (0 = first launch)
  DS_ELASTIC_RESUME_DIR     — checkpoint dir to resume from (generation
                              > 0; workers load it if it has a 'latest')
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..resilience.faults import fault_point

HEARTBEAT_DIR_ENV = "DS_ELASTIC_HEARTBEAT_DIR"
GENERATION_ENV = "DS_ELASTIC_GENERATION"
RESUME_DIR_ENV = "DS_ELASTIC_RESUME_DIR"


class WorldDegradedError(RuntimeError):
    """A peer controller missed its heartbeat: the world is degraded and
    issuing further collectives would hang. Checkpoint (if state is
    clean) and exit; the supervisor restarts at the surviving size."""

    def __init__(self, failed_ranks: Sequence[int]):
        self.failed_ranks = list(failed_ranks)
        super().__init__(
            f"world degraded: no heartbeat from rank(s) {self.failed_ranks}"
        )


def _hb_path(hb_dir: str, rank: int) -> str:
    return os.path.join(hb_dir, f"hb_{rank}.json")


class Heartbeat:
    """One controller's liveness record: an atomically-replaced file
    carrying (rank, step, generation, wall time). Written around the
    step loop — a wedged step loop stops beating, which is exactly the
    failure the monitor must catch (a process can be alive and hung)."""

    def __init__(self, hb_dir: str, rank: int, generation: int = 0):
        self.dir = hb_dir
        self.rank = rank
        self.generation = generation
        os.makedirs(hb_dir, exist_ok=True)

    def beat(self, step: int) -> None:
        # chaos fault point: kind='skip' suppresses the write — an
        # alive-but-wedged controller, exactly what staleness detection
        # exists for (deterministic stall tests without real hangs)
        act = fault_point("heartbeat.beat", rank=self.rank)
        if act is not None and act.kind == "skip":
            return
        payload = json.dumps({
            "rank": self.rank, "step": int(step),
            "generation": self.generation, "time": time.time(),
        })
        tmp = _hb_path(self.dir, self.rank) + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, _hb_path(self.dir, self.rank))  # atomic publish


def scan_heartbeats(hb_dir: str, world: int,
                    generation: Optional[int] = None) -> Dict[int, dict]:
    """rank → latest heartbeat payload (missing/corrupt files omitted;
    `generation` filters out stale files from a previous incarnation)."""
    out: Dict[int, dict] = {}
    for r in range(world):
        try:
            with open(_hb_path(hb_dir, r)) as f:
                hb = json.load(f)
        except (OSError, ValueError):
            continue
        if generation is not None and hb.get("generation") != generation:
            continue
        out[r] = hb
    return out


class StalenessTracker:
    """Judge staleness by when THIS observer last saw a peer's heartbeat
    CONTENT change — never by comparing the peer's embedded wall clock
    against the local clock (cross-host clock skew would otherwise make
    a healthy peer look permanently stale, or silently stretch
    detection latency)."""

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._seen: Dict[int, Tuple[tuple, float]] = {}

    def observe(self, hbs: Dict[int, dict], now: float) -> List[int]:
        """Feed one scan; returns ranks whose content is stale by the
        LOCAL clock. Ranks that never produced a heartbeat are not
        reported (startup is the first-beat deadline's job)."""
        stale = []
        for r, hb in hbs.items():
            fp = (hb.get("step"), hb.get("time"))
            prev = self._seen.get(r)
            if prev is None or prev[0] != fp:
                self._seen[r] = (fp, now)
            elif now - prev[1] > self.timeout_s:
                stale.append(r)
        return stale


class HealthMonitor:
    """Background scanner of peer heartbeats (the worker-side half of
    DSElasticAgent._invoke_run's monitor loop).

    A peer is declared failed when it HAS beaten this generation but its
    latest beat is older than `timeout_s` (startup/compile time is
    excluded by the has-beaten condition; the supervisor separately
    bounds startup with its own first-beat deadline). The training loop
    calls `check()` between steps — before the next collective."""

    def __init__(self, hb_dir: str, rank: int, world: int,
                 timeout_s: float = 60.0, interval_s: float = 1.0,
                 generation: int = 0,
                 on_degraded: Optional[Callable[[List[int]], None]] = None):
        self.hb_dir = hb_dir
        self.rank = rank
        self.world = world
        self.timeout_s = timeout_s
        self.interval_s = interval_s
        self.generation = generation
        self.on_degraded = on_degraded
        # written by the monitor thread, read by the training loop's
        # check() — both sides go through _lock (C001; the monitor
        # flips the list exactly once per degradation)
        self._failed: List[int] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "HealthMonitor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ds-health-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- queries --------------------------------------------------------
    @property
    def failed_ranks(self) -> List[int]:
        with self._lock:
            return list(self._failed)

    @property
    def degraded(self) -> bool:
        return bool(self.failed_ranks)

    def check(self) -> None:
        """Raise WorldDegradedError if a peer died — call between steps,
        BEFORE issuing the next collective."""
        if self.degraded:
            raise WorldDegradedError(self.failed_ranks)

    # -- scanner --------------------------------------------------------
    def _run(self) -> None:
        tracker = StalenessTracker(self.timeout_s)
        while not self._stop.wait(self.interval_s):
            self._scan_once(tracker)

    def _scan_once(self, tracker: "StalenessTracker",
                   now: Optional[float] = None) -> None:
        """One heartbeat sweep (the _run loop body; the interleaving
        harness drives it directly — tests/test_concurrency.py)."""
        hbs = scan_heartbeats(self.hb_dir, self.world, self.generation)
        hbs.pop(self.rank, None)
        failed = tracker.observe(
            hbs, time.monotonic() if now is None else now)
        newly = False
        if failed:
            with self._lock:
                if not self._failed:
                    self._failed = list(failed)
                    newly = True
        # user callback OUTSIDE the lock: a callback that reads
        # failed_ranks (or takes its own locks) must not nest under
        # ours (C002 lock-order discipline)
        if newly and self.on_degraded is not None:
            try:
                self.on_degraded(failed)
            except Exception:  # callback must not kill the scanner
                pass


# ---------------------------------------------------------------------------
# supervisor: launch, watch, restart (the DSElasticAgent node loop)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_generation(
    cmd: List[str], num_procs: int, generation: int, hb_dir: str,
    hb_timeout_s: float, first_beat_timeout_s: float,
    devices_per_proc: int = 0, env_extra=None, timeout_s: float = 0,
) -> Tuple[int, str]:
    """One world incarnation: spawn num_procs ranks, watch BOTH process
    exits and heartbeat staleness (launch_local only catches death; a
    hung-but-alive rank needs the heartbeat). Returns (rc, reason) with
    reason in {'ok', 'exit', 'heartbeat', 'timeout', 'startup'}."""
    # chaos fault point: a raise here models the RELAUNCH itself failing
    # (rendezvous host gone, quota refused) — the supervisor must count
    # the burned generation and keep shrinking, not wedge
    fault_point("elastic.launch", generation=generation, world=num_procs)
    port = str(_free_port())
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []

    def _stream(p: subprocess.Popen, rank: int) -> None:
        for line in p.stdout:  # type: ignore[union-attr]
            sys.stdout.write(f"[g{generation} rank{rank}] {line}")
            sys.stdout.flush()

    for rank in range(num_procs):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["MASTER_ADDR"] = "127.0.0.1"
        env["MASTER_PORT"] = port
        env["WORLD_SIZE"] = str(num_procs)
        env["RANK"] = str(rank)
        env["LOCAL_RANK"] = str(rank)
        env[HEARTBEAT_DIR_ENV] = hb_dir
        env[GENERATION_ENV] = str(generation)
        if devices_per_proc:
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={devices_per_proc}"
            )
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, rank), daemon=True)
        t.start()
        threads.append(t)

    def _kill_all():
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()

    start = time.monotonic()
    rc, reason = 0, "ok"
    tracker = StalenessTracker(hb_timeout_s)
    try:
        while True:
            codes = [p.poll() for p in procs]
            failed = [(i, c) for i, c in enumerate(codes)
                      if c not in (None, 0)]
            if failed:
                rank, rc = failed[0]
                print(f"[elastic-agent g{generation}] rank {rank} exited "
                      f"rc={rc}; tearing down the world", file=sys.stderr)
                reason = "exit"
                _kill_all()
                break
            if all(c is not None for c in codes):
                break
            hbs = scan_heartbeats(hb_dir, num_procs, generation)
            # a rank that already exited CLEANLY stops beating by
            # design — never count its silence as a failure
            live_hbs = {r: hb for r, hb in hbs.items() if codes[r] is None}
            stale = tracker.observe(live_hbs, time.monotonic())
            if stale:
                print(f"[elastic-agent g{generation}] rank(s) {stale} "
                      "missed heartbeat; tearing down the world",
                      file=sys.stderr)
                rc, reason = 1, "heartbeat"
                _kill_all()
                break
            elapsed = time.monotonic() - start
            if (first_beat_timeout_s and len(hbs) < num_procs
                    and elapsed > first_beat_timeout_s):
                missing = sorted(set(range(num_procs)) - set(hbs))
                print(f"[elastic-agent g{generation}] rank(s) {missing} "
                      "never produced a first heartbeat", file=sys.stderr)
                rc, reason = 1, "startup"
                _kill_all()
                break
            if timeout_s and elapsed > timeout_s:
                print(f"[elastic-agent g{generation}] generation timeout",
                      file=sys.stderr)
                rc, reason = 124, "timeout"
                _kill_all()
                break
            time.sleep(0.2)
    finally:
        for t in threads:
            t.join(timeout=5)
    return rc, reason


def run_elastic(
    cmd: List[str],
    num_procs: int,
    heartbeat_dir: str,
    resume_dir: str,
    heartbeat_timeout_s: float = 30.0,
    first_beat_timeout_s: float = 300.0,
    min_procs: int = 1,
    max_restarts: int = 3,
    devices_per_proc: int = 0,
    env_extra=None,
    generation_timeout_s: float = 0,
    shrink_on_failure: bool = True,
    world_size_ok: Optional[Callable[[int], bool]] = None,
) -> int:
    """The DSElasticAgent journey as one call: launch the world, and on
    any rank's death OR missed heartbeat tear it down and relaunch at
    the surviving size (num_procs-1 per failure when shrink_on_failure,
    modeling a lost host — the reference restarts on whatever nodes the
    rendezvous still has, ref elastic_agent.py:121 _invoke_run). Workers
    resume from `resume_dir` (they receive it via DS_ELASTIC_RESUME_DIR
    and load the last committed checkpoint). Returns the final rc.

    world_size_ok: optional predicate over candidate world sizes — wire
    the elastic batch arithmetic here (e.g.
    `lambda w: w * devices in compute_elastic_config(...)[1]`) so the
    supervisor skips sizes every worker would reject at initialize()
    (ElasticityIncompatibleWorldSize) instead of burning a generation
    discovering it, mirroring the reference's pre-launch check
    (elasticity/elasticity.py compatibility gate)."""
    os.makedirs(heartbeat_dir, exist_ok=True)
    if world_size_ok is not None and not world_size_ok(num_procs):
        raise ValueError(
            f"initial world size {num_procs} fails world_size_ok — the "
            "launch would be rejected by every worker's elastic check")
    world = num_procs
    extra = dict(env_extra or {})
    extra[RESUME_DIR_ENV] = resume_dir
    for generation in range(max_restarts + 1):
        # clear heartbeats from the previous incarnation so staleness is
        # judged against THIS generation only
        for r in range(max(world, num_procs)):
            try:
                os.remove(_hb_path(heartbeat_dir, r))
            except OSError:
                pass
        try:
            rc, reason = _launch_generation(
                cmd, world, generation, heartbeat_dir,
                hb_timeout_s=heartbeat_timeout_s,
                first_beat_timeout_s=first_beat_timeout_s,
                devices_per_proc=devices_per_proc, env_extra=extra,
                timeout_s=generation_timeout_s,
            )
        except OSError as e:
            # the relaunch itself failed (spawn error, injected
            # elastic.launch fault): a burned generation, not a wedge —
            # fall through to the shrink-and-retry arm
            print(f"[elastic-agent g{generation}] launch failed: {e!r}",
                  file=sys.stderr)
            rc, reason = 1, "launch"
        if rc == 0:
            return 0
        if generation == max_restarts:
            print(f"[elastic-agent] giving up after {generation + 1} "
                  f"generations (last reason: {reason})", file=sys.stderr)
            return rc
        if shrink_on_failure and world > min_procs:
            world -= 1
            while (world >= min_procs and world_size_ok is not None
                   and not world_size_ok(world)):
                print(f"[elastic-agent] skipping world={world} "
                      "(elastic-incompatible)", file=sys.stderr)
                world -= 1
            if world < min_procs:
                print("[elastic-agent] no elastic-compatible world size "
                      f">= min_procs {min_procs} remains; giving up "
                      f"(last reason: {reason})", file=sys.stderr)
                return rc
        print(f"[elastic-agent] restarting at world={world} "
              f"(generation {generation + 1}, reason {reason})",
              file=sys.stderr)
    return rc


def heartbeat_from_env(rank: int) -> Optional[Heartbeat]:
    """Engine integration: a Heartbeat when the supervisor's env
    contract is present, else None (zero overhead outside elastic
    runs)."""
    hb_dir = os.environ.get(HEARTBEAT_DIR_ENV)
    if not hb_dir:
        return None
    gen = int(os.environ.get(GENERATION_ENV, "0"))
    return Heartbeat(hb_dir, rank, generation=gen)
