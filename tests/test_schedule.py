"""Schedule-analyzer tests (analysis/schedule.py, S007-S009).

Same contract as the sanitizer/cost-model suites: every check fires
EXACTLY ONCE on a deliberately seeded violation — a serialized
collective with hideable compute (S007), a DCN-straddling replica
group (S008), a comm-dominated critical path (S009) — and stays silent
on the real training / decode programs. The ds_schedule gate is
exercised end-to-end through its CLI against the committed
SCHEDULE.json and an injected regression, and the autotuner's AOT
score is checked to rank a known-good config above a deliberately
comm-bound one with a deterministic top-k list.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.analysis.schedule import (
    PodTopology,
    ScheduleAnalysis,
    analyze_compiled,
    analyze_schedule,
    check_exposed_comm,
    check_hierarchy_placement,
    check_step_time,
)
from deepspeed_tpu.models import transformer as T

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# 32 MiB all-gather over 8 devices whose consumer is scheduled at the
# END of the program, with two 4 MiB compute instructions in the gap —
# the serialized-but-hideable shape S007 exists to catch
_SERIALIZED_HLO = """\
HloModule seeded, is_scheduled=true, num_partitions=8

ENTRY %main (p: f32[1024,1024]) -> f32[1024,1024] {
  %p = f32[1024,1024]{1,0} parameter(0)
  %ag = f32[8192,1024]{1,0} all-gather(f32[1024,1024]{1,0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %m1 = f32[1024,1024]{1,0} multiply(f32[1024,1024]{1,0} %p, f32[1024,1024]{1,0} %p)
  %m2 = f32[1024,1024]{1,0} add(f32[1024,1024]{1,0} %m1, f32[1024,1024]{1,0} %m1)
  ROOT %use = f32[1024,1024]{1,0} slice(f32[8192,1024]{1,0} %ag), slice={[0:1024], [0:1024]}
}
"""

# the same program with the consumer scheduled IMMEDIATELY after the
# collective: nothing to hide behind, S007 stays quiet
_NO_SLACK_HLO = """\
HloModule seeded, is_scheduled=true, num_partitions=8

ENTRY %main (p: f32[1024,1024]) -> f32[1024,1024] {
  %p = f32[1024,1024]{1,0} parameter(0)
  %ag = f32[8192,1024]{1,0} all-gather(f32[1024,1024]{1,0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %use = f32[1024,1024]{1,0} slice(f32[8192,1024]{1,0} %ag), slice={[0:1024], [0:1024]}
  %m1 = f32[1024,1024]{1,0} multiply(f32[1024,1024]{1,0} %use, f32[1024,1024]{1,0} %use)
  ROOT %m2 = f32[1024,1024]{1,0} add(f32[1024,1024]{1,0} %m1, f32[1024,1024]{1,0} %m1)
}
"""

# async pair: the 32 MiB gather runs across an explicit start..done
# window holding the two compute instructions
_ASYNC_HLO = """\
HloModule seeded, is_scheduled=true, num_partitions=8

ENTRY %main (p: f32[1024,1024]) -> f32[1024,1024] {
  %p = f32[1024,1024]{1,0} parameter(0)
  %ag-start = (f32[1024,1024]{1,0}, f32[8192,1024]{1,0}) all-gather-start(f32[1024,1024]{1,0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %m1 = f32[1024,1024]{1,0} multiply(f32[1024,1024]{1,0} %p, f32[1024,1024]{1,0} %p)
  %m2 = f32[1024,1024]{1,0} add(f32[1024,1024]{1,0} %m1, f32[1024,1024]{1,0} %m1)
  %ag-done = f32[8192,1024]{1,0} all-gather-done((f32[1024,1024]{1,0}, f32[8192,1024]{1,0}) %ag-start)
  ROOT %use = f32[1024,1024]{1,0} slice(f32[8192,1024]{1,0} %ag-done), slice={[0:1024], [0:1024]}
}
"""

# 64 MiB world all-reduce next to almost no compute: the critical path
# is wire time — the S009 comm-dominated shape
_COMM_BOUND_HLO = """\
HloModule seeded, is_scheduled=true, num_partitions=8

ENTRY %main (p: f32[4096,4096]) -> f32[4096,4096] {
  %p = f32[4096,4096]{1,0} parameter(0)
  ROOT %ar = f32[4096,4096]{1,0} all-reduce(f32[4096,4096]{1,0} %p), replica_groups={}, to_apply=%sum
}
"""


def _seeded_analysis(text, bytes_accessed=1e9, hbm=1e9,
                     hide_sync_slack=True):
    """Analysis with a 1-second compute leg (unit weights scale off
    bytes_accessed/hbm) over a synthetic scheduled module."""
    return analyze_schedule(
        text, flops=0.0, bytes_accessed=bytes_accessed, peak_flops=1e12,
        hbm_bandwidth=hbm, n_devices=8, label="seeded",
        hide_sync_slack=hide_sync_slack)


# ----------------------------------------------------------------------
# hlo.py DAG extraction
# ----------------------------------------------------------------------

class TestComputationParser:
    def test_entry_and_regions_split(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_computations

        hlo = ("HloModule m, is_scheduled=true\n\n"
               "%region_0.6 (a: f32[], b: f32[]) -> f32[] {\n"
               "  %a = f32[] parameter(0)\n"
               "  %b = f32[] parameter(1)\n"
               "  ROOT %add.9 = f32[] add(f32[] %a, f32[] %b)\n"
               "}\n\n"
               "ENTRY %main (p: f32[4,8]) -> f32[] {\n"
               "  %p = f32[4,8]{1,0} parameter(0)\n"
               "  %c = f32[] constant(0)\n"
               "  ROOT %r = f32[] reduce(f32[4,8]{1,0} %p, f32[] %c), "
               "dimensions={0,1}, to_apply=%region_0.6\n"
               "}\n")
        comps, entry = parse_hlo_computations(hlo)
        assert entry == "main"
        assert set(comps) == {"region_0.6", "main"}
        r = comps["main"][-1]
        assert r["root"] and r["op"] == "reduce"
        assert r["operands"] == ["p", "c"]
        assert r["called"] == ["region_0.6"]
        assert comps["main"][0]["op"] == "parameter"
        assert comps["main"][0]["nbytes"] == 4 * 8 * 4

    def test_real_compiled_module_parses(self):
        c = jax.jit(lambda x: (x @ x).sum()).lower(
            jnp.zeros((16, 16), jnp.float32)).compile()
        from deepspeed_tpu.profiling.hlo import parse_hlo_computations

        comps, entry = parse_hlo_computations(c.as_text())
        assert entry is not None and comps[entry]
        ops = {i["op"] for i in comps[entry]}
        assert "parameter" in ops

    def test_replica_group_forms(self):
        from deepspeed_tpu.profiling.hlo import parse_replica_groups

        assert parse_replica_groups(
            "replica_groups={{0,1},{2,3}}") == [[0, 1], [2, 3]]
        assert parse_replica_groups(
            "replica_groups=[2,4]<=[8]") == [[0, 1, 2, 3], [4, 5, 6, 7]]
        # transposed iota: iota(8).reshape(2,4).T.reshape(4,2)
        assert parse_replica_groups(
            "replica_groups=[4,2]<=[2,4]T(1,0)") == \
            [[0, 4], [1, 5], [2, 6], [3, 7]]
        assert parse_replica_groups("replica_groups={}") == []

    def test_source_target_pairs(self):
        from deepspeed_tpu.profiling.hlo import parse_source_target_pairs

        assert parse_source_target_pairs(
            "source_target_pairs={{0,1},{1,2},{2,0}}") == \
            [(0, 1), (1, 2), (2, 0)]


class TestCollectiveParsingHardening:
    """Satellite: async start/done pairs must not double-count in the
    S005 volume totals, and collectives wrapped inside fusions /
    while-loop bodies must still be attributed."""

    def test_async_start_done_counts_once(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("%ag-start = (f32[4,64]{1,0}, f32[16,64]{1,0}) "
               "all-gather-start(f32[4,64]{1,0} %p), "
               "replica_groups={{0,1,2,3}}, dimensions={0}\n"
               "%ag-done = f32[16,64]{1,0} all-gather-done("
               "(f32[4,64]{1,0}, f32[16,64]{1,0}) %ag-start)\n")
        recs = parse_hlo_collectives(hlo)
        assert len(recs) == 1
        assert recs[0]["op"] == "all-gather"
        assert recs[0]["bytes"] == 16 * 64 * 4  # the OUTPUT, once

    def test_start_with_calls_body_not_double_counted(self):
        """Async sugar printed with its wrapped computation: the start
        site carries the bytes, the body's inner collective must not
        count again."""
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("HloModule m\n\n"
               "%wrapped_ag (wp: f32[4,64]) -> f32[16,64] {\n"
               "  %wp = f32[4,64]{1,0} parameter(0)\n"
               "  ROOT %ag.inner = f32[16,64]{1,0} all-gather("
               "f32[4,64]{1,0} %wp), replica_groups={{0,1,2,3}}, "
               "dimensions={0}\n"
               "}\n\n"
               "ENTRY %main (p0: f32[4,64]) -> f32[16,64] {\n"
               "  %p0 = f32[4,64]{1,0} parameter(0)\n"
               "  %ags = (f32[4,64]{1,0}, f32[16,64]{1,0}) "
               "all-gather-start(f32[4,64]{1,0} %p0), "
               "replica_groups={{0,1,2,3}}, dimensions={0}, "
               "calls=%wrapped_ag\n"
               "  ROOT %agd = f32[16,64]{1,0} all-gather-done("
               "(f32[4,64]{1,0}, f32[16,64]{1,0}) %ags)\n"
               "}\n")
        recs = parse_hlo_collectives(hlo)
        assert len(recs) == 1
        assert recs[0]["bytes"] == 16 * 64 * 4

    def test_fusion_wrapped_collective_attributed(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("HloModule m\n\n"
               "%fused_computation (fp: f32[8,8]) -> f32[8,8] {\n"
               "  %fp = f32[8,8]{1,0} parameter(0)\n"
               "  ROOT %ar = f32[8,8]{1,0} all-reduce(f32[8,8]{1,0} "
               "%fp), replica_groups={{0,1}}, to_apply=%sum\n"
               "}\n\n"
               "ENTRY %main (p: f32[8,8]) -> f32[8,8] {\n"
               "  %p = f32[8,8]{1,0} parameter(0)\n"
               "  ROOT %f = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %p), "
               "kind=kLoop, calls=%fused_computation\n"
               "}\n")
        recs = parse_hlo_collectives(hlo)
        assert len(recs) == 1
        assert recs[0]["op"] == "all-reduce"
        assert recs[0]["bytes"] == 8 * 8 * 4

    def test_while_body_collective_attributed_once(self):
        """Collectives inside a while body (the gas microstep loop)
        count once — trip counts are not statically known, matching
        the S005 convention."""
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("HloModule m\n\n"
               "%while_body (wb: (s32[], f32[4,4])) -> (s32[], f32[4,4]) {\n"
               "  %wb = (s32[], f32[4,4]{1,0}) parameter(0)\n"
               "  %i = s32[] get-tuple-element((s32[], f32[4,4]{1,0}) "
               "%wb), index=0\n"
               "  %x = f32[4,4]{1,0} get-tuple-element((s32[], "
               "f32[4,4]{1,0}) %wb), index=1\n"
               "  %ar = f32[4,4]{1,0} all-reduce(f32[4,4]{1,0} %x), "
               "replica_groups={{0,1,2,3}}, to_apply=%sum\n"
               "  ROOT %t = (s32[], f32[4,4]{1,0}) tuple(s32[] %i, "
               "f32[4,4]{1,0} %ar)\n"
               "}\n\n"
               "ENTRY %main (p: (s32[], f32[4,4])) -> (s32[], f32[4,4]) {\n"
               "  %p = (s32[], f32[4,4]{1,0}) parameter(0)\n"
               "  ROOT %w = (s32[], f32[4,4]{1,0}) while((s32[], "
               "f32[4,4]{1,0}) %p), condition=%cond, body=%while_body\n"
               "}\n")
        recs = parse_hlo_collectives(hlo)
        assert len(recs) == 1
        assert recs[0]["bytes"] == 4 * 4 * 4


# ----------------------------------------------------------------------
# schedule analysis mechanics
# ----------------------------------------------------------------------

class TestAnalyzeSchedule:
    def test_sync_collective_serialized_mode_fully_exposed(self):
        """hide_sync_slack=False models serialized execution (the
        engine's overlap_comm: false twin): the wire time is fully
        exposed even though a hideable window exists."""
        a = _seeded_analysis(_SERIALIZED_HLO, hide_sync_slack=False)
        assert a.n_sync == 1 and a.n_async == 0
        c = a.collectives[0]
        assert c.payload_bytes == 8192 * 1024 * 4
        assert c.t_comm_s == pytest.approx(
            c.payload_bytes * (7 / 8) / 100e9)
        assert c.exposed_s == pytest.approx(c.t_comm_s)  # no overlap
        # the two 4 MiB instructions sit between it and its consumer:
        # 2/3 of the program's 1s compute leg
        assert c.slack_s == pytest.approx(2 / 3, rel=1e-3)
        assert a.step_time_s == pytest.approx(1.0 + c.t_comm_s)

    def test_sync_collective_slack_credited_by_default(self):
        """The default models XLA's latency-hiding scheduler: a sync
        collective with a real consumer window is credited
        min(slack, wire) of achieved overlap."""
        a = _seeded_analysis(_SERIALIZED_HLO)
        c = a.collectives[0]
        assert c.slack_s == pytest.approx(2 / 3, rel=1e-3)
        assert c.overlap_s == pytest.approx(c.t_comm_s)
        assert c.exposed_s == 0.0
        assert a.n_hidden_sync == 1
        assert a.step_time_s == pytest.approx(1.0)

    def test_async_window_overlap_reduces_exposure(self):
        a = _seeded_analysis(_ASYNC_HLO)
        assert a.n_async == 1 and a.n_sync == 0
        c = a.collectives[0]
        # the whole compute leg sits inside the start..done window and
        # dwarfs the wire time: fully hidden
        assert c.overlap_s == pytest.approx(2 / 3, rel=1e-3)
        assert c.exposed_s == 0.0
        assert a.step_time_s == pytest.approx(1.0)

    def test_identity_groups_carry_no_wire_time(self):
        hlo = ("%ar = f32[4,4]{1,0} all-reduce(f32[4,4]{1,0} %x), "
               "replica_groups={{0},{1},{2},{3}}, to_apply=%sum\n"
               "ENTRY %main (x: f32[4,4]) -> f32[4,4] {\n"
               "  %x = f32[4,4]{1,0} parameter(0)\n"
               "  ROOT %ar2 = f32[4,4]{1,0} all-reduce(f32[4,4]{1,0} "
               "%x), replica_groups={{0},{1},{2},{3}}, to_apply=%sum\n"
               "}\n")
        a = _seeded_analysis(hlo)
        assert all(c.t_comm_s == 0.0 for c in a.collectives)

    def test_analyze_compiled_real_program(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
        w = jax.device_put(jnp.zeros((8, 256), jnp.float32),
                           NamedSharding(mesh, P("d")))

        def f(t):
            full = jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, P()))
            return (full @ full.T).sum()

        a = analyze_compiled(jax.jit(f).lower(w).compile(), label="x")
        assert a is not None and a.n_devices == 8
        assert a.n_collectives >= 1
        assert a.step_time_s > 0
        assert a.collectives[0].groups  # iota form expanded


# ----------------------------------------------------------------------
# S007: exposed-collective time
# ----------------------------------------------------------------------

class TestExposedCommCheck:
    def test_serialized_collective_fires_exactly_once(self):
        a = _seeded_analysis(_SERIALIZED_HLO, hide_sync_slack=False)
        out = check_exposed_comm(a)
        assert len(out.findings) == 1
        f = out.findings[0]
        assert f.rule == "S007" and f.severity == "error"
        assert "could overlap" in f.message

    def test_no_hideable_compute_is_silent(self):
        """Exposed but with its consumer scheduled right behind it:
        there is nothing to hide behind — not a schedule bug."""
        a = _seeded_analysis(_NO_SLACK_HLO)
        assert check_exposed_comm(a).ok

    def test_hidden_async_collective_is_silent(self):
        a = _seeded_analysis(_ASYNC_HLO)
        assert check_exposed_comm(a).ok

    def test_below_floor_is_silent(self):
        a = _seeded_analysis(_SERIALIZED_HLO, hide_sync_slack=False)
        out = check_exposed_comm(a, min_exposed_us=1e6)
        assert out.ok

    def test_baseline_regression_fires(self):
        a = _seeded_analysis(_SERIALIZED_HLO,
                             hide_sync_slack=False)  # ~293us exposed
        out = check_exposed_comm(a, baseline={"exposed_us": 10.0})
        msgs = [f.message for f in out.findings]
        assert any("regressed" in m for m in msgs)

    def test_baseline_within_tolerance_silent(self):
        a = _seeded_analysis(_NO_SLACK_HLO)
        cur = a.exposed_s * 1e6
        out = check_exposed_comm(a, baseline={"exposed_us": cur})
        assert out.ok


# ----------------------------------------------------------------------
# S008: hierarchy-aware placement
# ----------------------------------------------------------------------

class TestHierarchyPlacementCheck:
    def _analysis(self, groups):
        a = ScheduleAnalysis(label="t", n_devices=8)
        from deepspeed_tpu.analysis.schedule import CollectiveNode

        a.collectives.append(CollectiveNode(
            name="ar", op="all-reduce", computation="main",
            payload_bytes=64 << 20,
            group_size=len(groups[0]) if groups else 0,
            groups=groups))
        return a

    def test_dcn_straddling_group_fires_exactly_once(self):
        a = self._analysis([[0, 1, 2, 3, 4, 5, 6, 7]])
        out = check_hierarchy_placement(
            a, PodTopology(slice_devices=4), target_devices=[256])
        assert len(out.findings) == 1
        f = out.findings[0]
        assert f.rule == "S008" and f.severity == "error"
        assert "straddle" in f.message and "256dev" in f.message

    def test_within_slice_groups_silent(self):
        a = self._analysis([[0, 1, 2, 3], [4, 5, 6, 7]])
        assert check_hierarchy_placement(
            a, PodTopology(slice_devices=4)).ok

    def test_degree_one_crossing_silent(self):
        """One member per slice is ALREADY the hierarchical layout —
        nothing to decompose."""
        a = self._analysis([[0, 4], [1, 5], [2, 6], [3, 7]])
        assert check_hierarchy_placement(
            a, PodTopology(slice_devices=4)).ok

    def test_no_topology_is_silent(self):
        a = self._analysis([[0, 1, 2, 3, 4, 5, 6, 7]])
        assert check_hierarchy_placement(a, None).ok

    def test_flat_world_group_projects_to_pod(self):
        """An unstated (flat) replica group spans every slice of the
        projected world — the ZeRO-over-DCN shape."""
        a = self._analysis([])
        out = check_hierarchy_placement(
            a, PodTopology(slice_devices=8, num_slices=4))
        assert len(out.findings) == 1
        assert "8x" in out.findings[0].message  # 32/4 members per slice

    def test_permute_pairs_classified(self):
        from deepspeed_tpu.analysis.schedule import CollectiveNode

        a = ScheduleAnalysis(label="t", n_devices=8)
        a.collectives.append(CollectiveNode(
            name="cp", op="collective-permute", computation="main",
            payload_bytes=64 << 20, group_size=0,
            pairs=[(0, 4), (4, 0)]))
        # cross-slice pairs but degree 2/2=1: hierarchical decomposition
        # cannot help a point-to-point edge — silent
        assert check_hierarchy_placement(
            a, PodTopology(slice_devices=4)).ok


# ----------------------------------------------------------------------
# S009: critical-path step-time
# ----------------------------------------------------------------------

class TestStepTimeCheck:
    def test_comm_dominated_fires_exactly_once(self):
        a = _seeded_analysis(_COMM_BOUND_HLO, bytes_accessed=1e5)
        out = check_step_time(a)
        assert len(out.findings) == 1
        f = out.findings[0]
        assert f.rule == "S009" and f.severity == "error"
        assert "comm-dominated" in f.message

    def test_compute_dominated_is_silent(self):
        a = _seeded_analysis(_SERIALIZED_HLO)  # 1s compute vs 293us
        assert check_step_time(a).ok

    def test_drift_growth_fires_error(self):
        a = _seeded_analysis(_SERIALIZED_HLO)
        cur = a.step_time_s * 1e6
        out = check_step_time(a, baseline={"step_time_us": cur * 0.7})
        assert len(out.findings) == 1
        assert out.findings[0].severity == "error"
        assert "drifted" in out.findings[0].message

    def test_drift_shrink_warns(self):
        a = _seeded_analysis(_SERIALIZED_HLO)
        cur = a.step_time_s * 1e6
        out = check_step_time(a, baseline={"step_time_us": cur * 1.5})
        assert len(out.findings) == 1
        assert out.findings[0].severity == "warning"

    def test_within_tolerance_silent(self):
        a = _seeded_analysis(_SERIALIZED_HLO)
        cur = a.step_time_s * 1e6
        assert check_step_time(
            a, baseline={"step_time_us": cur * 1.05}).ok

    def test_step_time_replaces_three_leg_sum(self):
        """The projection is serial-roofline + EXPOSED comm — a fully
        hidden collective costs nothing, unlike the leg sum."""
        hidden = _seeded_analysis(_ASYNC_HLO)
        serial = _seeded_analysis(_SERIALIZED_HLO, hide_sync_slack=False)
        assert hidden.t_comm_s > 0
        assert hidden.step_time_s == pytest.approx(hidden.t_compute_s)
        assert serial.step_time_s > serial.t_compute_s


# ----------------------------------------------------------------------
# real programs stay silent / wiring
# ----------------------------------------------------------------------

class TestRealProgramsSilent:
    @pytest.fixture(scope="class")
    def engine(self):
        mcfg = T.TransformerConfig(
            vocab_size=128, n_layers=2, n_heads=4, d_model=64,
            max_seq=32, variant="llama", use_flash=False)
        return ds.initialize(
            {"train_micro_batch_size_per_gpu": 1,
             "gradient_accumulation_steps": 2,
             "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 3,
                                   "param_persistence_threshold": 64},
             "bf16": {"enabled": True},
             "mesh": {"data": 4, "model": 2},
             "steps_per_print": 10**9},
            loss_fn=T.make_loss_fn(mcfg),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg)), mcfg

    def test_train_step_schedule_clean_with_aligned_topology(self, engine):
        """The real zero-3+TP step carries S007/S009 silently, and with
        a topology whose DCN tier spans the data axis (model innermost
        = ICI) S008 is silent too — every model-axis group stays inside
        one slice and the data-axis groups run one member per slice."""
        eng, _ = engine
        batch = {"tokens": np.zeros(
            (eng.config.train_batch_size, 33), np.int32)}
        rep = eng.sanitize(
            batch, target_topology=PodTopology(slice_devices=2))
        sched_rules = [f for f in rep.findings
                       if f.rule in ("S007", "S008", "S009")]
        assert sched_rules == [], rep.render()
        assert rep.cost is not None
        assert rep.cost.step_time_s > 0
        assert rep.cost.schedule["n_collectives"] > 0

    def test_misaligned_topology_fires_s008(self, engine):
        """The SAME healthy program under a topology that puts slice
        boundaries through the replica groups: S008 must surface the
        DCN-straddling collectives."""
        eng, _ = engine
        batch = {"tokens": np.zeros(
            (eng.config.train_batch_size, 33), np.int32)}
        rep = eng.sanitize(
            batch, target_topology=PodTopology(slice_devices=4,
                                               min_saving_us=0.0))
        s008 = [f for f in rep.findings if f.rule == "S008"]
        assert len(s008) >= 1
        assert all(f.rule == "S008" for f in s008)

    def test_serving_decode_schedule_clean(self):
        from deepspeed_tpu.inference import init_inference

        cfg = T.TransformerConfig(
            vocab_size=128, n_layers=2, n_heads=4, d_model=64,
            max_seq=32, variant="llama", use_flash=False)
        params = T.init(cfg, jax.random.PRNGKey(0))
        eng = init_inference(
            params, cfg,
            dict(max_seq_len=32, kv_block_size=8, num_kv_blocks=32,
                 min_prefill_bucket=8, max_batch_size=8),
            dtype=jnp.float32)
        eng.warmup(widths=[8], chunked=False, decode_chunks=(),
                   footprint=True)
        fp = eng.warmup_footprints[8]
        assert "step_time_us" in fp and fp["step_time_us"] > 0
        assert fp["exposed_comm_us"] < 50.0  # silent on the decode bucket


# ----------------------------------------------------------------------
# expert-axis collective parsing (dropless MoE, moe/dropless.py)
# ----------------------------------------------------------------------

class TestExpertCollectiveParsing:
    """The dropless a2a wire's dispatch/combine pair must be attributed
    EXACTLY ONCE each with 'expert'-axis replica groups — the contract
    engine.sanitize's S005/S007/S009 checks (and the committed
    train_step_moe baselines) depend on."""

    EP = 2

    @pytest.fixture(scope="class")
    def moe_compiled(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from deepspeed_tpu.moe import dropless_moe_ffn

        devs = np.array(jax.devices()[:4]).reshape(2, self.EP)
        mesh = Mesh(devs, ("data", "expert"))

        def sh(x, *spec):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(*spec)))

        r = np.random.default_rng(0)
        rw = jnp.asarray(r.normal(size=(16, 4)), jnp.float32)
        w_in = jnp.asarray(r.normal(size=(4, 16, 32)), jnp.float32) * 0.1
        w_gate = jnp.asarray(r.normal(size=(4, 16, 32)), jnp.float32) * 0.1
        w_out = jnp.asarray(r.normal(size=(4, 32, 16)), jnp.float32) * 0.1

        def fwd(t):
            return dropless_moe_ffn(
                t, rw, w_in, w_out, w_gate=w_gate, act=jax.nn.silu,
                top_k=2, shard=sh, ep_size=self.EP).out

        toks = jnp.zeros((64, 16), jnp.float32)
        with mesh:
            return jax.jit(fwd).lower(toks).compile()

    def test_a2a_pair_counted_once_with_expert_groups(self, moe_compiled):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        recs = parse_hlo_collectives(moe_compiled.as_text())
        a2a = [c for c in recs if c["op"] == "all-to-all"]
        # the forward wire: ONE dispatch + ONE combine, counted once
        # each (async -start/-done forms must not double-count)
        assert len(a2a) == 2, recs
        assert all(c["group_size"] == self.EP for c in a2a)
        assert all(c["bytes"] > 0 for c in a2a)

    def test_replica_groups_are_expert_pairs(self, moe_compiled):
        """The a2a replica groups pair devices ALONG the expert axis —
        {2k, 2k+1} under the (data=2, expert=2) mesh — never across
        data rows."""
        import re

        groups = set()
        for line in moe_compiled.as_text().splitlines():
            if "all-to-all" not in line or "replica_groups" not in line:
                continue
            m = re.search(r"replica_groups=\{(\{[^=]*?\})\}", line)
            if m is None:
                continue
            for g in re.findall(r"\{([\d,]+)\}", m.group(1)):
                groups.add(tuple(int(x) for x in g.split(",")))
        assert groups, "no explicit a2a replica groups parsed"
        for g in groups:
            assert len(g) == self.EP
            assert g[1] == g[0] + 1 and g[0] % self.EP == 0, groups

    def test_s005_quiet_on_expert_dispatch(self, moe_compiled):
        """The a2a pair is a legitimate dispatch, not an accidental-
        replication all-gather blowup: S005 stays silent."""
        from deepspeed_tpu.analysis.costmodel import (
            build_cost_report,
            check_collective_volume,
        )

        rep = build_cost_report(moe_compiled, label="moe[fwd]")
        assert rep is not None
        chk = check_collective_volume(rep, live_sharded_bytes=None,
                                      k=6.0, label="moe[fwd]")
        assert chk.ok, chk.render()
        # the pair's bytes land in the report's per-op volume table
        a2a = rep.collectives.get("all-to-all", {})
        assert a2a.get("count") == 2 and a2a.get("bytes", 0) > 0


# ----------------------------------------------------------------------
# autotuner AOT score (satellite)
# ----------------------------------------------------------------------

class TestAutotunerAot:
    def _tuner(self):
        from deepspeed_tpu.autotuning.autotuner import Autotuner

        mcfg = T.TransformerConfig(
            vocab_size=128, n_layers=2, n_heads=4, d_model=64,
            max_seq=32, variant="llama", use_flash=False)
        base = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 10**9,
            "mesh": {"data": 8},
        }
        return Autotuner(
            base, loss_fn=T.make_loss_fn(mcfg),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg),
            make_batch=lambda b: {"tokens": np.zeros((b, 33), np.int32)},
        )

    # known-good pure-DP config vs a deliberately comm-bound one:
    # zero-3 with zero persistence + TP over a toy d_model re-gathers
    # every param and psums every activation — far more wire bytes per
    # sample than the plain data-parallel step
    GOOD = {"zero_stage": 1, "micro_batch_size": 2, "mesh": {"data": 8}}
    BAD = {"zero_stage": 3, "micro_batch_size": 1,
           "mesh": {"data": 2, "model": 4}}

    def test_aot_ranks_good_above_comm_bound(self, tmp_path):
        tuner = self._tuner()
        tuner.results_dir = str(tmp_path)
        ranked = tuner.aot_rank([self.BAD, self.GOOD])
        assert ranked[0].get("aot_ok"), ranked[0]
        assert ranked[0]["mesh"] == {"data": 8}
        assert ranked[0]["aot_samples_per_sec"] > \
            ranked[1]["aot_samples_per_sec"]
        # the comm-bound candidate pays more exposed wire time AND more
        # projected step time per sample
        good_batch = 2 * 8   # micro 2 x dp 8
        bad_batch = 1 * 2    # micro 1 x dp 2
        assert ranked[1]["aot_exposed_comm_s"] / bad_batch > \
            ranked[0]["aot_exposed_comm_s"] / good_batch
        assert ranked[1]["aot_step_time_s"] / bad_batch > \
            ranked[0]["aot_step_time_s"] / good_batch

    def test_tune_aot_skips_trials_and_is_deterministic(self, tmp_path):
        """trial=False must never execute a step — and the ranked
        top-k list must be byte-deterministic for equal inputs."""
        tuner = self._tuner()
        tuner.results_dir = str(tmp_path)

        def boom(*a, **k):
            raise AssertionError("trial execution must be stubbed out")

        tuner._measure = boom
        cfg = tuner.tune_aot(candidates=[self.BAD, self.GOOD],
                             trial=False)
        assert cfg["mesh"] == {"data": 8}
        assert cfg["train_micro_batch_size_per_gpu"] == 2
        ledger = [r for r in tuner.results if r.get("phase") == "aot"]
        assert len(ledger) == 2
        assert os.path.exists(os.path.join(str(tmp_path), "exps.jsonl"))

    def test_rank_order_deterministic_under_ties(self):
        """Equal scores sort by the canonical candidate key — the
        top-k trial list cannot depend on dict order or randomness."""
        from deepspeed_tpu.autotuning.autotuner import Autotuner

        tuner = Autotuner({"train_micro_batch_size_per_gpu": 1},
                          loss_fn=None, param_init_fn=None,
                          make_batch=lambda b: None)
        cands = [{"zero_stage": s, "micro_batch_size": m}
                 for s in (3, 1, 2) for m in (4, 1)]
        tuner.aot_score = lambda c, **k: {
            **c, "aot_ok": True, "aot_samples_per_sec": 7.0}
        first = [tuner._aot_key(e) for e in tuner.aot_rank(cands)]
        second = [tuner._aot_key(e) for e in tuner.aot_rank(
            list(reversed(cands)))]
        assert first == second == sorted(first)


# ----------------------------------------------------------------------
# link-table single authority (satellite)
# ----------------------------------------------------------------------

class TestLinkAuthority:
    def test_costmodel_reexports_links(self):
        from deepspeed_tpu.analysis.costmodel import ICI_GBPS
        from deepspeed_tpu.platform.accelerator import LINKS

        assert ICI_GBPS == LINKS["ici_bytes_per_s"]
        assert LINKS["dcn_bytes_per_s"] < LINKS["ici_bytes_per_s"]

    def test_accelerator_methods_read_table(self):
        from deepspeed_tpu.platform.accelerator import (
            LINKS,
            get_accelerator,
        )

        acc = get_accelerator()
        assert acc.ici_bandwidth() == LINKS["ici_bytes_per_s"]
        assert acc.dcn_bandwidth() == LINKS["dcn_bytes_per_s"]

    def test_no_consumer_redeclares_the_constant(self):
        """The drift guard: only platform/accelerator.py may spell the
        link bandwidths; every consumer imports the table."""
        import deepspeed_tpu.analysis.costmodel as cm
        import deepspeed_tpu.analysis.schedule as sc

        consumers = [
            cm.__file__, sc.__file__,
            os.path.join(REPO, "scripts", "ici_projection.py"),
        ]
        for path in consumers:
            src = open(path, "r", encoding="utf-8").read()
            assert "100e9" not in src and "6.25e9" not in src, (
                f"{path} re-declares a link constant; import "
                "platform.accelerator.LINKS instead")
            assert "LINKS" in src

    def test_default_topology_uses_links(self):
        from deepspeed_tpu.platform.accelerator import LINKS

        t = PodTopology(slice_devices=4)
        assert t.ici_bandwidth == LINKS["ici_bytes_per_s"]
        assert t.dcn_bandwidth == LINKS["dcn_bytes_per_s"]


# ----------------------------------------------------------------------
# ds_schedule CLI gate
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestDsScheduleScript:
    """Slow lane: each subprocess rebuilds EVERY canonical program via
    ds_budget's builder (the MoE zero3+EP+TP engine included) — and
    the pre-test gate lane already runs `ds_schedule.py --check
    --strict` on every PR, so the fast lane carries no coverage gap."""

    def _run(self, *args):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the script sets its own device count
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "ds_schedule.py"), *args],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=600)

    def test_check_passes_on_committed_tree(self):
        r = self._run("--check", "--strict")
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        assert doc["ok"] and doc["findings"] == []

    def test_check_fails_on_injected_regression(self, tmp_path):
        base = json.load(open(os.path.join(REPO, "SCHEDULE.json")))
        # shrink the recorded projection so the (unchanged) tree reads
        # as a >= 10% step-time regression
        for prog in base["programs"].values():
            prog["step_time_us"] = prog["step_time_us"] * 0.7
        injected = tmp_path / "schedule.json"
        injected.write_text(json.dumps(base))
        r = self._run("--check", "--baseline", str(injected))
        assert r.returncode != 0, r.stdout + r.stderr
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        assert not doc["ok"]
        assert any(f["rule"] == "S009" and "drifted" in f["message"]
                   for f in doc["findings"])

    def test_capture_roundtrip(self, tmp_path):
        out = tmp_path / "fresh.json"
        r = self._run("--capture", "--baseline", str(out))
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(out.read_text())
        assert set(doc["programs"]) == {"train_step", "train_step_moe",
                                        "train_step_pipe3d",
                                        "serving_decode_w8",
                                        "serving_decode_w8_int8"}
        assert all(p["step_time_us"] > 0
                   for p in doc["programs"].values())
        assert doc["programs"]["train_step"]["n_collectives"] > 0
        assert doc["programs"]["train_step_moe"]["n_collectives"] > 0
        # the interleaved-pipeline entry commits the interleave-wins
        # pin: V=2's projection strictly below its V=1 twin's
        pp = doc["programs"]["train_step_pipe3d"]["pipe_projection"]
        assert pp["v2_step_time_us"] < pp["v1_step_time_us"]
        # the fused int8-KV decode entry commits its S006 verdict and
        # the gather-materialization probe
        q = doc["programs"]["serving_decode_w8_int8"]
        assert q["s006_bound"] == "memory"
        assert 0 < q["max_gather_bytes"] <= q["gather_bytes_limit"]
        r = self._run("--check", "--strict", "--baseline", str(out))
        assert r.returncode == 0, r.stdout + r.stderr
