"""Decoder-only transformer model family (GPT-2-class and Llama-class).

The in-tree reference models for the framework, playing the role of the
reference's test/bench models (ref: tests/unit/simple_model.py and the
model_implementations zoo). TPU-first design decisions:

- pure-functional params dict (no module system) with *logical axis
  names* per leaf — the sharding-rules table (parallel/sharding.py) maps
  these to mesh axes, which is this framework's AutoTP
  (ref: module_inject/auto_tp.py).
- layers stacked on a leading 'layers' dim and executed with `lax.scan`
  → O(1) compile time in depth, XLA-friendly.
- Ulysses sequence parallelism is two sharding constraints around
  attention (seq-sharded ↔ head-sharded); XLA inserts the all-to-all
  pair that the reference does by hand (ref: deepspeed/sequence/layer.py
  _SeqAllToAll:44, DistributedAttention:60).
- activation checkpointing = jax.checkpoint policy on the scanned layer
  body (ref: runtime/activation_checkpointing/checkpointing.py:989).
- GQA (n_kv_heads < n_heads), rotary embeddings, RMSNorm, SwiGLU for the
  Llama variant; learned positions, LayerNorm, gelu for GPT-2.
"""

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import causal_attention

DP = ("data", "expert")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # GQA; None = MHA
    d_model: int = 512
    d_ff: Optional[int] = None  # default: 4x (gpt2) or llama 8/3 rounding
    max_seq: int = 2048
    variant: str = "llama"  # "llama" | "gpt2"
    dropout: float = 0.0
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    remat: str = "none"  # none | full | dots (jax.checkpoint policy)
    use_flash: bool = True  # pallas flash attention on TPU, XLA fallback elsewhere
    # MoE (ref: deepspeed/moe/layer.py MoE:17 knobs). n_experts > 0 turns
    # every MLP into an expert-parallel MoE FFN.
    n_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    moe_noisy_gate_policy: Optional[str] = None  # None | RSample | Jitter

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.variant == "llama":
            d = int(self.d_model * 8 / 3)
            return ((d + 127) // 128) * 128
        return 4 * self.d_model

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Train-step matmul FLOPs per token for MFU accounting:
        6*N (fwd+bwd over all params) + causal attention term
        6*L*S*E (QK^T and AV each contribute ~S*E fwd flops/token under
        the causal mask; backward doubles it)."""
        S = seq_len or self.max_seq
        n = param_count(self)
        return 6.0 * n + 6.0 * self.n_layers * S * self.d_model


def param_count(cfg: TransformerConfig) -> int:
    shapes = jax.tree.leaves(jax.eval_shape(lambda k: init(cfg, k), jax.random.PRNGKey(0)))
    return sum(int(jnp.prod(jnp.array(s.shape))) for s in shapes)


# ---------------------------------------------------------------------------
# params + logical specs
# ---------------------------------------------------------------------------

def _layer_shapes(cfg: TransformerConfig) -> Dict[str, Tuple[Tuple[int, ...], Tuple]]:
    """name -> (shape-without-layer-dim, logical axes-without-layer-dim)"""
    E, H, KV, D, F = cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim, cfg.ff_dim
    shapes = {
        "ln1_scale": ((E,), ("embed",)),
        "ln2_scale": ((E,), ("embed",)),
        "wq": ((E, H, D), ("embed", "heads", "head_dim")),
        "wk": ((E, KV, D), ("embed", "heads", "head_dim")),
        "wv": ((E, KV, D), ("embed", "heads", "head_dim")),
        "wo": ((H, D, E), ("heads", "head_dim", "embed")),
    }
    X = cfg.n_experts
    if X > 0:
        # Expert-stacked FFN weights: leading experts dim shards over the
        # 'expert' mesh axis; the expert-hidden dim may additionally shard
        # over 'model' (ref: moe/experts.py local expert bundle — here one
        # stacked array instead of a ModuleList).
        shapes.update({
            "w_router": ((E, X), ("embed", None)),
            "w_in": ((X, E, F), ("expert", "embed", "expert_mlp")),
            "w_out": ((X, F, E), ("expert", "expert_mlp", "embed")),
        })
        if cfg.variant == "llama":
            shapes["w_gate"] = ((X, E, F), ("expert", "embed", "expert_mlp"))
    else:
        shapes.update({
            "w_in": ((E, F), ("embed", "mlp")),
            "w_out": ((F, E), ("mlp", "embed")),
        })
        if cfg.variant == "llama":
            shapes["w_gate"] = ((E, F), ("embed", "mlp"))
    if cfg.variant != "llama":
        shapes.update({
            "ln1_bias": ((E,), ("embed",)),
            "ln2_bias": ((E,), ("embed",)),
            "b_in": (((X, F) if X > 0 else (F,)), (("expert", "expert_mlp") if X > 0 else ("mlp",))),
            "b_out": (((X, E) if X > 0 else (E,)), (("expert", "embed") if X > 0 else ("embed",))),
            "bq": ((H, D), ("heads", "head_dim")),
            "bk": ((KV, D), ("heads", "head_dim")),
            "bv": ((KV, D), ("heads", "head_dim")),
            "bo": ((E,), ("embed",)),
        })
    return shapes


def init(cfg: TransformerConfig, rng) -> Dict[str, Any]:
    E, V, L = cfg.d_model, cfg.vocab_size, cfg.n_layers
    keys = jax.random.split(rng, 16)
    std = 0.02

    def norm_init(shape, scale_name):
        return jnp.ones(shape, jnp.float32) if "scale" in scale_name else jnp.zeros(shape, jnp.float32)

    params: Dict[str, Any] = {
        "embed": jax.random.normal(keys[0], (V, E), jnp.float32) * std,
        "ln_f_scale": jnp.ones((E,), jnp.float32),
    }
    if cfg.variant == "gpt2":
        params["pos_embed"] = jax.random.normal(keys[1], (cfg.max_seq, E), jnp.float32) * std
        params["ln_f_bias"] = jnp.zeros((E,), jnp.float32)
    if not cfg.tie_embeddings:
        params["lm_head"] = jax.random.normal(keys[2], (E, V), jnp.float32) * std

    layers = {}
    lkeys = jax.random.split(keys[3], len(_layer_shapes(cfg)))
    for i, (name, (shape, _)) in enumerate(sorted(_layer_shapes(cfg).items())):
        full = (L,) + shape
        if "ln" in name:
            layers[name] = jnp.broadcast_to(norm_init(shape, name), full).copy()
        elif name.startswith("b"):
            layers[name] = jnp.zeros(full, jnp.float32)
        else:
            scale = std / (2 * L) ** 0.5 if name in ("wo", "w_out") else std
            layers[name] = jax.random.normal(lkeys[i], full, jnp.float32) * scale
    params["layers"] = layers
    return params


def logical_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    specs: Dict[str, Any] = {
        "embed": ("vocab", "embed"),
        "ln_f_scale": ("embed",),
    }
    if cfg.variant == "gpt2":
        specs["pos_embed"] = (None, "embed")
        specs["ln_f_bias"] = ("embed",)
    if not cfg.tie_embeddings:
        specs["lm_head"] = ("embed", "vocab")
    specs["layers"] = {
        name: ("layers",) + logical for name, (_, logical) in _layer_shapes(cfg).items()
    }
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _norm(x, scale, bias, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.variant == "llama":
        rms = jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + cfg.norm_eps)
        out = x32 * rms * scale
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps) * scale + bias
    return out.astype(x.dtype)


def _rope(q, k, cfg: TransformerConfig, offset: int = 0):
    """Rotary embeddings (ref kernel: csrc/transformer/inference/csrc/
    apply_rotary_pos_emb.cu — on TPU this is pure VPU code XLA fuses)."""
    D = cfg.head_dim
    S = q.shape[1]
    pos = jnp.arange(offset, offset + S, dtype=jnp.float32)
    freqs = cfg.rope_theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    angles = pos[:, None] * freqs[None, :]  # [S, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)

    def rot(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _shard(x, *spec):
    """Sharding constraint against the ambient mesh (set by the engine via
    jax.sharding.set_mesh). Outside any mesh context — e.g. a plain
    single-device forward — constraints are skipped explicitly; inside a
    mesh context a bad spec raises rather than silently degrading."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(x, P(*spec))


def _dropout(x, rate: float, rng):
    """Inverted dropout (ref kernel: csrc/transformer/dropout_kernels.cu —
    on TPU this fuses into the surrounding elementwise ops)."""
    if rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _attention_block(x, lp, cfg: TransformerConfig, rng=None):
    B, S, E = x.shape
    h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), cfg)
    q = jnp.einsum("bse,ehd->bshd", h, lp["wq"].astype(x.dtype))
    k = jnp.einsum("bse,ehd->bshd", h, lp["wk"].astype(x.dtype))
    v = jnp.einsum("bse,ehd->bshd", h, lp["wv"].astype(x.dtype))
    if cfg.variant == "gpt2":
        q = q + lp["bq"].astype(x.dtype)
        k = k + lp["bk"].astype(x.dtype)
        v = v + lp["bv"].astype(x.dtype)
    else:
        q, k = _rope(q, k, cfg)

    # Ulysses: re-shard seq→heads around attention; XLA emits the
    # all-to-all pair (ref: sequence/layer.py single_all_to_all:15).
    q = _shard(q, DP, None, ("model", "seq"), None)
    k = _shard(k, DP, None, ("model", "seq"), None)
    v = _shard(v, DP, None, ("model", "seq"), None)

    out = causal_attention(q, k, v, use_flash=cfg.use_flash)  # [B,S,H,D]

    out = _shard(out, DP, "seq", "model", None)
    out = jnp.einsum("bshd,hde->bse", out, lp["wo"].astype(x.dtype))
    if cfg.variant == "gpt2":
        out = out + lp["bo"].astype(x.dtype)
    out = _dropout(out, cfg.dropout, rng)
    return x + out


def _mlp_block(x, lp, cfg: TransformerConfig, rng=None):
    """Dense or MoE FFN; returns (residual output, moe aux loss)."""
    if cfg.n_experts > 0:
        return _moe_mlp_block(x, lp, cfg, rng)
    h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg)
    if cfg.variant == "llama":
        gate = jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(x.dtype))
        up = jnp.einsum("bse,ef->bsf", h, lp["w_in"].astype(x.dtype))
        inner = jax.nn.silu(gate) * up
    else:
        inner = jax.nn.gelu(
            jnp.einsum("bse,ef->bsf", h, lp["w_in"].astype(x.dtype)) + lp["b_in"].astype(x.dtype)
        )
    inner = _shard(inner, DP, "seq", "model")
    out = jnp.einsum("bsf,fe->bse", inner, lp["w_out"].astype(x.dtype))
    if cfg.variant == "gpt2":
        out = out + lp["b_out"].astype(x.dtype)
    out = _dropout(out, cfg.dropout, rng)
    return x + out, jnp.float32(0.0)


def _moe_mlp_block(x, lp, cfg: TransformerConfig, rng=None):
    """Expert-parallel MoE FFN (ref: deepspeed/moe/sharded_moe.py
    MOELayer:421 — dispatch einsum / all-to-all / expert FFN / combine)."""
    from ..moe.sharded_moe import moe_ffn

    B, S, E = x.shape
    h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg)
    tokens = h.reshape(B * S, E)

    def expert_fn(xin):  # [X, C, E] expert-major
        if cfg.variant == "llama":
            gate = jnp.einsum("xce,xef->xcf", xin, lp["w_gate"].astype(x.dtype))
            up = jnp.einsum("xce,xef->xcf", xin, lp["w_in"].astype(x.dtype))
            inner = jax.nn.silu(gate) * up
        else:
            inner = jax.nn.gelu(
                jnp.einsum("xce,xef->xcf", xin, lp["w_in"].astype(x.dtype))
                + lp["b_in"][:, None, :].astype(x.dtype)
            )
        inner = _shard(inner, "expert", None, "model")
        out = jnp.einsum("xcf,xfe->xce", inner, lp["w_out"].astype(x.dtype))
        if cfg.variant == "gpt2":
            out = out + lp["b_out"][:, None, :].astype(x.dtype)
        return out

    def shard(t, *spec):
        return _shard(t, *spec)

    gate_rng = None
    if rng is not None and cfg.moe_noisy_gate_policy is not None:
        rng, gate_rng = jax.random.split(rng)
    out, l_aux = moe_ffn(
        tokens,
        lp["w_router"],
        expert_fn,
        top_k=cfg.moe_top_k,
        capacity_factor=cfg.moe_capacity_factor,
        min_capacity=cfg.moe_min_capacity,
        rng=gate_rng,
        noisy_gate_policy=cfg.moe_noisy_gate_policy,
        shard=shard,
    )
    out = out.reshape(B, S, E)
    out = _shard(out, DP, "seq", None)
    out = _dropout(out, cfg.dropout, rng)
    return x + out, l_aux


_REMAT_POLICIES = {
    "none": None,
    "full": None,  # full remat = jax.checkpoint with default policy
    "dots": "dots_with_no_batch_dims_saveable",
}


def forward_hidden(
    params: Dict[str, Any], tokens, cfg: TransformerConfig, rng=None, with_aux: bool = False
):
    """tokens [B, S] int32 → final hidden states [B, S, E] (post ln_f).

    with_aux=True additionally returns {"moe_aux_loss": scalar} (sum of
    per-layer load-balancing losses; 0 for dense models)."""
    x = params["embed"][tokens]
    x = _shard(x, DP, "seq", None)
    if cfg.variant == "gpt2":
        x = x + params["pos_embed"][: tokens.shape[1]].astype(x.dtype)

    # MoE gate noise also wants per-layer rngs, not just dropout.
    use_rng = rng is not None and (
        cfg.dropout > 0.0 or (cfg.n_experts > 0 and cfg.moe_noisy_gate_policy is not None)
    )

    def layer_body(carry, xs):
        if use_rng:
            h0, (lp, layer_rng) = carry, xs
            r1, r2 = jax.random.split(layer_rng)
        else:
            h0, lp = carry, xs
            r1 = r2 = None
        h = _attention_block(h0, lp, cfg, r1)
        h, l_aux = _mlp_block(h, lp, cfg, r2)
        h = _shard(h, DP, "seq", None)
        return h, l_aux

    if cfg.remat == "full":
        layer_body = jax.checkpoint(layer_body)
    elif cfg.remat == "dots":
        layer_body = jax.checkpoint(
            layer_body, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )

    if use_rng:
        layer_rngs = jax.random.split(rng, cfg.n_layers)
        x, aux = jax.lax.scan(layer_body, x, (params["layers"], layer_rngs))
    else:
        x, aux = jax.lax.scan(layer_body, x, params["layers"])
    out = _norm(x, params["ln_f_scale"], params.get("ln_f_bias"), cfg)
    if with_aux:
        return out, {"moe_aux_loss": jnp.sum(aux)}
    return out


def forward(params: Dict[str, Any], tokens, cfg: TransformerConfig, rng=None):
    """tokens [B, S] int32 → logits [B, S, V]."""
    x = forward_hidden(params, tokens, cfg, rng)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(x.dtype))
    return _shard(logits, DP, "seq", "model")


def _chunked_ce(x, head, targets, mask, n_chunks: int):
    """Cross-entropy without materializing [B,S,V] through backward.

    The per-chunk logits+logsumexp are rematerialized in bwd
    (jax.checkpoint), so peak memory is [B, S/n_chunks, V] — the TPU
    analog of the reference's fused softmax-xent kernels
    (ref: csrc/transformer softmax_kernels.cu), achieved with remat
    instead of a handwritten kernel.
    Returns (sum_nll, sum_mask)."""
    B, S, E = x.shape
    C = S // n_chunks

    @jax.checkpoint
    def chunk(x_c, t_c, m_c):
        logits = jnp.einsum("bce,ev->bcv", x_c, head.astype(x_c.dtype))
        logits = _shard(logits, DP, None, "model").astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        nll = (lse - tgt) * m_c
        return jnp.sum(nll), jnp.sum(m_c)

    def body(carry, xs):
        tot, cnt = carry
        x_c, t_c, m_c = xs
        s, c = chunk(x_c, t_c, m_c)
        return (tot + s, cnt + c), None

    xs = (
        x.reshape(B, n_chunks, C, E).swapaxes(0, 1),
        targets.reshape(B, n_chunks, C).swapaxes(0, 1),
        mask.reshape(B, n_chunks, C).swapaxes(0, 1),
    )
    (tot, cnt), _ = jax.lax.scan(body, (jnp.float32(0.0), jnp.float32(0.0)), xs)
    return tot, cnt


def make_loss_fn(cfg: TransformerConfig, loss_chunks: int = 8):
    """Next-token cross-entropy over batch {"tokens": [B, S(+1)]}.

    loss_chunks: sequence-chunked CE (memory: [B, S/chunks, V] instead of
    [B, S, V]); 1 disables chunking."""

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        x, aux = forward_hidden(params, inputs, cfg, rng, with_aux=True)
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        mask = (
            batch["mask"][:, 1:].astype(jnp.float32)
            if "mask" in batch
            else jnp.ones(targets.shape, jnp.float32)
        )
        n = loss_chunks if inputs.shape[1] % max(loss_chunks, 1) == 0 else 1
        tot, cnt = _chunked_ce(x, head, targets, mask, max(n, 1))
        loss = tot / jnp.maximum(cnt, 1.0)
        if cfg.n_experts > 0:
            # Load-balancing aux loss, coefficient per the reference's
            # Megatron-DeepSpeed recipe (ref: sharded_moe.py l_aux usage).
            loss = loss + cfg.moe_aux_loss_coef * aux["moe_aux_loss"]
        return loss

    return loss_fn
