"""Static analysis for compiled TPU programs and the codebase itself.

Two prongs (see docs/static_analysis.md):

  sanitizer — ground-truth checks on compiled/lowered artifacts:
              donation aliasing (S001), PartitionSpec survival (S002),
              recompilation-hazard classification (S003). Run against a
              live engine with `engine.sanitize(batch)`.
  lint      — `ds-lint`, an AST pass with project rules R001-R004
              (`python scripts/ds_lint.py --strict`).
"""

from .report import Finding, LintReport, SanitizerReport, merge_reports
from .sanitizer import (
    RecompileTracker,
    abstract_signature,
    check_donation,
    check_sharding,
)
from .lint import lint_paths, lint_source, RULES

__all__ = [
    "Finding",
    "LintReport",
    "SanitizerReport",
    "merge_reports",
    "RecompileTracker",
    "abstract_signature",
    "check_donation",
    "check_sharding",
    "lint_paths",
    "lint_source",
    "RULES",
]
