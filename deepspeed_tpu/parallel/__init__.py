from .sharding import (
    DEFAULT_LOGICAL_RULES,
    batch_spec,
    constraint,
    logical_to_mesh_spec,
    make_rules,
    tree_logical_to_mesh,
    tree_shardings,
)
