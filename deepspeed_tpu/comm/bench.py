"""Collective microbenchmark sweep — the `ds_bench` analog.

TPU-native replacement for the reference's comm benchmark CLI
(ref: bin/ds_bench → benchmarks/communication/run_all.py — sweeps
all_reduce/all_gather/all_to_all/broadcast/pt2pt payload sizes over
torch.distributed and prints achieved algbw/busbw). Here each op is a
one-line shard_map over the ambient mesh and XLA emits the collective;
the sweep validates an actual slice's ICI against the effective-bandwidth
constant the 70B scaling projection assumes
(scripts/ici_projection.py, SCALING_r04.json `ici_seconds_at_100GBps`).

Bus-bandwidth convention (matches the reference's busbw note —
benchmarks/communication/utils.py): for ring algorithms the wire moves
(n-1)/n of the payload per device, and all_reduce moves it twice:

  all_gather / reduce_scatter: busbw = algbw * (n-1)/n
  all_reduce:                  busbw = algbw * 2(n-1)/n
  all_to_all:                  busbw = algbw * (n-1)/n
  ppermute (pt2pt ring):       busbw = algbw

Timing: each trial is one dispatch synchronized through
`utils.sync.host_sync` (the named end-of-run choke point ds-lint R002
allowlists), and the reported time is the MEDIAN over trials. The tunnel round trip is measured once and emitted
as a separate `rtt_us` field per record (auditable) rather than
subtracted from the timings — the old pipelined-dispatch-minus-one-rtt
calibration under-corrected: a single tiny-add round trip does not
model the readback of a multi-MB collective result, and the subtraction
landed inside the per-trial average where one outlier skewed every
number. On a pod (multi-controller), run this module on every host
via the pod launcher:

  python -m deepspeed_tpu.launcher.pod --tpu my-slice --zone us-... \
      -- python -m deepspeed_tpu.comm.bench --sizes-mb 1,16,64

Single host / CPU-virtual (CI shape proof):

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python -m deepspeed_tpu.comm.bench --ops all_gather --sizes-mb 1
"""

import argparse
import json
import sys
import time
from functools import partial
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.sync import host_readback, host_sync

OPS = ("all_gather", "all_reduce", "reduce_scatter", "all_to_all",
       "ppermute")


def _busbw_factor(op: str, n: int) -> float:
    if op == "all_reduce":
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0  # ppermute: the payload crosses one link once


def _build(op: str, mesh: Mesh, axis: str) -> Callable:
    """jitted fn taking the axis-sharded operand; the collective is the
    whole program (comm.py wrappers are in-jit ops; shard_map binds the
    axis name exactly as the engine's compiled step does)."""
    from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]

    def body(x):
        if op == "all_gather":
            return jax.lax.all_gather(x, axis, tiled=True)
        if op == "all_reduce":
            return jax.lax.psum(x, axis)
        if op == "reduce_scatter":
            return jax.lax.psum_scatter(x, axis, tiled=True)
        if op == "all_to_all":
            return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                      tiled=True)
        if op == "ppermute":
            return jax.lax.ppermute(
                x, axis, [(i, (i + 1) % n) for i in range(n)])
        raise ValueError(op)

    spec = P(axis)
    out_spec = P(None) if op == "all_gather" else spec
    return jax.jit(shard_map(body, mesh=mesh, in_specs=spec,
                             out_specs=out_spec, check_rep=False))


def _payload_shape(op: str, size_bytes: int, n: int, dtype) -> tuple:
    """GLOBAL operand shape for ~size_bytes per-device payload."""
    itemsize = jnp.dtype(dtype).itemsize
    # per-device rows of width 1024 lanes
    width = 1024
    rows = max(1, size_bytes // (itemsize * width))
    return (n * rows, width)


def _rtt() -> float:
    f = jax.jit(lambda x: x + 1)
    host_readback(f(jnp.zeros((8, 128))))
    ts = []
    for i in range(5):
        t0 = time.perf_counter()
        host_readback(f(jnp.full((8, 128), float(i))))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def sweep(
    ops: List[str],
    sizes_bytes: List[int],
    axis: str = "data",
    mesh: Mesh = None,
    trials: int = 10,
    dtype=jnp.bfloat16,
    ici_assumption_gbps: float = 100.0,
) -> List[Dict]:
    """Run the sweep on the ambient devices; returns one record per
    (op, size) with achieved algbw/busbw GB/s and the ratio to the
    assumed effective ICI bandwidth."""
    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs, (axis,))
    n = mesh.shape[axis]
    rtt = _rtt()
    out: List[Dict] = []
    for op in ops:
        fn = _build(op, mesh, axis)
        for size in sizes_bytes:
            shape = _payload_shape(op, size, n, dtype)
            sharding = NamedSharding(mesh, P(axis))
            x = jax.device_put(
                jnp.ones(shape, dtype), sharding)
            host_sync(fn(x))  # compile + warm
            times = []
            for _ in range(trials):
                t0 = time.perf_counter()
                host_sync(fn(x))  # per-trial boundary (the R002 choke point)
                times.append(time.perf_counter() - t0)
            dt = max(float(np.median(times)), 1e-9)
            per_dev_bytes = (np.prod(shape) // n) * jnp.dtype(dtype).itemsize
            algbw = per_dev_bytes / dt / 1e9
            busbw = algbw * _busbw_factor(op, n)
            out.append({
                "op": op, "bytes_per_device": int(per_dev_bytes),
                "time_us": dt * 1e6, "rtt_us": rtt * 1e6,
                "algbw_GBps": algbw, "busbw_GBps": busbw,
                "vs_ici_assumption": busbw / ici_assumption_gbps,
                "devices": int(n),
            })
    return out


def print_table(records: List[Dict], ici_assumption_gbps: float) -> None:
    hdr = (f"{'op':<16}{'MB/dev':>9}{'time(us)':>12}{'algbw GB/s':>12}"
           f"{'busbw GB/s':>12}{'vs assumed':>12}")
    print(hdr)
    print("-" * len(hdr))
    for r in records:
        print(f"{r['op']:<16}{r['bytes_per_device']/2**20:>9.2f}"
              f"{r['time_us']:>12.1f}{r['algbw_GBps']:>12.2f}"
              f"{r['busbw_GBps']:>12.2f}{r['vs_ici_assumption']:>12.3f}")
    print(f"(busbw vs the {ici_assumption_gbps:.0f} GB/s effective-ICI "
          "constant the 70B projection assumes — SCALING_r04.json)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--ops", default="all_gather,all_reduce,"
                    "reduce_scatter,all_to_all,ppermute",
                    help=f"comma list from {OPS}")
    ap.add_argument("--sizes-mb", default="1,4,16,64",
                    help="per-device payload MB list")
    ap.add_argument("--axis", default="data")
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--ici-gbps", type=float, default=100.0,
                    help="assumed effective ICI GB/s to compare against")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line instead of the table")
    args = ap.parse_args(argv)

    from . import init_distributed

    init_distributed()
    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    for o in ops:
        if o not in OPS:
            ap.error(f"unknown op {o!r} (choose from {OPS})")
    sizes = [int(float(s) * 2**20) for s in args.sizes_mb.split(",")]
    records = sweep(ops, sizes, axis=args.axis, trials=args.trials,
                    dtype=jnp.dtype(args.dtype),
                    ici_assumption_gbps=args.ici_gbps)
    if jax.process_index() == 0:
        if args.json:
            print(json.dumps({"ds_bench": records}))
        else:
            print_table(records, args.ici_gbps)
    return 0


if __name__ == "__main__":
    sys.exit(main())
