"""Compile-time cost model tests (analysis/costmodel.py, S004-S006).

Same contract as the sanitizer suite: every check fires EXACTLY ONCE on
a deliberately seeded violation and stays silent on the real training /
decode / serving step programs. The ds_budget gate is exercised
end-to-end through its CLI against the committed MEMBUDGET.json and an
injected regression.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.analysis.costmodel import (
    CostReport,
    build_cost_report,
    check_against_baseline,
    check_collective_volume,
    check_hbm_budget,
    check_roofline,
    roofline,
)
from deepspeed_tpu.models import transformer as T

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 128


def model_cfg(**kw):
    base = dict(vocab_size=VOCAB, n_layers=2, n_heads=4, d_model=64,
                max_seq=32, variant="llama", use_flash=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def mesh8(shape=(8,), names=("d",)):
    return Mesh(np.array(jax.devices()[:8]).reshape(*shape), names)


# ----------------------------------------------------------------------
# hlo.py extensions: collective metadata + entry-param hardening
# ----------------------------------------------------------------------

class TestCollectiveMetadata:
    def test_explicit_replica_groups(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("%ag = bf16[16,64]{1,0} all-gather(bf16[4,64]{1,0} %x), "
               "replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}")
        recs = parse_hlo_collectives(hlo)
        assert len(recs) == 1
        assert recs[0]["group_size"] == 4
        assert recs[0]["operand_bytes"] == 4 * 64 * 2
        assert recs[0]["bytes"] == 16 * 64 * 2

    def test_iota_replica_groups(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = ("%rs = f32[2,8]{1,0} reduce-scatter(f32[8,8]{1,0} %x), "
               "replica_groups=[2,4]<=[8], dimensions={0}")
        recs = parse_hlo_collectives(hlo)
        assert recs[0]["group_size"] == 4

    def test_flat_world_group_is_zero(self):
        from deepspeed_tpu.profiling.hlo import parse_hlo_collectives

        hlo = "%ar = f32[4]{0} all-reduce(f32[4]{0} %x), replica_groups={}"
        recs = parse_hlo_collectives(hlo)
        assert recs[0]["group_size"] == 0


class TestEntryParamHardening:
    def test_token_typed_param(self):
        from deepspeed_tpu.profiling.hlo import parse_entry_parameters

        hlo = ("ENTRY %main (p0: f32[4], t: token[]) -> f32[4] {\n"
               "  %p0 = f32[4]{0} parameter(0)\n"
               "  %t = token[] parameter(1)\n"
               "}\n")
        recs = parse_entry_parameters(hlo)
        assert [r["index"] for r in recs] == [0, 1]
        assert recs[1]["dtype"] == "token"
        assert recs[1]["nbytes"] == 0
        assert recs[0]["nbytes"] == 16

    def test_tuple_nested_param(self):
        from deepspeed_tpu.profiling.hlo import parse_entry_parameters

        hlo = ("ENTRY %main (p: (f32[2,4], s32[])) -> f32[2,4] {\n"
               "  %p = (f32[2,4]{1,0}, s32[]) parameter(0), "
               "sharding={{replicated}, {replicated}}\n"
               "}\n")
        recs = parse_entry_parameters(hlo)
        assert len(recs) == 1
        assert recs[0]["dtype"] == "tuple"
        assert recs[0]["nbytes"] == 2 * 4 * 4 + 4


class TestSafeArtifactWrappers:
    class _Broken:
        def memory_analysis(self):
            raise NotImplementedError("unimplemented for this backend")

        def cost_analysis(self):
            raise NotImplementedError("unimplemented for this backend")

        def as_text(self):
            return ("HloModule m\n\nENTRY %main (p0: f32[8]) -> f32[8] {\n"
                    "  %p0 = f32[8]{0} parameter(0)\n}\n")

    def test_unimplemented_returns_none_not_crash(self):
        from deepspeed_tpu.profiling.hlo import (
            compiled_cost_stats,
            compiled_memory_stats,
        )

        assert compiled_memory_stats(self._Broken()) is None
        assert compiled_cost_stats(self._Broken()) is None

    def test_real_compiled_artifacts(self):
        from deepspeed_tpu.profiling.hlo import (
            compiled_cost_stats,
            compiled_memory_stats,
        )

        c = jax.jit(lambda x: x @ x).lower(
            jnp.zeros((16, 16), jnp.float32)).compile()
        mem = compiled_memory_stats(c)
        assert mem is not None and mem["argument_bytes"] == 16 * 16 * 4
        cost = compiled_cost_stats(c)
        assert cost is not None and cost["flops"] > 0

    def test_cost_list_form_normalized(self):
        from deepspeed_tpu.profiling.hlo import compiled_cost_stats

        class Listy:
            def cost_analysis(self):
                return [{"flops": 7.0, "bytes accessed": 3.0}]

        assert compiled_cost_stats(Listy()) == {"flops": 7.0,
                                                "bytes_accessed": 3.0}

    def test_estimated_fallback_report(self):
        rep = build_cost_report(self._Broken(), label="fallback")
        assert rep is not None and rep.estimated
        assert rep.arg_bytes == 8 * 4  # rebuilt from the entry params
        assert rep.peak_hbm_bytes == rep.arg_bytes


# ----------------------------------------------------------------------
# CostReport construction + projection
# ----------------------------------------------------------------------

class TestCostReport:
    def test_real_program_report(self):
        mesh = mesh8()
        w = jax.device_put(jnp.zeros((8, 64), jnp.float32),
                           NamedSharding(mesh, P("d")))
        c = jax.jit(lambda v: v * 2).lower(w).compile()
        rep = build_cost_report(c, label="x2")
        assert rep is not None and not rep.estimated
        assert rep.n_devices == 8
        assert rep.arg_bytes == 64 * 4  # per-shard: 1 of 8 rows
        assert rep.sharded_arg_bytes > 0 and rep.replicated_arg_bytes == 0
        assert rep.peak_hbm_bytes > 0

    def test_projection_shrinks_sharded_keeps_replicated(self):
        rep = CostReport(label="p", arg_bytes=1000, sharded_arg_bytes=800,
                         replicated_arg_bytes=200, n_devices=8)
        # 8 -> 64 devices: the sharded 800 shrinks 8x, the 200 stays
        assert rep.projected_arg_bytes(64) == 800 // 8 + 200
        # projecting DOWN concentrates shards (8 -> 2: 4x growth)
        assert rep.projected_arg_bytes(2) == 800 * 4 + 200


# ----------------------------------------------------------------------
# S004: per-device HBM budget
# ----------------------------------------------------------------------

class TestHbmBudgetCheck:
    def _report(self):
        # a replicated 1 MiB weight: every device holds the full copy
        w = jnp.zeros((256, 1024), jnp.float32)
        c = jax.jit(lambda v: v + 1).lower(w).compile()
        return build_cost_report(c, label="big_replicated")

    def test_over_budget_fires_exactly_once(self):
        rep = self._report()
        out = check_hbm_budget(rep, budget_bytes=256 * 1024)
        assert len(out.findings) == 1
        f = out.findings[0]
        assert f.rule == "S004" and f.severity == "error"
        assert "exceeds the per-device budget" in f.message

    def test_within_budget_is_silent(self):
        rep = self._report()
        assert check_hbm_budget(rep, budget_bytes=1 << 30).ok

    def test_replicated_floor_survives_projection(self):
        """A replicated-dominated program cannot be saved by a bigger
        mesh: the projected footprint stays over budget at any size."""
        rep = self._report()
        out = check_hbm_budget(rep, budget_bytes=256 * 1024,
                               target_devices=1024)
        assert len(out.findings) == 1
        assert "projected 1024 devices" in out.findings[0].message

    def test_sharded_program_shrinks_at_scale(self):
        mesh = mesh8()
        w = jax.device_put(jnp.zeros((8, 65536), jnp.float32),
                           NamedSharding(mesh, P("d")))
        c = jax.jit(lambda v: v * 2).lower(w).compile()
        rep = build_cost_report(c, label="sharded")
        budget = rep.peak_hbm_bytes // 2  # too small at 8 devices...
        assert not check_hbm_budget(rep, budget_bytes=budget).ok
        # ...but fits once the mesh grows 8x
        assert check_hbm_budget(rep, budget_bytes=budget,
                                target_devices=64).ok


# ----------------------------------------------------------------------
# S005: collective-volume blowups
# ----------------------------------------------------------------------

class TestCollectiveVolumeCheck:
    def test_seeded_full_gather_of_sharded_table_fires(self):
        """The accidental-replication class: a [64, 4096] f32 table
        sharded over 8 devices is materialized WHOLE (one full
        all-gather) when the consumer only needs a handful of rows."""
        mesh = mesh8()
        table = jax.device_put(jnp.zeros((64, 4096), jnp.float32),
                               NamedSharding(mesh, P("d")))

        def f(t, idx):
            # replicated constraint forces the full gather of t
            full = jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, P()))
            return full[idx]

        c = jax.jit(f).lower(table, jnp.zeros((4,), jnp.int32)).compile()
        rep = build_cost_report(c, label="lookup")
        assert rep.all_gather_bytes >= table.nbytes * 7 // 8
        # live need: the 4 rows the lookup consumes
        live = 4 * 4096 * 4
        out = check_collective_volume(rep, live_sharded_bytes=live, k=4.0)
        assert len(out.findings) == 1
        f0 = out.findings[0]
        assert f0.rule == "S005" and f0.severity == "error"
        assert "accidental full-gather" in f0.message

    def test_proportional_gather_is_silent(self):
        mesh = mesh8()
        table = jax.device_put(jnp.zeros((8, 4096), jnp.float32),
                               NamedSharding(mesh, P("d")))

        def f(t):
            full = jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, P()))
            return full.sum()

        c = jax.jit(f).lower(table).compile()
        rep = build_cost_report(c, label="reduce")
        # the whole table IS the live working set here: one gather of it
        # is proportional, not accidental
        out = check_collective_volume(
            rep, live_sharded_bytes=int(table.nbytes), k=4.0)
        assert out.ok

    def test_baseline_regression_fires(self):
        rep = CostReport(label="r", collectives={
            "all-reduce": {"count": 1, "bytes": 1200}})
        out = check_collective_volume(
            rep, baseline={"comm_bytes": 1000}, tolerance=0.10)
        assert len(out.findings) == 1
        assert "regressed" in out.findings[0].message

    def test_baseline_within_tolerance_is_silent(self):
        rep = CostReport(label="r", collectives={
            "all-reduce": {"count": 1, "bytes": 1050}})
        assert check_collective_volume(
            rep, baseline={"comm_bytes": 1000}, tolerance=0.10).ok


# ----------------------------------------------------------------------
# S006: roofline balance
# ----------------------------------------------------------------------

class TestRooflineCheck:
    def _comm_heavy(self):
        return CostReport(label="comm_heavy", flops=1e6, bytes_accessed=1e6,
                          collectives={"all-gather": {"count": 1,
                                                      "bytes": 1e9}})

    def test_comm_bound_program_flagged(self):
        rep = self._comm_heavy()
        out = check_roofline(rep, peak_flops=1e12, hbm_bandwidth=1e12,
                             ici_bandwidth=1e8, expect="compute")
        assert len(out.findings) == 1
        f = out.findings[0]
        assert f.rule == "S006" and "comm-bound" in f.message

    def test_compute_bound_is_silent(self):
        rep = CostReport(label="gemm", flops=1e12, bytes_accessed=1e6)
        assert check_roofline(rep, peak_flops=1e12, hbm_bandwidth=1e12,
                              expect="compute").ok

    def test_comm_only_tolerates_memory_bound(self):
        """Toy verification slices are legitimately memory-bound;
        comm_only keeps S006 quiet about that while still catching
        collective domination."""
        rep = CostReport(label="toy", flops=1e3, bytes_accessed=1e9)
        out = check_roofline(rep, peak_flops=1e12, hbm_bandwidth=1e9,
                             expect="compute", comm_only=True)
        assert out.ok
        out = check_roofline(rep, peak_flops=1e12, hbm_bandwidth=1e9,
                             expect="compute", comm_only=False)
        assert len(out.findings) == 1

    def test_no_cost_artifacts_is_silent(self):
        rep = CostReport(label="empty")
        assert check_roofline(rep, peak_flops=1e12,
                              hbm_bandwidth=1e12).ok

    def test_roofline_ratios(self):
        r = roofline(self._comm_heavy(), peak_flops=1e12,
                     hbm_bandwidth=1e12, ici_bandwidth=1e8)
        assert r["bound"] == "comm"
        assert r["t_ici"] == pytest.approx(10.0)


# ----------------------------------------------------------------------
# baseline regression form (ds_budget's S004)
# ----------------------------------------------------------------------

class TestBaselineCheck:
    def test_regression_fires(self):
        rep = CostReport(label="p", arg_bytes=1200)
        out = check_against_baseline(rep, {"peak_hbm_bytes": 1000},
                                     tolerance=0.10)
        assert len(out.findings) == 1
        assert out.findings[0].rule == "S004"

    def test_within_tolerance_silent(self):
        rep = CostReport(label="p", arg_bytes=1050)
        assert check_against_baseline(rep, {"peak_hbm_bytes": 1000},
                                      tolerance=0.10).ok


# ----------------------------------------------------------------------
# the real step programs stay silent (acceptance: S004/S005/S006 quiet
# on every real train/decode/serving step)
# ----------------------------------------------------------------------

class TestRealProgramsSilent:
    def test_train_step_cost_clean(self):
        mcfg = model_cfg()
        engine = ds.initialize(
            {"train_micro_batch_size_per_gpu": 1,
             "gradient_accumulation_steps": 2,
             "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 3,
                                   "param_persistence_threshold": 64},
             "bf16": {"enabled": True},
             "mesh": {"data": 4, "model": 2},
             "steps_per_print": 1000},
            loss_fn=T.make_loss_fn(mcfg),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg))
        batch = {"tokens": np.zeros(
            (engine.config.train_batch_size, 33), np.int32)}
        rep = engine.sanitize(batch)
        assert rep.ok, rep.render()
        assert rep.cost is not None
        assert rep.cost.peak_hbm_bytes > 0
        assert "peak" in rep.render()  # cost rides the report rendering

    def test_train_step_over_budget_fires_once(self):
        """The SAME healthy program becomes the seeded S004 violation
        under a deliberately impossible budget — exactly one finding."""
        mcfg = model_cfg()
        engine = ds.initialize(
            {"train_micro_batch_size_per_gpu": 1,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
             "steps_per_print": 1000,
             "mesh": {"data": 8}},
            loss_fn=T.make_loss_fn(mcfg),
            param_init_fn=lambda k: T.init(mcfg, k),
            param_logical_specs=T.logical_specs(mcfg))
        batch = {"tokens": np.zeros(
            (engine.config.train_batch_size, 33), np.int32)}
        rep = engine.sanitize(batch, hbm_budget_bytes=1024)
        s004 = [f for f in rep.findings if f.rule == "S004"]
        assert len(s004) == 1, rep.render()


class TestServingBudget:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = model_cfg(max_seq=64)
        return cfg, T.init(cfg, jax.random.PRNGKey(0))

    def _engine(self, model):
        from deepspeed_tpu.inference import init_inference

        cfg, params = model
        return init_inference(
            params, cfg,
            dict(max_seq_len=64, kv_block_size=8, num_kv_blocks=32,
                 min_prefill_bucket=8, max_batch_size=8),
            dtype=jnp.float32)

    def test_warmup_captures_footprints_and_budget_clean(self, model):
        from deepspeed_tpu.inference import (
            ServingScheduler,
            ServingSchedulerConfig,
        )

        sched = ServingScheduler(
            self._engine(model),
            ServingSchedulerConfig(max_num_batched_tokens=16))
        assert sched.engine.warmup_footprints  # per-bucket reports exist
        assert all(f["peak_hbm_bytes"] > 0
                   for f in sched.engine.warmup_footprints.values())
        assert sched.budget_report.ok, sched.budget_report.render()
        m = sched.metrics()
        assert m["hbm_peak_mb"] > 0
        assert any(k.startswith("hbm_w") for k in m)
        assert m["budget_findings"] == 0.0

    def test_over_budget_config_flagged_once(self, model):
        from deepspeed_tpu.inference import (
            ServingScheduler,
            ServingSchedulerConfig,
        )

        sched = ServingScheduler(
            self._engine(model),
            ServingSchedulerConfig(max_num_batched_tokens=16,
                                   hbm_budget_gb=1e-6))  # ~1 KB budget
        s004 = [f for f in sched.budget_report.findings
                if f.rule == "S004" and f.severity == "error"]
        assert len(s004) == 1
        assert sched.metrics()["budget_findings"] >= 1.0

    def test_token_budget_overcommit_warns(self, model):
        from deepspeed_tpu.inference import (
            ServingScheduler,
            ServingSchedulerConfig,
        )

        sched = ServingScheduler(
            self._engine(model),
            ServingSchedulerConfig(max_num_batched_tokens=10_000,
                                   warmup=False))
        assert any("overcommit" in f.message
                   for f in sched.budget_report.findings)


# ----------------------------------------------------------------------
# ds_budget CLI gate
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestDsBudgetScript:
    """Slow lane: each subprocess rebuilds EVERY canonical program
    (two engine compiles + two inference compiles since the MoE
    program joined) — and the pre-test gate lane already runs
    `ds_budget.py --check --strict` on every PR, so the fast lane
    carries no coverage gap."""

    def _run(self, *args):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the script sets its own device count
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "ds_budget.py"),
             *args],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=600)

    def test_check_passes_on_committed_tree(self):
        r = self._run("--check", "--strict")
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        assert doc["ok"] and doc["findings"] == []

    def test_check_fails_on_injected_regression(self, tmp_path):
        base = json.load(open(os.path.join(REPO, "MEMBUDGET.json")))
        # shrink the recorded baseline so the (unchanged) tree reads as
        # a >= 10% peak-HBM regression
        for prog in base["programs"].values():
            prog["peak_hbm_bytes"] = int(prog["peak_hbm_bytes"] * 0.8)
        injected = tmp_path / "membudget.json"
        injected.write_text(json.dumps(base))
        r = self._run("--check", "--baseline", str(injected))
        assert r.returncode != 0, r.stdout + r.stderr
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        assert not doc["ok"]
        assert any(f["rule"] == "S004" and "regressed" in f["message"]
                   for f in doc["findings"])

    def test_capture_roundtrip(self, tmp_path):
        out = tmp_path / "fresh.json"
        r = self._run("--capture", "--baseline", str(out))
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(out.read_text())
        assert set(doc["programs"]) == {"train_step", "train_step_moe",
                                        "train_step_pipe3d",
                                        "serving_decode_w8",
                                        "serving_decode_w8_int8"}
        assert all(p["peak_hbm_bytes"] > 0
                   for p in doc["programs"].values())
        # int8-KV capacity ratio committed + above the floor
        b = doc["budgets"]
        assert b["kv_bytes_per_token_ref"] >= 1.8 * \
            b["kv_bytes_per_token_int8"] > 0
        r = self._run("--check", "--strict", "--baseline", str(out))
        assert r.returncode == 0, r.stdout + r.stderr
