#!/usr/bin/env python
"""ds-overload CLI — deterministic overload-resilience gate: the
pressure governor, KV spill-to-host preemption, and SLO-aware
admission under a 4x-capacity burst (docs/fault_tolerance.md pressure
section).

Usage:
    python scripts/ds_overload.py                  # check vs committed OVERLOAD.json
    python scripts/ds_overload.py --check --strict # identical; gate-CLI symmetry
    python scripts/ds_overload.py --capture        # (re)write OVERLOAD.json
    python scripts/ds_overload.py --plan my.json   # custom plan

The eighth tier-1 pre-test gate next to ds_lint / ds_budget /
ds_numerics / the serving-fleet smoke / ds_chaos / ds_elastic / ds_sdc
(.claude/skills/verify/SKILL.md): runs `bench.py --overload-sim` — a
burst trace at ~4x single-replica capacity served against an
unpressured reference, with the governor + spill tier on and then with
armed 'spill.io' faults — and fails unless every gate holds:

  no_livelock_every_admitted_request_finishes
                                     sustained pressure never wedges
                                     the scheduler; every admitted
                                     request reaches a finish_reason
  spill_path_exercised_under_red     the governor climbed to RED and
                                     answered preemption with
                                     export-to-host + import-resume
  spill_resume_token_identical       spilled/resumed outputs equal the
                                     unpressured run token for token
  spill_fault_falls_back_to_recompute injected spill put/get failures
                                     fell back to flush-and-recompute
                                     with zero token loss
  deadline_rejects_consume_no_blocks unservable SLO deadlines rejected
                                     at submit (finish_reason
                                     'deadline'), zero KV blocks
                                     touched, nothing leaked
  deterministic_rerun                same plan + same trace = same
                                     spills, fallbacks, and tokens,
                                     byte for byte
  ledger_matches_baseline            spill/rejection counts equal the
                                     committed OVERLOAD.json

A legitimate change to the lane's geometry re-captures the baseline in
the same PR: `python scripts/ds_overload.py --capture` and commit
OVERLOAD.json. Everything is virtual-time and seeded: a red gate is a
pressure-governor regression, never flake. The only exception is the
shared device-probe guard (bench_device_guard): backend-init timeouts
exit 0 with an infra_flake marker per the ROADMAP flaky-infra policy.
"""

import argparse
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--plan", default="default",
                    help="'default' (the committed OVERLOAD.json) or a "
                         "FaultPlan JSON path with workload/expect "
                         "blocks")
    ap.add_argument("--capture", action="store_true",
                    help="run the lane and (re)write OVERLOAD.json "
                         "with the plan + measured pressure ledger")
    ap.add_argument("--check", action="store_true",
                    help="explicit check mode (the default)")
    ap.add_argument("--strict", action="store_true",
                    help="accepted for symmetry with the other gates "
                         "(every overload gate is already hard)")
    args = ap.parse_args(argv)

    from deepspeed_tpu.platform.accelerator import bench_device_guard

    rc = bench_device_guard("overload_sim_gates_green",
                            timeout_default=120.0)
    if rc is not None:
        return rc  # infra flake -> 0 per ROADMAP policy, init error -> 1

    import bench

    capture = os.path.join(_REPO, "OVERLOAD.json") if args.capture \
        else None
    rc = bench._overload_sim(args.plan, capture=capture)
    print(json.dumps({"ok": rc == 0, "gate": "ds_overload",
                      "plan": args.plan,
                      "mode": "capture" if args.capture else "check"}),
          file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
