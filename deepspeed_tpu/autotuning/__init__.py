from .autotuner import Autotuner
