"""Elastic-autoscaling tests (docs/autoscaling.md): the Autoscaler
policy loop on a fake fleet under a virtual clock (hysteresis,
asymmetric cooldowns, premium bypass, burned-spin-up retry backoff),
the router's replica lifecycle (cache-warm spin-up with donor-RED
deferral, two-phase join + queue rebalance, graceful drain with
page-move migration, typed last-replica rejection, stable metric
ids across add/drain/release), the parked-prefix-chain export
substrate, and the replica.spinup/replica.drain chaos points.

Fast lane: the policy-loop suite (FakeFleet, pure host arithmetic)
plus the cheap lifecycle edges. The engine-backed lifecycle lanes
(tiny model, f32, CPU, warmup off — but every test builds 2-5 fresh
engines whose decode programs compile) are slow-marked: the fast
tier-1 lane was already at its timeout budget, and the ds_autoscale
pre-test gate exercises the same spin-up/drain/chaos machinery
end-to-end deterministically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config.config import AutoscalerConfig
from deepspeed_tpu.inference import (
    Autoscaler,
    ReplicaDrainError,
    RouterFleetAdapter,
    ServingRouter,
    ServingScheduler,
    ServingSchedulerConfig,
    init_inference,
)
from deepspeed_tpu.inference.engine import HandoffIntegrityError
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.resilience import FaultPlan, armed
from deepspeed_tpu.resilience.faults import InjectedFault


@pytest.fixture(scope="module")
def model():
    cfg = T.TransformerConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=64, max_seq=64,
        variant="llama", use_flash=False)
    params = T.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def engine_for(model, **over):
    cfg, params = model
    kw = dict(max_seq_len=64, kv_block_size=8, num_kv_blocks=32,
              min_prefill_bucket=8, max_batch_size=8)
    kw.update(over)
    return init_inference(params, cfg, kw, dtype=jnp.float32)


NO_WARM = {"scheduler": {"warmup": False}}


def router_for(model, n, seed=0, clock=None, **cfg):
    c = dict(NO_WARM)
    c.update(cfg)
    c["replicas"] = n
    return ServingRouter([engine_for(model) for _ in range(n)], c,
                         seed=seed, clock=clock)


def reference_outputs(model, prompts, max_new, seed=0):
    sched = ServingScheduler(
        engine_for(model), ServingSchedulerConfig(warmup=False),
        seed=seed)
    rids = [sched.submit(p, max_new, stream=i)
            for i, p in enumerate(prompts)]
    sched.run()
    return [sched.finished[r].output for r in rids]


# -- the policy loop on a fake fleet -----------------------------------
class FakeFleet:
    """Scripted fleet: the policy loop's decisions are observed, its
    scale calls mutate only counters."""

    def __init__(self, n=2, fail_spinups=0):
        self.n = n
        self.sig = {"queue_depth": 0.0, "max_pressure_level": 0.0,
                    "shed_requests": 0.0, "deadline_rejections": 0.0,
                    "premium_sheds": 0.0, "premium_rejections": 0.0}
        self.ups = []
        self.downs = []
        self.fail_spinups = fail_spinups

    def live_replicas(self):
        return self.n

    def signals(self):
        return dict(self.sig)

    def scale_up(self, now):
        if self.fail_spinups > 0:
            self.fail_spinups -= 1
            raise InjectedFault("spin-up burned")
        self.n += 1
        self.ups.append(now)

    def scale_down(self, now):
        if self.n <= 1:
            return False
        self.n -= 1
        self.downs.append(now)
        return True


ACFG = dict(enabled=True, min_replicas=1, max_replicas=4,
            evaluation_interval_s=1.0, scale_up_pressure=2,
            scale_up_queue_per_replica=4.0,
            scale_down_queue_per_replica=1.0,
            up_hysteresis=2, down_hysteresis=3,
            scale_up_cooldown_s=5.0, scale_down_cooldown_s=10.0,
            spinup_retry_backoff_s=1.0, spinup_max_retries=2,
            premium_classes=["premium"])


class TestAutoscalerPolicy:
    def test_scale_up_needs_hysteresis(self):
        fleet = FakeFleet(2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        assert asc.tick(now=0.0) is None       # vote 1 of 2
        assert asc.tick(now=1.0) == "scale_up"  # vote 2 fires
        assert fleet.ups == [1.0]

    def test_noise_resets_votes(self):
        fleet = FakeFleet(2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        asc.tick(now=0.0)
        fleet.sig["max_pressure_level"] = 0.0   # blip clears
        asc.tick(now=1.0)
        fleet.sig["max_pressure_level"] = 2.0
        assert asc.tick(now=2.0) is None        # votes restarted
        assert asc.tick(now=3.0) == "scale_up"

    def test_premium_impact_bypasses_hysteresis(self):
        fleet = FakeFleet(2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        asc.tick(now=0.0)                       # baseline deltas
        fleet.sig["premium_sheds"] = 1.0
        fleet.sig["shed_requests"] = 1.0
        assert asc.tick(now=1.0) == "scale_up"  # ONE eval, no wait
        assert asc.counters["premium_bypass"] == 1

    def test_cooldown_holds_second_scale_up(self):
        fleet = FakeFleet(2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        asc.tick(now=0.0)
        assert asc.tick(now=1.0) == "scale_up"
        asc.tick(now=2.0)
        assert asc.tick(now=3.0) is None        # inside 5 s cooldown
        assert asc.counters["cooldown_holds"] >= 1
        # votes kept accruing through the hold: the first eval past
        # the cooldown window acts
        assert asc.tick(now=6.5) == "scale_up"

    def test_scale_down_needs_long_calm_and_respects_min(self):
        fleet = FakeFleet(2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        for t in (0.0, 1.0):
            assert asc.tick(now=t) is None      # calm votes 1, 2
        assert asc.tick(now=2.0) == "scale_down"  # vote 3 fires
        assert fleet.n == 1
        # at min_replicas the fleet never shrinks further
        for t in (20.0, 21.0, 22.0, 23.0):
            assert asc.tick(now=t) is None
        assert fleet.n == 1

    def test_max_replicas_denies_scale_up(self):
        fleet = FakeFleet(4)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        asc.tick(now=0.0)
        assert asc.tick(now=1.0) is None
        assert asc.counters["scale_up_denied"] == 1
        assert fleet.n == 4

    def test_burned_spinup_retries_with_exponential_backoff(self):
        fleet = FakeFleet(2, fail_spinups=2)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        asc.tick(now=0.0)
        assert asc.tick(now=1.0) == "spinup_failed"   # burn 1
        assert asc.tick(now=1.5) is None              # backoff 1.0 s
        assert asc.tick(now=2.0) == "spinup_failed"   # retry burns
        # backoff doubled to 2.0 s; the eval path must NOT race past
        # the pending retry's backoff window
        assert asc.tick(now=3.0) is None
        assert asc.tick(now=4.0) == "scale_up"        # retry succeeds
        assert asc.counters["spinup_failures"] == 2
        assert asc.counters["spinup_retries"] == 2
        assert fleet.ups == [4.0]

    def test_retry_exhaustion_rearms_on_signal(self):
        fleet = FakeFleet(2, fail_spinups=3)
        asc = Autoscaler(fleet, ACFG, clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 2.0
        asc.tick(now=0.0)
        asc.tick(now=1.0)           # burn 1, schedules retry
        asc.tick(now=2.0)           # retry burn 2
        asc.tick(now=4.0)           # retry burn 3 -> abandoned
        assert asc._retry_at is None
        # the NEXT evaluation window can still decide to scale up
        # (votes held through the burned attempts)
        assert asc.tick(now=5.0) == "scale_up"

    def test_disabled_autoscaler_never_acts(self):
        fleet = FakeFleet(1)
        asc = Autoscaler(fleet, dict(ACFG, enabled=False),
                         clock=lambda: 0.0)
        fleet.sig["max_pressure_level"] = 3.0
        for t in range(5):
            assert asc.tick(now=float(t)) is None
        assert fleet.n == 1

    def test_config_dead_band_validated(self):
        with pytest.raises(ValueError):
            AutoscalerConfig(scale_up_queue_per_replica=1.0,
                             scale_down_queue_per_replica=2.0)
        with pytest.raises(ValueError):
            AutoscalerConfig(min_replicas=4, max_replicas=2)


# -- parked-chain export substrate -------------------------------------
class TestParkedChainExport:
    def test_parked_chains_enumerate_flushed_prefixes(self, model, rng):
        eng = engine_for(model)
        prefix = list(rng.integers(0, 128, 16))  # 2 full blocks
        eng.generate([prefix + [1, 2]], max_new_tokens=2)
        chains = eng.state.parked_chains(8)
        assert len(chains) == 1
        tokens, blocks = chains[0]
        assert tokens == prefix and len(blocks) == 2

    @pytest.mark.slow
    def test_export_import_registers_prefix_on_joiner(self, model, rng):
        donor = engine_for(model)
        prefix = list(rng.integers(0, 128, 16))
        donor.generate([prefix + [1, 2]], max_new_tokens=2)
        payloads = donor.export_parked_kv(8)
        assert len(payloads) == 1 and "digest" in payloads[0]
        joiner = engine_for(model)
        joiner.import_kv(0, payloads[0])
        joiner.flush(0)
        assert joiner.state.lookup_prefix(prefix + [7, 7]) == 16
        # warm pages serve token-identically to a cold engine
        probe = prefix + list(rng.integers(0, 128, 4))
        cold = engine_for(model).generate([probe], max_new_tokens=4)
        assert joiner.generate([probe], max_new_tokens=4) == cold

    @pytest.mark.slow
    def test_tampered_warm_payload_rejected(self, model, rng):
        donor = engine_for(model)
        prefix = list(rng.integers(0, 128, 16))
        donor.generate([prefix + [1, 2]], max_new_tokens=2)
        payload = donor.export_parked_kv(1)[0]
        payload["k"] = payload["k"].copy()
        payload["k"].reshape(-1)[3] += 1
        joiner = engine_for(model)
        with pytest.raises(HandoffIntegrityError):
            joiner.import_kv(0, payload)


# -- replica lifecycle on the real router ------------------------------
class TestSpinUp:
    @pytest.mark.slow
    def test_add_replica_warm_boots_and_serves(self, model, rng):
        router = router_for(model, 1)
        prefix = list(rng.integers(0, 128, 16))
        prompts = [prefix + list(rng.integers(0, 128, 4))
                   for _ in range(3)]
        for p in prompts[:2]:
            router.submit(p, 4)
        router.serve()
        rid = router.add_replica(engine_for(model))
        assert rid == 1
        assert router.counters["scale_ups"] == 1
        assert router.counters["warm_prefix_imports"] >= 1
        # the joiner's index already holds the donor's prefix
        assert router.schedulers[1].engine.state.lookup_prefix(
            prefix + [5, 5]) == 16
        g = router.submit(prompts[2], 4)
        router.serve()
        ref = reference_outputs(model, prompts, 4)
        assert router.result(g).output == ref[2]

    def test_two_phase_join_skips_warming_replica(self, model, rng):
        router = router_for(model, 1)
        rid = router.add_replica(engine_for(model), join=False)
        assert router.lifecycle(rid) == "warming"
        # routing never picks a warming replica
        for _ in range(4):
            g = router.submit(list(rng.integers(0, 128, 8)), 2)
            assert router._where[g] == 0
        router.join_replica(rid)
        assert router.lifecycle(rid) == "active"

    @pytest.mark.slow
    def test_join_rebalances_waiting_backlog(self, model, rng):
        router = router_for(model, 1)
        for _ in range(10):
            router.submit(list(rng.integers(0, 128, 8)), 2)
        rid = router.add_replica(engine_for(model), join=False)
        assert len(router.schedulers[rid].waiting) == 0
        router.join_replica(rid)
        assert router.counters["rebalanced_on_join"] >= 4
        assert len(router.schedulers[rid].waiting) >= 4
        router.serve()
        assert all(r.done for r in router._reqs.values())

    @pytest.mark.slow
    def test_warm_boot_defers_when_donor_at_red(self, model, rng):
        # a governor'd donor whose pool sits above the RED watermark:
        # the join must go cache-cold and touch NOTHING on the donor
        router = router_for(model, 1, scheduler={
            "warmup": False,
            "pressure": {"enabled": True, "yellow": 0.2, "red": 0.3,
                         "brownout": 0.99}})
        prefix = list(rng.integers(0, 128, 16))
        router.submit(prefix + [1, 2], 2)
        router.serve()  # parks the prefix chain
        # pin live occupancy above RED with long prompts mid-flight
        gids = [router.submit(list(rng.integers(0, 128, 40)), 24)
                for _ in range(6)]
        for _ in range(3):
            router.step()
        router.schedulers[0].governor.update()
        assert router._pressure(0) >= 2
        evict0 = router.schedulers[0].engine.state.cache_stats()[
            "evictions"]
        rid = router.add_replica(engine_for(model))
        assert router.counters["warm_joins_deferred"] == 1
        assert router.counters["warm_prefix_imports"] == 0
        assert router.schedulers[rid].engine.state.indexed_blocks == 0
        assert router.schedulers[0].engine.state.cache_stats()[
            "evictions"] == evict0  # no eviction storm on the donor
        router.serve()
        assert all(router.result(g).done for g in gids)

    @pytest.mark.slow
    def test_spinup_chaos_burns_replica_and_autoscaler_retries(
            self, model, rng):
        t = [0.0]
        router = router_for(model, 1, clock=lambda: t[0])
        adapter = RouterFleetAdapter(router, lambda: engine_for(model))
        asc = Autoscaler(adapter, dict(ACFG, up_hysteresis=1),
                         clock=lambda: t[0])
        plan = FaultPlan([{"point": "replica.spinup", "kind": "raise",
                           "error": "io", "where": {"phase": "join"},
                           "at": 1, "times": 1}])
        with armed(plan):
            for _ in range(12):
                router.submit(list(rng.integers(0, 128, 8)), 2)
            # up_hysteresis=1: the first eval sees the queue and acts;
            # the armed plan kills the spin-up at its join phase
            assert asc.tick(now=0.0) == "spinup_failed"
            assert router.counters["burned_replicas"] == 1
            assert len(router.schedulers) == 1    # nothing registered
            t[0] = 1.0
            assert asc.tick(now=1.0) == "scale_up"  # backoff retry
        assert len(router.schedulers) == 2
        assert router.lifecycle(1) == "active"
        router.serve()
        assert all(r.done for r in router._reqs.values())


class TestDrain:
    @pytest.mark.slow
    def test_drain_migrates_running_sequences_token_identically(
            self, model, rng):
        prompts = [list(rng.integers(0, 128, 8)) for _ in range(6)]
        ref = reference_outputs(model, prompts, 12)
        router = router_for(model, 2, policy="round_robin")
        gids = [router.submit(p, 12) for p in prompts]
        for _ in range(3):
            router.step()  # mid-decode on both replicas
        victim = 1
        assert any(r.state == "running"
                   for r in router.schedulers[victim].active) or \
            router.schedulers[victim].active
        router.drain_replica(victim)
        assert router.counters["drain_migrations"] >= 1
        router.serve()
        assert router.lifecycle(victim) == "released"
        assert [router.result(g).output for g in gids] == ref
        m = router.metrics()
        assert m["fleet/scale_downs"] == 1.0
        assert m["fleet/drain_p95_ms"] >= 0.0

    @pytest.mark.slow
    def test_drain_breaks_and_repins_sessions(self, model, rng):
        router = router_for(model, 2)
        p = list(rng.integers(0, 128, 8))
        g = router.submit(p, 2, session="s")
        pinned = router._where[g]
        router.serve()
        router.drain_replica(pinned)
        assert router.counters["affinity_drain_breaks"] == 1
        assert "s" not in router._sessions
        g2 = router.submit(p + [1], 2, session="s")
        other = router._where[g2]
        assert other != pinned
        assert router._sessions["s"] == other  # re-scored + re-pinned
        router.serve()
        assert router.result(g2).done

    def test_drain_last_decode_replica_rejected_typed(self, model):
        router = router_for(model, 1)
        with pytest.raises(ReplicaDrainError):
            router.drain_replica(0)
        # two replicas, one already draining: the second is now last
        router = router_for(model, 2)
        router.drain_replica(1)
        with pytest.raises(ReplicaDrainError):
            router.drain_replica(0)

    @pytest.mark.slow
    def test_drain_with_in_flight_handoff_payload(self, model, rng):
        """A draining prefill replica's parked handoff payloads are
        finished work: pump() must move them to decode replicas (never
        INTO the draining one) and the drain completes with zero token
        change."""
        prompts = [list(rng.integers(0, 128, 8)) for _ in range(3)]
        ref = reference_outputs(model, prompts, 8)
        router = router_for(model, 3, mode="disaggregated",
                            prefill_replicas=2)
        gids = [router.submit(p, 8) for p in prompts]
        # prefill until at least one handoff parks, WITHOUT pumping
        for _ in range(12):
            if any(s.handoff_ready for s in router.schedulers):
                break
            for i in range(3):
                router.schedulers[i].step()
        assert any(s.handoff_ready
                   for i, s in enumerate(router.schedulers)
                   if i in router.prefill_idx)
        victim = next(i for i in router.prefill_idx
                      if router.schedulers[i].handoff_ready)
        router.drain_replica(victim)
        assert router.lifecycle(victim) == "draining"
        router.serve()  # pump drains the payload out, drain completes
        assert router.lifecycle(victim) == "released"
        assert victim not in router.prefill_idx
        assert [router.result(g).output for g in gids] == ref

    @pytest.mark.slow
    def test_draining_replica_invisible_to_routing_and_pump(
            self, model, rng):
        router = router_for(model, 3, policy="round_robin")
        gids = [router.submit(list(rng.integers(0, 128, 8)), 20)
                for _ in range(3)]
        for _ in range(2):
            router.step()
        router.drain_replica(2)
        for _ in range(6):
            g = router.submit(list(rng.integers(0, 128, 8)), 2)
            assert router._where[g] != 2
        assert not router._decode_can_take() or all(
            i != 2 for i in router.decode_idx if router._routable(i))
        router.serve()
        assert all(r.done for r in router._reqs.values())

    @pytest.mark.slow
    def test_released_slot_is_tombstoned(self, model, rng):
        router = router_for(model, 2)
        g = router.submit(list(rng.integers(0, 128, 8)), 2)
        router.serve()
        router.drain_replica(1)
        router.serve()
        assert router.lifecycle(1) == "released"
        assert router.fail_replica(1) == 0
        with pytest.raises(ValueError):
            router.restore_replica(1)
        with pytest.raises(ValueError):
            router.drain_replica(1)
        # a new replica gets a FRESH id — released ids are never reused
        rid = router.add_replica(engine_for(model))
        assert rid == 2
        assert router.result(g).done

    @pytest.mark.slow
    def test_drain_fault_point_fires(self, model, rng):
        router = router_for(model, 2)
        plan = FaultPlan([{"point": "replica.drain", "kind": "raise",
                           "error": "io", "at": 1, "times": 1}])
        with armed(plan):
            with pytest.raises(InjectedFault):
                router.drain_replica(1)
        # nothing mutated: the replica still serves
        assert router.lifecycle(1) == "active"
        g = router.submit(list(rng.integers(0, 128, 8)), 2)
        router.serve()
        assert router.result(g).done


class TestObservability:
    @pytest.mark.slow
    def test_metric_ids_stable_across_add_and_release(self, model, rng):
        t = [0.0]
        router = router_for(model, 2, clock=lambda: t[0])
        router.observe_time(0.0)
        g = router.submit(list(rng.integers(0, 128, 8)), 4)
        router.serve()
        before = router.metrics()
        assert before["replica1/lifecycle"] == 0.0
        t[0] = 3600.0
        rid = router.add_replica(engine_for(model), now=3600.0)
        t[0] = 7200.0
        router.drain_replica(1, now=7200.0)
        router.serve()
        m = router.metrics()
        # stable ids: replica1's name still means the SAME replica
        assert m["replica1/lifecycle"] == 3.0          # released
        assert m[f"replica{rid}/lifecycle"] == 0.0     # the newcomer
        assert m["fleet/replicas"] == 3.0
        assert m["fleet/live_replicas"] == 2.0
        assert m["fleet/released_replicas"] == 1.0
        assert m["fleet/scale_ups"] == 1.0
        assert m["fleet/scale_downs"] == 1.0
        # replica-hours integrated on the injected clock: 2 replicas
        # for the first hour, 3 for the second
        assert m["fleet/replica_hours"] == pytest.approx(5.0)
        # released replicas keep their final counters addressable
        assert f"replica1/steps" in m

    @pytest.mark.slow
    def test_monitor_events_include_lifecycle_keys(self, model, rng):
        from deepspeed_tpu.monitor.monitor import serving_events

        router = router_for(model, 2)
        router.submit(list(rng.integers(0, 128, 8)), 2)
        router.serve()
        names = {n for n, _, _ in serving_events(router, step=1)}
        for key in ("fleet/replica_hours", "fleet/scale_ups",
                    "fleet/scale_downs", "fleet/drain_p95_ms",
                    "fleet/warming_replicas",
                    "fleet/draining_replicas"):
            assert f"inference/serving/{key}" in names
        assert "inference/serving/replica0/lifecycle" in names

    def test_shed_by_class_counts_premium(self, model, rng):
        router = router_for(model, 1, max_fleet_queue=2,
                            scheduler={"warmup": False,
                                       "slo_classes": {"premium": 60.0}})
        router.submit(list(rng.integers(0, 128, 8)), 2,
                      session="a", slo_class="premium")
        router.submit(list(rng.integers(0, 128, 8)), 2, session="a",
                      slo_class="premium")
        with pytest.raises(Exception):
            router.submit(list(rng.integers(0, 128, 8)), 2,
                          session="a", slo_class="premium")
        assert router.shed_by_class.get("premium", 0) >= 1
        assert router.metrics()["fleet/shed_premium"] >= 1.0


class TestAdapter:
    @pytest.mark.slow
    def test_adapter_signals_and_scale_paths(self, model, rng):
        router = router_for(model, 2)
        adapter = RouterFleetAdapter(router, lambda: engine_for(model),
                                     premium_classes=("premium",))
        for _ in range(4):
            router.submit(list(rng.integers(0, 128, 8)), 2)
        sig = adapter.signals()
        assert sig["queue_depth"] == 4.0
        assert adapter.live_replicas() == 2
        rid = adapter.scale_up(now=0.0)
        assert adapter.live_replicas() == 3
        assert adapter.scale_down(now=1.0)
        router.serve()
        assert router.counters["scale_downs"] == 1
        # the drained victim was the youngest idle replica, never the
        # last one: two more downs hit the floor
        assert adapter.scale_down(now=2.0)
        router.serve()
        assert not adapter.scale_down(now=3.0)
