from .config import (
    DeepSpeedTPUConfig,
    MeshConfig,
    OffloadConfig,
    ZeroConfig,
    ZeroStage,
    parse_config,
)
