"""Loss-scaler state machine tests (ref model: tests/unit/runtime/
half_precision — DynamicLossScaler dynamics)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.config.config import FP16Config
from deepspeed_tpu.runtime.precision import (

    clip_grads_by_global_norm,
    found_inf_in_grads,
    global_grad_norm,
    init_loss_scale,
    update_loss_scale,
)

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def cfg(**kw):
    return FP16Config(enabled=True, **kw)


def test_initial_scale():
    s = init_loss_scale(cfg(initial_scale_power=8))
    assert float(s.scale) == 256.0


def test_backoff_on_overflow():
    c = cfg(initial_scale_power=8, hysteresis=1)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 128.0


def test_hysteresis_delays_backoff():
    c = cfg(initial_scale_power=8, hysteresis=2)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 256.0  # first overflow burns hysteresis
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 128.0


def test_growth_after_window():
    c = cfg(initial_scale_power=8, loss_scale_window=3, hysteresis=1)
    s = init_loss_scale(c)
    for _ in range(3):
        s = update_loss_scale(s, jnp.bool_(False), c)
    assert float(s.scale) == 512.0


def test_static_scale_never_moves():
    c = cfg(loss_scale=1024.0)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 1024.0


def test_min_loss_scale_floor():
    c = cfg(initial_scale_power=1, hysteresis=1, min_loss_scale=1.0)
    s = init_loss_scale(c)
    for _ in range(5):
        s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 1.0


def test_found_inf():
    good = {"a": jnp.ones(3), "b": jnp.zeros(2)}
    bad = {"a": jnp.array([1.0, jnp.inf]), "b": jnp.zeros(2)}
    assert not bool(found_inf_in_grads(good))
    assert bool(found_inf_in_grads(bad))


def test_global_norm_and_clip():
    grads = {"a": jnp.full((3,), 2.0), "b": jnp.full((4,), 2.0)}
    n = global_grad_norm(grads)
    np.testing.assert_allclose(float(n), (7 * 4.0) ** 0.5, rtol=1e-6)
    clipped = clip_grads_by_global_norm(grads, 1.0, n)
    np.testing.assert_allclose(float(global_grad_norm(clipped)), 1.0, rtol=1e-4)
    # no-op when under the limit
    same = clip_grads_by_global_norm(grads, 100.0, n)
    np.testing.assert_allclose(same["a"], grads["a"], rtol=1e-6)


def test_sustained_overflow_keeps_halving():
    """Reference consecutive_hysteresis=False: once hysteresis is spent,
    EVERY further overflow halves (ADVICE r1: fast divergence recovery)."""
    c = cfg(initial_scale_power=8, hysteresis=2)
    s = init_loss_scale(c)
    scales = []
    for _ in range(4):
        s = update_loss_scale(s, jnp.bool_(True), c)
        scales.append(float(s.scale))
    assert scales == [256.0, 128.0, 64.0, 32.0]


def test_good_steps_do_not_refill_hysteresis():
    c = cfg(initial_scale_power=8, hysteresis=2, loss_scale_window=1000)
    s = update_loss_scale(init_loss_scale(c), jnp.bool_(True), c)  # burn 1
    s = update_loss_scale(s, jnp.bool_(False), c)  # good step: no refill
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 128.0  # halves immediately


def test_consecutive_hysteresis_refills_on_good_steps():
    c = cfg(initial_scale_power=8, hysteresis=2, consecutive_hysteresis=True)
    s = update_loss_scale(init_loss_scale(c), jnp.bool_(True), c)  # burn 1
    s = update_loss_scale(s, jnp.bool_(False), c)  # refill
    s = update_loss_scale(s, jnp.bool_(True), c)  # burns refilled credit
    assert float(s.scale) == 256.0


# --- direct overflow/growth WINDOW dynamics (PR-5 satellite) -----------

def test_overflow_resets_growth_window():
    """good_steps is the growth window's clock: an overflow at step
    window-1 zeroes it, so growth needs a FULL clean window again."""
    c = cfg(initial_scale_power=8, loss_scale_window=3, hysteresis=1)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(False), c)
    s = update_loss_scale(s, jnp.bool_(False), c)
    s = update_loss_scale(s, jnp.bool_(True), c)  # overflow at window-1
    assert int(s.good_steps) == 0
    assert float(s.scale) == 128.0  # hysteresis=1: immediate backoff
    for _ in range(2):
        s = update_loss_scale(s, jnp.bool_(False), c)
    assert float(s.scale) == 128.0  # window not yet refilled
    s = update_loss_scale(s, jnp.bool_(False), c)
    assert float(s.scale) == 256.0  # full window elapsed -> grow


def test_growth_exactly_at_window_boundary():
    c = cfg(initial_scale_power=8, loss_scale_window=2, hysteresis=1)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(False), c)
    assert float(s.scale) == 256.0  # 1 < window: no growth yet
    s = update_loss_scale(s, jnp.bool_(False), c)
    assert float(s.scale) == 512.0  # exactly window clean steps
    assert int(s.good_steps) == 0  # window clock restarts after growth


def test_growth_refills_hysteresis():
    """Growth is the ONLY hysteresis refill under the reference default
    (consecutive_hysteresis=False)."""
    c = cfg(initial_scale_power=8, loss_scale_window=2, hysteresis=2)
    s = init_loss_scale(c)
    s = update_loss_scale(s, jnp.bool_(True), c)  # burn one credit
    assert int(s.hysteresis_left) == 1
    s = update_loss_scale(s, jnp.bool_(False), c)
    s = update_loss_scale(s, jnp.bool_(False), c)  # window -> grow
    assert float(s.scale) == 512.0
    assert int(s.hysteresis_left) == 2  # refilled by growth
    s = update_loss_scale(s, jnp.bool_(True), c)
    assert float(s.scale) == 512.0  # credit absorbs the next overflow


def test_found_inf_skips_integer_leaves():
    grads = {"w": jnp.array([1.0, 2.0]),
             "token_count": jnp.array([3], jnp.int32)}
    assert not bool(found_inf_in_grads(grads))
    grads["w"] = jnp.array([1.0, jnp.inf])
    assert bool(found_inf_in_grads(grads))


def test_found_inf_empty_and_integer_only_trees():
    assert not bool(found_inf_in_grads({}))
    assert not bool(found_inf_in_grads(
        {"steps": jnp.zeros((2,), jnp.int32)}))
