"""Peer-redundant ZeRO shards: in-memory checkpoints that turn a
preemption into a seconds-scale reshard instead of a minutes-scale disk
restore (docs/fault_tolerance.md training section).

The Gemini (Wang et al., SOSP'23) / Bamboo (Thorpe et al., NSDI'23)
observation: under ZeRO the optimizer state is already partitioned one
shard per rank, so every rank can mirror its shard to a neighbor's host
DRAM every K steps at a cost that is tiny next to the step itself. When
a world of W loses up to `spare` ranks, the lost shards still exist on
surviving peers: reconstruction is a host-side concatenation, and
`reshard_state` lays the assembled arrays onto whatever mesh the
surviving world builds — NO disk checkpoint is read. Recovery rolls the
whole world back to the last mirror boundary (at most K-1 steps), and
the dataloader/RNG state carried in the same snapshot makes the replay
sample-exact (no loss, no duplication — elasticity/trainer.py owns the
ledger).

Storage model (honesty contract): `PeerRedundantStore` keeps one
payload per (holder rank) — a rank's OWN slice plus the slices mirrored
TO it by its `spare` predecessors-by-stride. `lose(ranks)` deletes
everything those hosts held, exactly as a preemption would; a
reconstruction may only consume what survives. The store itself is
plain host numpy — it outlives the engine whose mesh died.

Slicing contract: `runtime/zero.zero_sharded_dims` names, per leaf, the
dim that carries the ZeRO axes (-1 = replicated). Rank r of a world of
W owns [r*d/W, (r+1)*d/W) along that dim — the same partition XLA's
SPMD sharding uses, so a payload is byte-identical to what rank r's HBM
actually holds.
"""

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .faults import fault_point
from .integrity import corrupt_tree, tree_digest
from ..utils.logging import log_dist

__all__ = [
    "RedundancyError", "UnrecoverableWorldError", "PeerRedundantStore",
    "slice_tree", "assemble_tree", "assemble_state", "split_dims",
    "stage_payload_bytes", "engine_shard_dims",
    "export_rank_payloads", "reshard_state",
]


class RedundancyError(RuntimeError):
    """Peer-redundancy protocol violation (bad world/slice geometry)."""


class UnrecoverableWorldError(RedundancyError):
    """More ranks died than the redundancy degree covers: some shard
    exists on no surviving host. The caller falls back to the last
    verified disk checkpoint (the path this module exists to avoid)."""

    def __init__(self, missing_ranks):
        self.missing_ranks = list(missing_ranks)
        super().__init__(
            f"shards of rank(s) {self.missing_ranks} survive on no live "
            "host; peer reconstruction impossible — disk fallback required"
        )


# ---------------------------------------------------------------------------
# slice/assemble: the shard <-> full-array geometry
# ---------------------------------------------------------------------------

def _slice_leaf(x: np.ndarray, dim: int, rank: int, world: int) -> np.ndarray:
    """Rank r's ZeRO shard of one host leaf (a copy, so the store never
    aliases live engine buffers)."""
    if dim < 0:
        return np.array(x)
    d = x.shape[dim]
    if d % world:
        raise RedundancyError(
            f"leaf dim {dim} of size {d} does not divide world {world}")
    c = d // world
    idx = [slice(None)] * x.ndim
    idx[dim] = slice(rank * c, (rank + 1) * c)
    return np.array(x[tuple(idx)])


def slice_tree(tree, dims, rank: int, world: int):
    """Per-leaf ZeRO slices owned by `rank` (dims from
    zero.zero_sharded_dims; -1 leaves copy whole — replicated state is
    resident on every rank)."""
    import jax

    return jax.tree.map(
        lambda x, d: _slice_leaf(np.asarray(x), int(d), rank, world),
        tree, dims)


def assemble_tree(payloads: Dict[int, Any], dims):
    """Inverse of slice_tree: full host arrays from a COMPLETE set of
    rank payloads (0..world-1). Replicated leaves take rank 0's copy;
    sharded leaves concatenate in rank order along the sharded dim."""
    import jax

    world = len(payloads)
    if sorted(payloads) != list(range(world)):
        raise RedundancyError(
            f"assemble_tree needs payloads for ranks 0..{world - 1}, "
            f"got {sorted(payloads)}")
    leaves = {r: jax.tree.leaves(payloads[r]) for r in payloads}
    dim_leaves = jax.tree.leaves(dims)
    out = []
    for i, d in enumerate(dim_leaves):
        if int(d) < 0:
            out.append(leaves[0][i])
        else:
            out.append(np.concatenate(
                [leaves[r][i] for r in range(world)], axis=int(d)))
    return jax.tree.unflatten(jax.tree.structure(dims), out)


def split_dims(dims):
    """(zero_dims_by_key, pipe_dims_by_key | None, pipe_world,
    dp_world | None) for both dims formats: the legacy flat
    {'params'/'master'/'opt': dim-tree} ZeRO contract, and the
    pipeline grid format engine_shard_dims emits under a pipe > 1
    mesh ({'zero': ..., 'pipe': ..., 'pipe_world': P, 'dp_world': d})."""
    if isinstance(dims, dict) and "pipe_world" in dims:
        return (dims["zero"], dims["pipe"], int(dims["pipe_world"]),
                int(dims["dp_world"]))
    return dims, None, 1, None


def _zdims_without_pipe_overlap(zdims, pdims):
    """Zero-dim tree with any leaf whose zero dim COINCIDES with its
    pipe dim masked to -1 (cannot happen for pipe-led stage dims —
    zero never lands on a dim whose local extent is 1 — but the guard
    keeps a future layout change safe rather than silently
    double-slicing one dim)."""
    import jax

    return jax.tree.map(
        lambda z, p: -1 if (int(z) >= 0 and int(z) == int(p)) else int(z),
        zdims, pdims)


def assemble_state(payloads: Dict[int, Any], dims) -> Dict[str, Any]:
    """Full host state from a COMPLETE logical-rank payload map, either
    dims format. Legacy (pipe_world == 1): rank r is a ZeRO rank and
    leaves concatenate along their zero dim. Pipeline grid: logical
    rank r = s*dp + d (stage-major) carries stage s's slice of ZeRO
    shard d — zero assembles within each stage row first, then the
    stage rows concatenate along each leaf's pipe dim."""
    zdims, pdims, pipe_world, dp = split_dims(dims)
    if pipe_world <= 1:
        return {k: assemble_tree({r: payloads[r][k] for r in payloads},
                                 zdims[k])
                for k in zdims}
    out = {}
    for k in zdims:
        zmask = _zdims_without_pipe_overlap(zdims[k], pdims[k])
        rows = {}
        for s in range(pipe_world):
            rows[s] = assemble_tree(
                {d: payloads[s * dp + d][k] for d in range(dp)}, zmask)
        out[k] = assemble_tree(rows, pdims[k])
    return out


def stage_payload_bytes(payloads: Dict[int, Any], dims) -> int:
    """Bytes of PIPELINE-STAGE-sliced leaves across one payload map —
    the stage-mirror traffic counter of
    monitor.training_resilience_events (0 under a pipe-less mesh,
    where no leaf carries a stage dim)."""
    import jax

    _zdims, pdims, pipe_world, _dp = split_dims(dims)
    if pipe_world <= 1 or pdims is None:
        return 0
    total = 0
    for payload in payloads.values():
        for k, tree in payload.items():
            leaves = jax.tree.leaves(tree)
            dls = jax.tree.leaves(pdims[k])
            total += sum(int(x.nbytes) for x, d in zip(leaves, dls)
                         if int(d) >= 0)
    return int(total)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class PeerRedundantStore:
    """Per-rank shard snapshots + their neighbor mirrors, all at one
    consistent step. `spare` is the redundancy degree R: each rank's
    payload is mirrored to its next `spare` ranks by `stride`, so any
    loss of <= R ranks (that doesn't wipe a rank AND all its holders)
    reconstructs."""

    def __init__(self, world: int, spare: int = 1, stride: int = 1):
        if world < 1:
            raise RedundancyError(f"world must be >= 1, got {world}")
        if not (0 <= spare < world):
            # spare=0 (forced at world 1: a lone rank has no peer) keeps
            # snapshots local-only — consistent bookkeeping, but any
            # loss is unrecoverable without the disk fallback
            raise RedundancyError(
                f"spare must be in [0, world-1], got {spare} for world "
                f"{world}")
        self.world = int(world)
        self.spare = int(spare)
        self.stride = int(stride)
        self.step: Optional[int] = None
        self.lost: set = set()
        self._local: Dict[int, Any] = {}
        # holder -> {owner: payload}: what each host keeps FOR its peers
        self._mirror: Dict[int, Dict[int, Any]] = {}
        # replicated snapshot metadata (loader state, slice dims), one
        # copy per holder — any survivor can provide it
        self._shared: Dict[int, Any] = {}
        self.mirrors_taken = 0
        self.bytes_mirrored = 0
        self.reconstructions = 0
        self.last_reconstruction_s = 0.0
        # integrity envelope: per-owner blake2b digest of the payload
        # at snapshot time (tiny; conceptually replicated to every
        # holder with the shared metadata, so any survivor can verify)
        self._digests: Dict[int, str] = {}
        self.integrity_failures = 0  # digest mismatches seen at reconstruct

    def holders_of(self, owner: int) -> List[int]:
        return [(owner + i * self.stride) % self.world
                for i in range(1, self.spare + 1)]

    def snapshot(self, step: int, payloads: Dict[int, Any],
                 shared: Any = None) -> None:
        """One consistent mirror round: every rank's slice at `step`,
        plus its copies on the neighbor holders. Atomic by construction
        — the previous round is replaced wholesale, never mixed."""
        import jax

        if sorted(payloads) != list(range(self.world)):
            raise RedundancyError(
                f"snapshot needs payloads for ranks 0..{self.world - 1}, "
                f"got {sorted(payloads)}")
        self._local = dict(payloads)
        # digests BEFORE mirroring: the envelope certifies the payload
        # as read from the live state, so any later DRAM flip in a
        # holder's copy (or the owner's own) is a mismatch
        self._digests = {owner: tree_digest(payload)
                         for owner, payload in payloads.items()}
        self._mirror = {r: {} for r in range(self.world)}
        nbytes = 0
        for owner, payload in payloads.items():
            for holder in self.holders_of(owner):
                mirrored = payload
                # chaos point: one invocation PER mirror entry, so a
                # plan's `where` pins exactly (holder, owner) — an
                # injected flip lands in that holder's copy only (the
                # corrupt_tree copy never aliases the local payload)
                act = fault_point("mirror.payload", step=int(step),
                                  holder=holder, owner=owner)
                if act is not None and act.kind == "corrupt":
                    mirrored, flips = corrupt_tree(
                        payload, act.seed, act.invocation,
                        bit_class="any")
                    log_dist(
                        f"chaos: corrupted mirror copy of rank {owner} "
                        f"held by rank {holder} at step {step} "
                        f"({flips})", ranks=[0])
                self._mirror[holder][owner] = mirrored
                nbytes += int(sum(x.nbytes
                                  for x in jax.tree.leaves(payload)))
        self._shared = {r: shared for r in range(self.world)}
        self.step = int(step)
        self.lost = set()
        self.mirrors_taken += 1
        self.bytes_mirrored += nbytes

    def lose(self, ranks) -> None:
        """A preemption: everything resident on these hosts is gone —
        their own slice AND the mirrors they held for others."""
        for f in ranks:
            self.lost.add(int(f))
            self._local.pop(int(f), None)
            self._mirror[int(f)] = {}
            self._shared.pop(int(f), None)

    def recoverable(self) -> Tuple[bool, List[int]]:
        """(ok, ranks whose slice survives nowhere)."""
        missing = []
        for r in range(self.world):
            if r in self._local:
                continue
            if any(h not in self.lost and r in self._mirror.get(h, {})
                   for h in self.holders_of(r)):
                continue
            missing.append(r)
        return (not missing), missing

    def _sources_of(self, r: int):
        """Surviving (label, payload) candidates for rank r's slice, in
        preference order: the rank's own copy first, then its holders'
        mirrors by stride order."""
        if r in self._local:
            yield f"local[{r}]", self._local[r]
        for h in self.holders_of(r):
            if h not in self.lost and r in self._mirror.get(h, {}):
                yield f"mirror[{h}]", self._mirror[h][r]

    def reconstruct(self, verify: bool = True
                    ) -> Tuple[int, Dict[int, Any], Any]:
        """(step, complete rank->payload map, shared metadata) assembled
        from SURVIVING hosts only — and, with `verify` (the default),
        only from copies whose blake2b digest matches the snapshot-time
        envelope: a bit-flipped copy is skipped (counted in
        `integrity_failures`) and the next holder's mirror is used
        instead, so a silent DRAM corruption can never be resharded
        into live state. Raises UnrecoverableWorldError when no
        (verified) copy of some slice survives."""
        t0 = time.perf_counter()
        if self.step is None:
            ok, missing = self.recoverable()
            if not ok:
                raise UnrecoverableWorldError(missing)
            raise RedundancyError("reconstruct before any snapshot")
        payloads = {}
        missing: List[int] = []
        for r in range(self.world):
            want = self._digests.get(r) if verify else None
            found = None
            for label, payload in self._sources_of(r):
                if want is not None and tree_digest(payload) != want:
                    self.integrity_failures += 1
                    log_dist(
                        f"peer-redundancy: digest mismatch on rank "
                        f"{r}'s copy at {label} (step {self.step}); "
                        "falling over to the next holder", ranks=[0])
                    continue
                found = payload
                break
            if found is None:
                missing.append(r)
            else:
                payloads[r] = found
        if missing:
            raise UnrecoverableWorldError(missing)
        shared = next(iter(self._shared.values())) if self._shared else None
        self.reconstructions += 1
        self.last_reconstruction_s = time.perf_counter() - t0
        return self.step, payloads, shared

    def staleness(self, current_step: int) -> int:
        """Steps of work a recovery right now would replay (the
        redundancy-staleness metric in the monitor feed)."""
        if self.step is None:
            return int(current_step)
        return max(0, int(current_step) - self.step)


# ---------------------------------------------------------------------------
# engine glue: extract shard payloads / lay a full state onto a new mesh
# ---------------------------------------------------------------------------

def engine_shard_dims(engine) -> Dict[str, Any]:
    """Per-leaf sharded dims for a fused-path engine's state trees
    (params / master / opt), the slicing contract for its shards. The
    worker-major 1-bit/0-1-Adam layouts and the host/NVMe offload tiers
    hold state outside the fused TrainState — not covered here.

    Under a pipe-less mesh: the legacy flat ZeRO format
    ({'params'/'master'/'opt': dim-tree}). Under pipeline parallelism
    (mesh pipe > 1): the GRID format — logical rank r = s*dp + d is a
    stage host holding stage s's slice of ZeRO shard d, so each state
    key carries a zero-dim tree AND a pipe-dim tree
    (runtime/zero.axis_sharded_dims: the leading-'pipe' stage dim of
    the [P, L/P, ...] / [v, P, lc, ...] layer stacks) plus the two
    world factors. A preempted stage host then recovers from peers
    exactly like a ZeRO rank: its (stage, shard) slice survives on its
    mirror holders (docs/pipeline.md)."""
    import jax

    from ..runtime import zero

    if getattr(engine, "_offload", False) or getattr(engine, "_onebit", False) \
            or getattr(engine, "_zoadam", False):
        raise NotImplementedError(
            "peer redundancy covers the fused ZeRO step; 1-bit/0-1-Adam "
            "worker layouts and offload tiers keep state outside "
            "TrainState")
    shapes = jax.tree.map(lambda p: tuple(p.shape), engine.state.params)
    leaf_dims = zero.zero_sharded_dims(
        engine.opt_specs, engine.tp_specs, shapes, engine.mesh)
    param_dims = zero.zero_sharded_dims(
        engine.param_specs, engine.tp_specs, shapes, engine.mesh)
    dims: Dict[str, Any] = {"params": param_dims}
    if engine.state.master is not None:
        dims["master"] = leaf_dims
    if engine.state.opt is not None:
        dims["opt"] = {k: leaf_dims for k in engine.state.opt}
    pipe_world = int(engine.mesh.shape.get("pipe", 1))
    if pipe_world <= 1:
        return dims
    pipe_param = zero.axis_sharded_dims(
        engine.param_specs, shapes, engine.mesh, axis="pipe")
    pipe_opt = zero.axis_sharded_dims(
        engine.opt_specs, shapes, engine.mesh, axis="pipe")
    pipe: Dict[str, Any] = {"params": pipe_param}
    if engine.state.master is not None:
        pipe["master"] = pipe_opt
    if engine.state.opt is not None:
        pipe["opt"] = {k: pipe_opt for k in engine.state.opt}
    return {"zero": dims, "pipe": pipe, "pipe_world": pipe_world,
            "dp_world": int(engine.dp_world_size)}


def export_rank_payloads(engine) -> Tuple[Dict[int, Any], Dict[str, Any]]:
    """One host read of the live state, sliced into every logical
    rank's payload: (rank -> {'params': ..., 'master': ..., 'opt': ...},
    dims). The D2H read is the mirror protocol's whole cost — it runs
    between steps, off the compiled path, every K steps."""
    import jax

    dims = engine_shard_dims(engine)
    zdims, pdims, pipe_world, _ = split_dims(dims)
    world = int(engine.dp_world_size)
    host: Dict[str, Any] = {
        "params": jax.device_get(engine.state.params)}
    if "master" in zdims:
        host["master"] = jax.device_get(engine.state.master)
    if "opt" in zdims:
        host["opt"] = jax.device_get(engine.state.opt)
    if pipe_world <= 1:
        payloads = {
            r: {k: slice_tree(host[k], zdims[k], r, world) for k in zdims}
            for r in range(world)
        }
        return payloads, dims
    # pipeline grid: logical rank s*dp + d owns stage s's slice of ZeRO
    # shard d — pipe slice first (the leading stage dim), zero slice
    # within it (the two dims are distinct by construction; the overlap
    # mask guards a future layout change)
    payloads = {}
    for s in range(pipe_world):
        for d in range(world):
            payloads[s * world + d] = {
                k: slice_tree(
                    slice_tree(host[k], pdims[k], s, pipe_world),
                    _zdims_without_pipe_overlap(zdims[k], pdims[k]),
                    d, world)
                for k in zdims}
    return payloads, dims


def reshard_state(engine, full_state: Dict[str, Any],
                  global_steps: int) -> None:
    """Lay a full host state onto `engine`'s (new) mesh — the
    old_mesh -> new_mesh reshard. The target engine's freshly
    initialized TrainState provides the destination shardings (derived
    for ITS world size), so a 4-rank state lands correctly ZeRO-sharded
    on a 2-rank mesh and back. No disk is touched."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    def put(host_leaf, live_leaf):
        return jax.device_put(
            np.asarray(host_leaf).astype(live_leaf.dtype),
            live_leaf.sharding)

    state = engine.state
    new_params = jax.tree.map(put, full_state["params"], state.params)
    new_master = state.master
    if state.master is not None:
        if "master" not in full_state:
            raise RedundancyError(
                "target engine keeps an fp32 master but the snapshot "
                "carries none")
        new_master = jax.tree.map(put, full_state["master"], state.master)
    new_opt = state.opt
    if state.opt is not None and "opt" in full_state:
        new_opt = jax.tree.map(put, full_state["opt"], state.opt)
    step = jax.device_put(
        jnp.asarray(int(global_steps), jnp.int32), state.step.sharding)
    engine.state = dataclasses.replace(
        state, params=new_params, master=new_master, opt=new_opt,
        step=step)
    engine.global_steps = int(global_steps)
