"""On-device token sampling for the serving engine.

TPU-native redesign of the reference's sampling story: FastGen gathers
last-token logits on device (ref: inference/v2/kernels/ragged_ops/
logits_gather/) and MII applies the HF LogitsProcessor chain GPU-side;
the v1 engine inherits HF `generate` sampling (ref:
inference/engine.py:613). Here the whole chain — repetition penalty,
temperature, top-k, top-p, and the categorical draw — runs INSIDE the
compiled decode program, so a decode step returns token ids ([S] int32)
instead of shipping [S, vocab] fp32 logits to the host (8-13 MB/step at
batch 64 — round 3's structural serving-latency tax).

Design notes (XLA-first):
- the categorical draw is GUMBEL-MAX: argmax(logits/T + G),
  G = -log(-log(U)). Exact for categoricals, needs no cumsum/sort, and
  is replayable: the same threefry key on any backend yields the same
  U, so a host oracle given the same logits and key reproduces the
  token bit-exactly (tested in tests/test_sampling.py).
- top-p needs sorted cumulative mass; sorting 32k logits per step is
  VPU-hostile, so the CANDIDATES come from lax.top_k — at width
  top_k when top-k is set (the HF chain order means top-p sees the
  top-k-filtered distribution, so the pool never needs to exceed k),
  else cand_width (default 256) — while their masses come from the
  full softmax (or the k survivors). Exact whenever the nucleus fits
  in the candidate width; the host oracle applies the same
  truncation. The reference's sampler post-processes on full vocab —
  document the difference, don't hide it.
- the DRAW also runs at pool width (round 5): gumbel noise over the
  [S, W] candidates + argmax mapped back through the top_k indices —
  per-step PRNG cost W draws per row, not 32k (the r4 bench's 28%
  sampled-decode tax was threefry over the full vocab every step).
  Pure temperature sampling (no top-k/top-p) keeps the full-vocab
  draw.
- repetition penalty needs the seen-token set; a [S, vocab] presence
  bitmap rides the decode scan and is updated with max(presence,
  one_hot(token)) — no scatter (XLA scatter carries a fixed multi-ms
  cost on TPU, docs/PROFILE_r02.md).
- per-sequence PRNG streams: key_i = fold_in(base, slot_i), step t uses
  fold_in(key_i, t) — batch composition never changes a sequence's
  stream (the host sampler had the same property via per-uid
  np.random.Generator).
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """STATIC sampling knobs (compiled into the decode program; the
    engine caches one program per distinct config). Scalar knobs that
    could be traced (temperature, top_p, penalty) are still static
    here: serving configs change rarely and static values let XLA fold
    the filter chain."""

    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    cand_width: int = 256  # top-p candidate pool (exactness bound)

    @property
    def greedy(self) -> bool:
        return (not self.do_sample) or self.temperature <= 0.0

    @property
    def needs_presence(self) -> bool:
        return self.repetition_penalty != 1.0

    def key(self):
        return dataclasses.astuple(self)


def _penalized(logits, cfg: SamplingConfig, presence: Optional[Any]):
    """Repetition penalty (CTRL rule — divide positive seen logits,
    multiply negative; ref HF RepetitionPenaltyLogitsProcessor, which
    the reference engine inherits) + temperature."""
    logits = logits.astype(jnp.float32)
    if cfg.needs_presence and presence is not None:
        seen = presence.astype(jnp.bool_)
        pen = jnp.float32(cfg.repetition_penalty)
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / pen, logits * pen), logits)
    if not cfg.greedy:
        logits = logits / jnp.float32(max(cfg.temperature, 1e-6))
    return logits


def _pool_width(cfg: SamplingConfig, V: int) -> int:
    """Candidate-pool width: top-k bounds the nucleus when set (TopP
    sees the TOP-K-FILTERED distribution per the HF chain order), so
    the pool never needs to exceed k — pooling at cand_width when k=40
    would pay a 6x-wider lax.top_k for rows that can never win
    (r4 bench: the sampled-decode tax)."""
    k_eff = cfg.top_k if cfg.top_k and 0 < cfg.top_k < V else 0
    if k_eff:
        return min(V, k_eff)
    if 0.0 < cfg.top_p < 1.0:
        return min(V, cfg.cand_width)
    return 0  # pure temperature sampling: full vocab


def _pool_filter(logits, vals, cfg: SamplingConfig):
    """-inf out pool entries (descending [S, W]) cut by top-k/top-p.

    top-k keeps exactly the first k columns (the pool IS the top-k).
    top-p masses come from the top-k-renormalized distribution when
    top-k is set, else from the FULL softmax (pool renormalization
    would inflate every cumulative mass and push the nucleus cutoff
    too deep — r4 review finding). Keeps the smallest prefix reaching
    top_p (always at least the top-1)."""
    if 0.0 < cfg.top_p < 1.0:
        V = logits.shape[-1]
        k_eff = cfg.top_k if cfg.top_k and 0 < cfg.top_k < V else 0
        if k_eff:
            lse = jax.scipy.special.logsumexp(vals, axis=-1, keepdims=True)
        else:
            lse = jax.scipy.special.logsumexp(logits, axis=-1,
                                              keepdims=True)
        probs = jnp.exp(vals - lse)  # true masses, descending order
        csum = jnp.cumsum(probs, axis=-1)
        keep = (csum - probs) < jnp.float32(cfg.top_p)
        vals = jnp.where(keep, vals, -jnp.inf)
    return vals


def apply_penalty_and_filters(logits, cfg: SamplingConfig,
                              presence: Optional[Any] = None):
    """[S, V] f32 logits -> filtered logits (still [S, V]; filtered-out
    entries at -inf). Full-vocab form of the filter chain — kept for
    distribution-level tests; the sampling hot path draws from the
    candidate pool instead (sample_tokens) so the PRNG + argmax run
    over W candidates, not 32k logits."""
    logits = _penalized(logits, cfg, presence)
    if cfg.greedy:
        return logits
    V = logits.shape[-1]
    W = _pool_width(cfg, V)
    if not W:
        return logits
    vals = jax.lax.top_k(logits, W)[0]
    filt = _pool_filter(logits, vals, cfg)
    thr = jnp.min(jnp.where(jnp.isfinite(filt), filt, jnp.inf),
                  axis=-1)[:, None]
    return jnp.where(logits < thr, -jnp.inf, logits)


def sample_tokens(logits, cfg: SamplingConfig, keys=None, step=None,
                  presence: Optional[Any] = None):
    """[S, V] logits -> [S] int32 tokens.

    keys: [S] per-sequence PRNG keys (jax.random key array); step: [S]
    int32 per-sequence draw counters (folded into the key so fused
    multi-step decode advances each stream exactly like stepwise).

    The draw is gumbel-max over the CANDIDATE POOL (top-k/top-p
    survivors, [S, W]): exact for the filtered categorical, and the
    per-step PRNG cost is W draws per row instead of V (the r4 bench's
    28% sampled-decode tax was threefry over [32, 32000] every step)."""
    logits = _penalized(logits, cfg, presence)
    if cfg.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    W = _pool_width(cfg, V)
    if W:
        vals, idx = jax.lax.top_k(logits, W)  # [S, W] descending
        pool = _pool_filter(logits, vals, cfg)
    else:
        pool, idx = logits, None

    def draw(key, t, row):
        u = jax.random.uniform(
            jax.random.fold_in(key, t), row.shape,
            minval=jnp.float32(1e-20), maxval=1.0)
        g = -jnp.log(-jnp.log(u))
        return jnp.argmax(row + g).astype(jnp.int32)

    choice = jax.vmap(draw)(keys, step, pool)
    if idx is None:
        return choice
    return jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0] \
        .astype(jnp.int32)


def update_presence(presence, tokens):
    """presence [S, V] uint8 | tokens [S] -> updated presence (one_hot
    max, not scatter)."""
    oh = jax.nn.one_hot(tokens, presence.shape[-1], dtype=presence.dtype)
    return jnp.maximum(presence, oh)


def presence_from_prompts(prompts, vocab: int, width: int):
    """Host-side initial presence for `width` slots from python/numpy
    token lists (rows beyond len(prompts) stay empty)."""
    import numpy as np

    out = np.zeros((width, vocab), np.uint8)
    for i, p in enumerate(prompts):
        toks = np.asarray(p, np.int64).ravel()
        toks = toks[(toks >= 0) & (toks < vocab)]
        out[i, toks] = 1
    return out


def host_oracle_token(logits, cfg: SamplingConfig, key, t,
                      presence_row=None) -> int:
    """Replay one draw host-side (numpy logits + the same key/step):
    must reproduce sample_tokens bit-exactly — the parity contract the
    tests pin down. Runs the SAME pooled draw as the device path (the
    PRNG stream depends on the pool width, so the oracle must pool
    identically)."""
    import numpy as np

    row = jnp.asarray(np.asarray(logits, np.float32))[None]
    pres = (jnp.asarray(np.asarray(presence_row, np.uint8))[None]
            if presence_row is not None else None)
    if cfg.greedy:
        return int(jnp.argmax(_penalized(row, cfg, pres)[0]))
    keys = jnp.asarray(key)[None]
    steps = jnp.asarray(t, jnp.int32)[None]
    return int(sample_tokens(row, cfg, keys, steps, pres)[0])
