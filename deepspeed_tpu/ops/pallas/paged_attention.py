"""Pallas paged-KV kernels: decode attention + cache write (TPU).

TPU-native redesign of the FastGen ragged hot path
(ref: inference/v2/kernels/ragged_ops/blocked_flash/ paged flash,
linear_blocked_kv_rotary/ fused KV-cache store; the block table is a
scalar-prefetch argument and BlockSpec index maps do the paging — the
idiomatic Mosaic equivalent of the reference's attention-atom
descriptors).

Cache layout: [num_blocks, block_size, KV_heads, head_dim].
One cache block is a CONTIGUOUS (block_size, KV, D) tile — a single
256KB-class DMA fetches every head's slice of a page, so the decode grid
is (seqs, table_slots) with a static head loop inside (measured 8x fewer
grid steps and much higher effective bandwidth than a per-head grid).
The trailing (KV, D) dims satisfy TPU (8,128) tiling; TP shards the KV
dim. "Block i of sequence s" lives at cache[table[s, i]]; pages beyond a
sequence's context are never streamed — the index map clamps the slot to
the last needed block so pruned steps revisit a resident tile (no DMA),
mirroring the causal clamp in flash_attention.py.

int8 per-block KV quantization (docs/paged_attention.md): pools may
hold int8 codes with a per-block [block_size, KV] f32 scale tile
riding the same index maps — dequant fuses into the attention inner
loop and the fused write+attend mode quantizes new rows in-kernel.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _dot, _interpret


def _arena_block(idx, n_blocks: int):
    """THE containment clamp for every block index that reaches a DMA
    or BlockSpec index map: a violated block-table contract (caller
    bug) must produce wrong-but-contained traffic, never a wild DMA —
    an out-of-bounds manual DMA doesn't just crash the program, it can
    wedge the TPU runtime for every later client. Change containment
    policy HERE, nowhere else."""
    return jnp.clip(idx, 0, n_blocks - 1)


# ---------------------------------------------------------------------------
# int8 per-block KV quantization
#
# One scale per (token slot, KV head), stored in per-block scale tiles
# [num_blocks, block_size, KV] riding alongside the int8 code pools —
# "block i's scales" live at k_scale[i], so a block and its scales move
# together through every path that moves pages (COW copies, export/
# import handoffs, spill-to-host). Dequantization is FUSED into the
# attention inner loop (codes stream from HBM at half the bf16 bytes;
# the f32 multiply is VPU work the MXU wait hides), and quantization of
# a decode step's new rows happens inside the fused write+attend kernel.
# ---------------------------------------------------------------------------

KV_QUANT_MAX = 127.0
# the scale is amax * (1/127), spelled as a MULTIPLY in both the XLA
# and the in-kernel quantizer: XLA strength-reduces a divide-by-
# constant to this multiply in some programs but not others, and the
# resulting 1-ULP scale skew would break the codes-are-identical
# contract between the fused and separate write paths
_KV_QUANT_INV = 1.0 / 127.0


def quantize_kv_rows(k, v):
    """Quantize new KV rows [T, KV, D] -> int8 codes + per-(row, head)
    f32 scales ([T, KV]). THE rounding authority: the in-kernel
    quantizer in _decode_kernel uses the same formula, so a token's
    codes are identical whether it entered through prefill's separate
    write, the chunked-continuation write, or the fused write+attend
    kernel — token identity across those paths depends on it."""
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    ks = jnp.max(jnp.abs(kf), axis=-1) * jnp.float32(_KV_QUANT_INV)
    vs = jnp.max(jnp.abs(vf), axis=-1) * jnp.float32(_KV_QUANT_INV)
    ks = jnp.where(ks > 0, ks, jnp.float32(1.0))
    vs = jnp.where(vs > 0, vs, jnp.float32(1.0))
    qk = jnp.clip(jnp.round(kf / ks[..., None]),
                  -KV_QUANT_MAX, KV_QUANT_MAX).astype(jnp.int8)
    qv = jnp.clip(jnp.round(vf / vs[..., None]),
                  -KV_QUANT_MAX, KV_QUANT_MAX).astype(jnp.int8)
    return qk, ks, qv, vs


def _quant_row_kernel(row, compute_dtype):
    """In-kernel quantize of one [KV, D] row (must mirror
    quantize_kv_rows bit for bit); returns (codes int8, scale [KV] f32,
    dequantized row in compute_dtype)."""
    rf = row.astype(jnp.float32)
    sc = jnp.max(jnp.abs(rf), axis=-1) * jnp.float32(_KV_QUANT_INV)
    sc = jnp.where(sc > 0, sc, jnp.float32(1.0))
    q = jnp.clip(jnp.round(rf / sc[:, None]),
                 -KV_QUANT_MAX, KV_QUANT_MAX).astype(jnp.int8)
    deq = (q.astype(jnp.float32) * sc[:, None]).astype(compute_dtype)
    return q, sc, deq


# ---------------------------------------------------------------------------
# decode attention
# ---------------------------------------------------------------------------

def _win_jbase_decode(ctx, window: int, block_size: int):
    """First table slot the sliding window needs (window > 0)."""
    return jnp.maximum(ctx - window, 0) // block_size


def _decode_kernel(
    tbl_ref, ctx_ref, allow_ref, slot_ref,  # scalar prefetch: [S, NB]
    # block table, [S] ctx lens, [S, NB] allowed-slot bitmap (block-
    # sparse; all-ones sentinel when dense), [S] write slots (fused
    # write+attend; all -1 sentinel when not fused)
    q_ref, *rest,
    block_size: int, scale: float, n_kv: int, gp: int, window: int,
    sparse: bool, fused: bool, alibi: bool, quant: bool,
):
    # positional ref layout (mirrors paged_decode_attention's arg
    # order): q, [kn, vn], k, v, [ks, vs], [ab] | o, [ck, cv,
    # [cks, cvs]] | acc, m, l scratch. quant adds the per-block scale
    # tiles next to their code pools on BOTH sides.
    i = 0
    kn_ref = vn_ref = ck_out = cv_out = None
    ks_ref = vs_ref = cks_out = cvs_out = None
    ab_ref = None
    if fused:
        kn_ref, vn_ref = rest[i], rest[i + 1]
        i += 2
    k_ref, v_ref = rest[i], rest[i + 1]
    i += 2
    if quant:
        ks_ref, vs_ref = rest[i], rest[i + 1]
        i += 2
    if alibi:  # [KV, Gp] ALiBi slopes ride as the LAST input
        ab_ref = rest[i]
        i += 1
    o_ref = rest[i]
    i += 1
    if fused:
        ck_out, cv_out = rest[i], rest[i + 1]
        i += 2
        if quant:
            cks_out, cvs_out = rest[i], rest[i + 1]
            i += 2
    acc_sc, m_sc, l_sc = rest[i:i + 3]
    s = pl.program_id(0)
    j = pl.program_id(1)  # table slot (sequential; window-relative)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    ctx = ctx_ref[s]
    last = jnp.maximum(ctx - 1, 0) // block_size
    # fused: the cache holds only positions < ctx-1 (the new token rides
    # in as its own column below) — a block with no OLD live column is
    # skipped entirely, which also keeps the online softmax away from
    # the all-masked NaN corner (ctx==1, or a token opening a new block)
    eff_ctx = ctx - 1 if fused else ctx
    if window > 0:
        # grid walks only the ~window/bs slots inside the window
        j_abs = _win_jbase_decode(ctx, window, block_size) + j
        needed = j_abs * block_size < eff_ctx
    else:
        j_abs = j
        needed = j * block_size < eff_ctx
    if sparse:
        # block-sparse layout row: slots outside the layout are skipped
        # entirely (compute AND their DMA is clamped to a resident tile)
        needed = jnp.logical_and(needed, allow_ref[s, j_abs] != 0)

    @pl.when(needed)
    def _compute():
        k = k_ref[0]  # (bs, KV, D)
        v = v_ref[0]
        if quant:
            # dequant fused into the attention inner loop: int8 codes
            # stream from HBM, the per-(slot, head) scale tile rides in
            # the same BlockSpec index map as its code block
            k = (k.astype(jnp.float32)
                 * ks_ref[0][..., None]).astype(q_ref.dtype)
            v = (v.astype(jnp.float32)
                 * vs_ref[0][..., None]).astype(q_ref.dtype)
        cols = j_abs * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (gp, block_size), 1
        )
        # fused: the new token's row is NOT in the cache yet — mask its
        # position (ctx-1) out here; its contribution enters as a single
        # extra online-softmax column at the final grid step below. This
        # keeps the per-block compute identical to the non-fused kernel
        # (an earlier variant folded the row into the loaded block with
        # a (bs, KV, D) select at EVERY grid step — ~10us/call of VPU
        # time at decode widths).
        live = cols < eff_ctx
        if window > 0:
            live = jnp.logical_and(live, cols >= ctx - window)
        for h in range(n_kv):
            q = q_ref[0, h]  # (Gp, D)
            kh = k[:, h, :]  # (bs, D)
            st = _dot(q, kh, trans_b=True) * scale  # (Gp, bs) f32
            if alibi:
                # bias slope_h * key_pos: exact up to the per-row shift
                # softmax cancels (single query at position ctx-1)
                st = st + ab_ref[h, :][:, None] * cols.astype(jnp.float32)
            st = jnp.where(live, st, NEG_INF)

            row = slice(h * gp, (h + 1) * gp)
            m_prev = m_sc[row]
            m_new = jnp.maximum(m_prev, jnp.max(st, axis=1, keepdims=True))
            p = jnp.exp(st - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_sc[row] = l_sc[row] * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_sc[row] = acc_sc[row] * corr + _dot(p.astype(v.dtype), v[:, h, :])
            m_sc[row] = m_new

    if fused:
        slot = slot_ref[s]
        if quant:
            # quantize the new row ONCE (codes/scales shared by the
            # column update and the store); attention sees the
            # round-tripped value so this step's logits match every
            # later step's read of the same codes
            qkn, skn, kn_use = _quant_row_kernel(kn_ref[0], q_ref.dtype)
            qvn, svn, vn_use = _quant_row_kernel(vn_ref[0], q_ref.dtype)
        else:
            kn_use = kn_ref[0]
            vn_use = vn_ref[0]

        @pl.when(jnp.logical_and(j == nb - 1, slot >= 0))
        def _new_token_column():
            # the new token's score as a 1-column online-softmax update,
            # straight from the VMEM-resident kn/vn rows
            for h in range(n_kv):
                q = q_ref[0, h]  # (Gp, D)
                stn = (jnp.sum(q * kn_use[h][None, :], axis=1,
                               keepdims=True) * scale
                       ).astype(jnp.float32)  # (Gp, 1)
                if alibi:
                    # the new token sits at key position ctx-1
                    stn = stn + (ab_ref[h, :][:, None]
                                 * (ctx - 1).astype(jnp.float32))
                row = slice(h * gp, (h + 1) * gp)
                m_prev = m_sc[row]
                m_new = jnp.maximum(m_prev, stn)
                p = jnp.exp(stn - m_new)
                corr = jnp.exp(m_prev - m_new)
                l_sc[row] = l_sc[row] * corr + p
                acc_sc[row] = (acc_sc[row] * corr
                               + p * vn_use[h][None, :].astype(jnp.float32))
                m_sc[row] = m_new

        @pl.when(j == nb - 1)
        def _store():
            # at the final step the index clamp guarantees the loaded
            # block IS the write target (tbl[s, last]); RMW the new
            # token's row into it once. Pad rows (slot -1) write the
            # loaded block back unchanged — their table points at the
            # reserved scratch block, never a live one.
            kb = k_ref[0]
            vb = v_ref[0]
            rowm = jax.lax.broadcasted_iota(
                jnp.int32, (block_size, 1, 1), 0
            ) == jnp.maximum(slot, 0) % block_size
            wmask = jnp.logical_and(slot >= 0, rowm)
            if quant:
                ck_out[0] = jnp.where(wmask, qkn[None], kb)
                cv_out[0] = jnp.where(wmask, qvn[None], vb)
                # the scale tile RMWs alongside its code block (same
                # target index map, (bs, KV) row mask)
                smask = jnp.logical_and(slot >= 0, rowm[:, :, 0])
                cks_out[0] = jnp.where(smask, skn[None], ks_ref[0])
                cvs_out[0] = jnp.where(smask, svn[None], vs_ref[0])
            else:
                ck_out[0] = jnp.where(wmask, kn_ref[0][None], kb)
                cv_out[0] = jnp.where(wmask, vn_ref[0][None], vb)

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (
            (acc_sc[:] / l_safe)
            .reshape(n_kv, gp, acc_sc.shape[-1])
            .astype(o_ref.dtype)
        )


def paged_decode_attention(q, k_cache, v_cache, block_table, ctx_lens,
                           window: int = 0, allowed_slots=None,
                           k_new=None, v_new=None, slots=None,
                           alibi_slopes=None, k_scale=None, v_scale=None):
    """One-token-per-sequence attention over the paged KV cache.

    q: [S, H, D] (the new token's queries)
    k_cache/v_cache: [num_blocks, block_size, KV, D]
    k_scale/v_scale: optional [num_blocks, block_size, KV] f32 — int8
      per-block KV quantization: the caches hold int8 codes and each
      block carries a (block_size, KV) scale tile; dequant fuses into
      the attention inner loop, and the fused write+attend mode
      quantizes the new rows in-kernel (codes + scales RMW'd back
      through aliased outputs, so fused mode returns
      (out, k_cache, v_cache, k_scale, v_scale)).
    block_table: [S, NB] int32 — cache block ids per sequence
    ctx_lens: [S] int32 — context length INCLUDING the new token; rows
      with 0 are batch padding (output is garbage, sliced by the caller)
    window > 0: token-exact sliding window (Mistral-class serving) — the
      slot grid shrinks to ~window/block_size steps per sequence
    allowed_slots: optional [S, NB] int32/bool — block-sparse serving:
      slot j of sequence s participates only when nonzero (the layout
      row at cache-block granularity; requires the sparse block size to
      be a multiple of the cache block size so each cache block falls in
      ONE layout block). Skipped slots cost no compute and their DMA is
      clamped to a resident tile.
    k_new/v_new [S, KV, D] + slots [S]: FUSED write+attend — the new
      token's KV is folded into its target block in VMEM (attention sees
      it) and the block is RMW'd back to the arena, replacing the
      separate paged_kv_write call (which cost a second kernel launch
      per layer; decode at small batch is launch-bound). Returns
      (out, new_k_cache, new_v_cache) with the caches aliased in place.
      REQUIRES: distinct sequences per row (no chunked-continuation
      rows sharing a table — their writes would race across grid steps)
      and pad rows (ctx 0 / slot -1) pointing at a reserved scratch
      block, since each row's target block is written back even when
      nothing changed. The write slot must be ctx-1's flat slot.
    returns: [S, H, D] (fused: (out, k_cache, v_cache))
    """
    S, H, D = q.shape
    NBLK, bs, KV, _ = k_cache.shape
    NB = block_table.shape[1]
    G = H // KV
    Gp = max(G, 8)  # sublane-pad tiny query blocks
    scale = 1.0 / (D**0.5)
    sparse = allowed_slots is not None
    fused = k_new is not None
    alibi = alibi_slopes is not None
    quant = k_scale is not None
    allow = (allowed_slots.astype(jnp.int32) if sparse
             else jnp.ones((S, NB), jnp.int32))
    slots_arr = (slots.astype(jnp.int32) if fused
                 else jnp.full((S,), -1, jnp.int32))

    qg = q.reshape(S, KV, G, D)
    if Gp != G:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Gp - G), (0, 0)))
    ab = None
    if alibi:
        ab = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, G)
        if Gp != G:
            ab = jnp.pad(ab, ((0, 0), (0, Gp - G)))

    def kv_block_of(s, j, tbl_ref, ctx_ref, allow_ref, slot_ref):
        last = jnp.maximum(ctx_ref[s] - 1, 0) // bs
        if window > 0:
            j = _win_jbase_decode(ctx_ref[s], window, bs) + j
        j = jnp.minimum(j, last)
        if sparse:
            # layout-skipped slots revisit the last block instead of
            # streaming their own — like the causal clamp, repeat visits
            # to a resident tile cost no DMA, so sparse decode saves
            # bandwidth as well as compute
            j = jnp.where(allow_ref[s, j] != 0, j, last)
        # clip to the arena: a violated table contract must stay
        # contained (a wild block index can wedge the TPU runtime)
        return _arena_block(tbl_ref[s, j], NBLK)

    def kv_index(s, j, *refs):
        return (kv_block_of(s, j, *refs), 0, 0, 0)

    def sc_index(s, j, *refs):
        # a block's scale tile rides the SAME paging as its codes
        return (kv_block_of(s, j, *refs), 0, 0)

    def row_index(s, j, tbl_ref, ctx_ref, allow_ref, slot_ref):
        return (s, 0, 0)

    def q_index(s, j, tbl_ref, ctx_ref, allow_ref, slot_ref):
        return (s, 0, 0, 0)

    def tgt_block_of(s, j, tbl_ref, ctx_ref, allow_ref, slot_ref):
        # constant in j: the sequence's NEWEST block — flushed once
        last = jnp.maximum(ctx_ref[s] - 1, 0) // bs
        return _arena_block(tbl_ref[s, last], NBLK)

    def tgt_index(s, j, *refs):
        return (tgt_block_of(s, j, *refs), 0, 0, 0)

    def tgt_sc_index(s, j, *refs):
        return (tgt_block_of(s, j, *refs), 0, 0)

    NBw = min(NB, pl.cdiv(window, bs) + 1) if window > 0 else NB
    kv_spec = pl.BlockSpec((1, bs, KV, D), kv_index)
    sc_spec = pl.BlockSpec((1, bs, KV), sc_index)
    in_specs = [pl.BlockSpec((1, KV, Gp, D), q_index)]
    if fused:
        in_specs += [pl.BlockSpec((1, KV, D), row_index),
                     pl.BlockSpec((1, KV, D), row_index)]
    in_specs += [kv_spec, kv_spec]
    if quant:
        in_specs += [sc_spec, sc_spec]
    if alibi:  # whole [KV, Gp] slope table resident in VMEM
        in_specs.append(pl.BlockSpec(
            (KV, Gp), lambda s, j, tbl_ref, ctx_ref, allow_ref, slot_ref:
            (0, 0)))
    o_spec = pl.BlockSpec((1, KV, Gp, D), q_index)
    o_shape = jax.ShapeDtypeStruct((S, KV, Gp, D), q.dtype)
    if fused:
        tgt_spec = pl.BlockSpec((1, bs, KV, D), tgt_index)
        out_specs = [o_spec, tgt_spec, tgt_spec]
        out_shape = [o_shape,
                     jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                     jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)]
        # args: (4 scalar-prefetch), q, kn, vn, k_cache, v_cache
        # [, k_scale, v_scale] — code pools and scale tiles alias
        # through so the arena updates in place
        aliases = {7: 1, 8: 2}
        if quant:
            tgt_sc_spec = pl.BlockSpec((1, bs, KV), tgt_sc_index)
            out_specs += [tgt_sc_spec, tgt_sc_spec]
            out_shape += [
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype)]
            aliases = {7: 1, 8: 2, 9: 3, 10: 4}
    else:
        out_specs = o_spec
        out_shape = o_shape
        aliases = {}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, NBw),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((KV * Gp, D), jnp.float32),
            pltpu.VMEM((KV * Gp, 1), jnp.float32),
            pltpu.VMEM((KV * Gp, 1), jnp.float32),
        ],
    )
    call = pl.pallas_call(
        functools.partial(
            _decode_kernel, block_size=bs, scale=scale, n_kv=KV, gp=Gp,
            window=window, sparse=sparse, fused=fused, alibi=alibi,
            quant=quant,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=_interpret(),
    )
    sc = (k_scale, v_scale) if quant else ()
    tail = (ab,) if alibi else ()
    if fused:
        res = call(block_table, ctx_lens, allow, slots_arr, qg,
                   k_new, v_new, k_cache, v_cache, *sc, *tail)
        if quant:
            out, ck, cv, cks, cvs = res
            return out[:, :, :G, :].reshape(S, H, D), ck, cv, cks, cvs
        out, ck, cv = res
        return out[:, :, :G, :].reshape(S, H, D), ck, cv
    out = call(block_table, ctx_lens, allow, slots_arr, qg, k_cache, v_cache,
               *sc, *tail)
    return out[:, :, :G, :].reshape(S, H, D)


def paged_decode_attention_xla(q, k_cache, v_cache, block_table, ctx_lens,
                               allowed=None, window: int = 0,
                               alibi_slopes=None, k_scale=None,
                               v_scale=None):
    """jnp oracle for the kernel (tests; also a CPU fallback, and the
    block-sparse serving path via `allowed`).

    Gathers each sequence's paged KV into a dense [S, NB*bs, KV, D]
    context — O(S·max_ctx) memory, fine at test scale. THIS is the
    per-step block-table gather materialization the fused kernel
    exists to avoid; it stays as the reference/oracle path only.

    allowed: optional [S, NB*bs] bool — extra per-position mask (the
    block-sparse layout row of each query's position).
    window > 0: token-exact sliding window per row.
    alibi_slopes: optional [H] — score bias slope_h * key_pos (the
    single query row makes the absolute form exact under softmax).
    k_scale/v_scale: int8-KV mode — per-block scale tiles
    [NBLK, bs, KV]; codes gather with their scales and dequantize to
    the compute dtype exactly as the kernel's fused dequant does."""
    S, H, D = q.shape
    _, bs, KV, _ = k_cache.shape
    G = H // KV
    k = k_cache[block_table].reshape(S, -1, KV, D)  # [S, NB*bs, KV, D]
    v = v_cache[block_table].reshape(S, -1, KV, D)
    if k_scale is not None:
        ks = k_scale[block_table].reshape(S, -1, KV)
        vs = v_scale[block_table].reshape(S, -1, KV)
        k = (k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    if G > 1:
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)
    logits = jnp.einsum("shd,skhd->shk", q, k).astype(jnp.float32)
    logits = logits / (D**0.5)
    pos = jnp.arange(k.shape[1])
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        logits = logits + (slopes[None, :, None]
                           * pos.astype(jnp.float32)[None, None, :])
    mask = pos[None, :] < ctx_lens[:, None]  # [S, NB*bs]
    if window > 0:
        mask = mask & (pos[None, :] >= ctx_lens[:, None] - window)
    if allowed is not None:
        mask = mask & allowed
    logits = jnp.where(mask[:, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("shk,skhd->shd", probs, v)


# ---------------------------------------------------------------------------
# fused decode v2: per-sequence grid, manual-DMA block loop
# ---------------------------------------------------------------------------

def _decode_fused_kernel(
    tbl_ref, ctx_ref, slot_ref, allow_ref,          # scalar prefetch
    q_ref, kn_ref, vn_ref, k_any, v_any,            # inputs (caches in HBM)
    *rest,                                          # [ab], outs, scratch
    n_seqs: int, block_size: int, scale: float, n_kv: int, gp: int,
    window: int, sparse: bool, alibi: bool,
):
    if alibi:  # [KV, Gp] ALiBi slope table rides as the LAST input
        ab_ref, o_ref, ck_any, cv_any, bufk, bufv, wsem, lsem = rest
    else:
        o_ref, ck_any, cv_any, bufk, bufv, wsem, lsem = rest
        ab_ref = None
    """One grid step per SEQUENCE (compile size O(1) in batch — an
    earlier all-sequences-unrolled variant ran ~8us/call faster at S=8
    but its Mosaic compile exploded at S=64). The KV arenas stay in HBM
    (memory_space=ANY); a fori_loop walks ONLY the live blocks of this
    sequence's table, double-buffering block DMAs. Dead table slots cost
    nothing, the new token's row is DMA'd straight into its cache slot
    (2 KB, vs RMW-ing whole 256 KB blocks through the output pipeline),
    and its attention contribution enters as one extra online-softmax
    column from VMEM. Scratch persists across grid steps, so each step
    prefetches the NEXT sequence's first block (buffer sets alternate by
    sequence parity) — the common short-context case never stalls.

    sparse: block-sparse layouts ride in as the allow_ref bitmap — a
    disallowed slot's load is never ISSUED (its iteration neither waits
    nor computes; block j+1's load is issued by iteration j regardless
    of j's own allow bit, so pipelining is preserved across gaps). The
    (S, NB)-grid kernel could only clamp a pruned slot's DMA to a
    resident tile; here pruned slots are genuinely free."""
    bs = block_size
    D = q_ref.shape[-1]
    s = pl.program_id(0)

    def jbase_of(ctx):
        return (jnp.maximum(ctx - window, 0) // bs) if window > 0 else 0

    def nblk_of(ctx):
        return pl.cdiv(jnp.maximum(ctx - 1, 0), bs)

    def allowed(sq, j):
        if not sparse:
            return True
        return allow_ref[sq, j] != 0

    # every HBM index is CLAMPED to the arena: a violated block-table
    # contract (caller bug) must produce wrong-but-contained results,
    # never a wild DMA — an out-of-bounds manual DMA doesn't just crash
    # the program, it can wedge the TPU runtime for every later client
    n_blk = k_any.shape[0]

    def load(sq, bufset, j, buf_slot):
        blk = _arena_block(tbl_ref[sq, j], n_blk)
        pltpu.make_async_copy(k_any.at[blk], bufk.at[bufset, buf_slot],
                              lsem.at[bufset, buf_slot, 0]).start()
        pltpu.make_async_copy(v_any.at[blk], bufv.at[bufset, buf_slot],
                              lsem.at[bufset, buf_slot, 1]).start()

    def prefetch_first(sq):
        ctx = ctx_ref[sq]
        jb = jbase_of(ctx)

        @pl.when(jnp.logical_and(jb < nblk_of(ctx), allowed(sq, jb)))
        def _():
            load(sq, sq % 2, jb, jb % 2)

    @pl.when(s == 0)
    def _prefetch_self():
        prefetch_first(0)

    @pl.when(s + 1 < n_seqs)
    def _prefetch_next_seq():
        prefetch_first(s + 1)

    ctx = ctx_ref[s]
    slot = slot_ref[s]
    L = jnp.maximum(ctx - 1, 0)      # old tokens in the cache
    bufset = s % 2

    def body(j, carry):
        ms, ls, accs = carry  # per-head tuples: (Gp,1),(Gp,1),(Gp,D)
        bslot = j % 2

        @pl.when(jnp.logical_and(j + 1 < nblk_of(ctx), allowed(s, j + 1)))
        def _prefetch_next():
            load(s, bufset, j + 1, (j + 1) % 2)

        ok = allowed(s, j)
        cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (gp, bs), 1)
        live = cols < L
        if window > 0:
            live = jnp.logical_and(live, cols >= ctx - window)
        if sparse:
            # a disallowed block has no in-flight DMA: don't wait, and
            # mask every column so the accumulators pass through
            live = jnp.logical_and(live, ok)

            @pl.when(ok)
            def _wait_allowed():
                pltpu.make_async_copy(k_any.at[0], bufk.at[bufset, bslot],
                                      lsem.at[bufset, bslot, 0]).wait()
                pltpu.make_async_copy(v_any.at[0], bufv.at[bufset, bslot],
                                      lsem.at[bufset, bslot, 1]).wait()
        else:
            pltpu.make_async_copy(k_any.at[0], bufk.at[bufset, bslot],
                                  lsem.at[bufset, bslot, 0]).wait()
            pltpu.make_async_copy(v_any.at[0], bufv.at[bufset, bslot],
                                  lsem.at[bufset, bslot, 1]).wait()
        kb = bufk[bufset, bslot]  # (bs, KV, D)
        vb = bufv[bufset, bslot]
        ms2, ls2, accs2 = [], [], []
        for h in range(n_kv):
            q = q_ref[s, h]  # (Gp, D)
            st = _dot(q, kb[:, h, :], trans_b=True) * scale  # (Gp, bs)
            if alibi:
                st = st + ab_ref[h, :][:, None] * cols.astype(jnp.float32)
            st = jnp.where(live, st, NEG_INF)
            m_new = jnp.maximum(ms[h], jnp.max(st, axis=1, keepdims=True))
            p = jnp.exp(st - m_new)
            corr = jnp.exp(ms[h] - m_new)
            l_new = ls[h] * corr + jnp.sum(p, axis=1, keepdims=True)
            a_new = accs[h] * corr + _dot(p.astype(vb.dtype), vb[:, h, :])
            if sparse:
                # disallowed block: carry passes through untouched (the
                # stale buffer's garbage and the all--inf exp NaNs are in
                # the UNSELECTED where branch — never propagated)
                m_new = jnp.where(ok, m_new, ms[h])
                l_new = jnp.where(ok, l_new, ls[h])
                a_new = jnp.where(ok, a_new, accs[h])
            ls2.append(l_new)
            accs2.append(a_new)
            ms2.append(m_new)
        return tuple(ms2), tuple(ls2), tuple(accs2)

    init = (
        tuple(jnp.full((gp, 1), NEG_INF, jnp.float32)
              for _ in range(n_kv)),
        tuple(jnp.zeros((gp, 1), jnp.float32) for _ in range(n_kv)),
        tuple(jnp.zeros((gp, D), jnp.float32) for _ in range(n_kv)),
    )
    ms, ls, accs = jax.lax.fori_loop(jbase_of(ctx), nblk_of(ctx),
                                     body, init)

    if alibi:
        # fold the new token's ALiBi bias into its online-softmax column
        ab_newcol = [ab_ref[h, :][:, None] * (ctx - 1).astype(jnp.float32)
                     for h in range(n_kv)]

    # this sequence's new row -> its cache slot, started only AFTER its
    # own block loads are consumed: the write may tear bf16 values
    # mid-DMA, and although the row's column is masked out of the
    # softmax, 0 * NaN from a torn load would still poison the
    # accumulator. Other sequences' loads never touch this block (rows
    # are distinct sequences). Waited at the final grid step.
    @pl.when(slot >= 0)
    def _write_row():
        blk = _arena_block(slot // bs, n_blk)
        off = slot % bs
        pltpu.make_async_copy(kn_ref.at[s], ck_any.at[blk, off],
                              wsem.at[s, 0]).start()
        pltpu.make_async_copy(vn_ref.at[s], cv_any.at[blk, off],
                              wsem.at[s, 1]).start()

    # the new token's own column (kn/vn are VMEM-resident inputs)
    def newcol(carry):
        ms, ls, accs = carry
        ms2, ls2, accs2 = [], [], []
        for h in range(n_kv):
            q = q_ref[s, h]
            stn = (jnp.sum(q * kn_ref[s, h][None, :], axis=1,
                           keepdims=True) * scale).astype(jnp.float32)
            if alibi:
                stn = stn + ab_newcol[h]
            m_new = jnp.maximum(ms[h], stn)
            p = jnp.exp(stn - m_new)
            corr = jnp.exp(ms[h] - m_new)
            ls2.append(ls[h] * corr + p)
            accs2.append(accs[h] * corr
                         + p * vn_ref[s, h][None, :].astype(jnp.float32))
            ms2.append(m_new)
        return tuple(ms2), tuple(ls2), tuple(accs2)

    ms, ls, accs = jax.lax.cond(slot >= 0, newcol, lambda c: c,
                                (ms, ls, accs))

    for h in range(n_kv):
        l_safe = jnp.where(ls[h] == 0.0, 1.0, ls[h])
        o_ref[s, h] = (accs[h] / l_safe).astype(o_ref.dtype)

    @pl.when(s == n_seqs - 1)
    def _wait_rows():
        for sq in range(n_seqs):
            @pl.when(slot_ref[sq] >= 0)
            def _w(sq=sq):
                blk = _arena_block(slot_ref[sq] // bs, n_blk)
                off = slot_ref[sq] % bs
                pltpu.make_async_copy(kn_ref.at[sq], ck_any.at[blk, off],
                                      wsem.at[sq, 0]).wait()
                pltpu.make_async_copy(vn_ref.at[sq], cv_any.at[blk, off],
                                      wsem.at[sq, 1]).wait()


def supports_fused_v2(head_dim: int) -> bool:
    """The per-sequence-grid kernel's row-write DMA needs lane-aligned
    (KV, D) slices."""
    return head_dim % 128 == 0


def paged_decode_fused(q, k_cache, v_cache, block_table, ctx_lens,
                       k_new, v_new, slots, window: int = 0,
                       allowed_slots=None, alibi_slopes=None):
    """Fused single-token decode: write the batch's new KV rows into the
    paged arenas AND attend over them, one kernel launch. The serving
    engine's hot path for dense AND (via allowed_slots) block-sparse
    layouts; only D % 128 != 0 models fall back to _decode_kernel's
    bitmap grid.

    Same contract as paged_decode_attention's fused mode: rows are
    DISTINCT sequences; ctx INCLUDES the new token; slots [S] are the
    new tokens' flat cache slots (-1 = pad row, nothing written).
    Returns (out [S, H, D], k_cache, v_cache) with the arenas updated in
    place (donate them).

    allowed_slots: optional [S, NB] block-sparse bitmap — disallowed
    slots are never DMA'd at all (the (S, NB)-grid kernel could only
    clamp them to a resident tile).

    Requires head_dim % 128 == 0: the per-row (KV, D) write DMA must be
    lane-aligned (D=64 models route to paged_decode_attention's fused
    mode instead — see supports_fused_v2)."""
    S, H, D = q.shape
    NBLK, bs, KV, _ = k_cache.shape
    NB = block_table.shape[1]
    G = H // KV
    Gp = max(G, 8)
    scale = 1.0 / (D**0.5)
    sparse = allowed_slots is not None
    alibi = alibi_slopes is not None
    allow = (allowed_slots.astype(jnp.int32) if sparse
             else jnp.zeros((S, NB), jnp.int32))

    qg = q.reshape(S, KV, G, D)
    if Gp != G:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Gp - G), (0, 0)))
    ab = ()
    if alibi:
        ab_arr = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, G)
        if Gp != G:
            ab_arr = jnp.pad(ab_arr, ((0, 0), (0, Gp - G)))
        ab = (ab_arr,)

    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S,),
        in_specs=[
            vmem(), vmem(), vmem(),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ] + ([vmem()] if alibi else []),
        out_specs=[
            vmem(),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 2, bs, KV, D), k_cache.dtype),
            pltpu.VMEM((2, 2, bs, KV, D), v_cache.dtype),
            pltpu.SemaphoreType.DMA((S, 2)),
            pltpu.SemaphoreType.DMA((2, 2, 2)),
        ],
    )
    out, ck, cv = pl.pallas_call(
        functools.partial(
            _decode_fused_kernel, n_seqs=S, block_size=bs, scale=scale,
            n_kv=KV, gp=Gp, window=window, sparse=sparse, alibi=alibi,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((S, KV, Gp, D), q.dtype),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        # args: 4 scalar prefetch, q, kn, vn, k_cache, v_cache [, ab]
        input_output_aliases={7: 1, 8: 2},
        interpret=_interpret(),
    )(block_table, ctx_lens, slots.astype(jnp.int32), allow, qg,
      k_new, v_new, k_cache, v_cache, *ab)
    return out[:, :, :G, :].reshape(S, H, D), ck, cv


# ---------------------------------------------------------------------------
# paged KV write
# ---------------------------------------------------------------------------

def _kv_write_kernel(
    slots_ref, kn_ref, vn_ref, ck_in, cv_in, ck_out, cv_out,
    *, block_size: int, n_blocks: int,
):
    """Read-modify-write one token row into its cache block.

    XLA's scatter lowering costs ~3ms per call on TPU regardless of size
    (measured, docs/PROFILE_r02.md); at 2 scatters x n_layers per decode
    step that dominated the engine. This kernel instead RMWs whole cache
    blocks through VMEM: tokens are pre-sorted by slot so consecutive
    grid steps hitting the same block keep it resident, and the block is
    copied from the aliased input only on first visit (a later copy
    would erase rows written by earlier same-block steps)."""
    t = pl.program_id(0)
    slot = slots_ref[t]

    def cb(i):  # clamped block id of token i (same clip as cache_index)
        return _arena_block(slots_ref[i] // block_size, n_blocks)

    first = jnp.logical_or(t == 0, cb(t) != cb(jnp.maximum(t - 1, 0)))

    @pl.when(first)
    def _copy():
        ck_out[...] = ck_in[...]
        cv_out[...] = cv_in[...]

    @pl.when(slot >= 0)
    def _write():
        # Mosaic cannot vector-store at a dynamic sublane offset, so the
        # row write is a masked full-block select (VPU, block in VMEM)
        off = slot % block_size
        row = jax.lax.broadcasted_iota(jnp.int32, (1, block_size, 1, 1), 1)
        mask = row == off
        kn = kn_ref[0][None, None]  # (1, 1, KV, D)
        vn = vn_ref[0][None, None]
        ck_out[...] = jnp.where(mask, kn, ck_out[...])
        cv_out[...] = jnp.where(mask, vn, cv_out[...])


def paged_kv_write(cache_k, cache_v, k_new, v_new, flat_slots):
    """Write [T, KV, D] new KV rows into [NBLK, bs, KV, D] caches at flat
    slot ids [T] (block*bs + offset; -1 rows are dropped). The TPU-native
    fused-cache-store (ref: inference/v2/kernels/ragged_ops/
    linear_blocked_kv_rotary/ — rotary is applied upstream in XLA)."""
    NBLK, bs, KV, D = cache_k.shape
    T = flat_slots.shape[0]
    order = jnp.argsort(flat_slots)
    slots = flat_slots[order].astype(jnp.int32)
    kn = k_new[order]
    vn = v_new[order]

    def cache_index(t, slots_ref):
        # clip both ends: negatives are pad rows, and an over-range slot
        # (caller contract bug) must stay inside the arena
        return (_arena_block(slots_ref[t] // bs, NBLK), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, KV, D), lambda t, slots_ref: (t, 0, 0)),
            pl.BlockSpec((1, KV, D), lambda t, slots_ref: (t, 0, 0)),
            pl.BlockSpec((1, bs, KV, D), cache_index),
            pl.BlockSpec((1, bs, KV, D), cache_index),
        ],
        out_specs=[
            pl.BlockSpec((1, bs, KV, D), cache_index),
            pl.BlockSpec((1, bs, KV, D), cache_index),
        ],
        scratch_shapes=[],
    )
    return pl.pallas_call(
        functools.partial(_kv_write_kernel, block_size=bs, n_blocks=NBLK),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
        ],
        # alias caches through: in-place RMW, no copy of the arena
        input_output_aliases={3: 0, 4: 1},
        interpret=_interpret(),
    )(slots, kn, vn, cache_k, cache_v)


def paged_scale_write(k_scale, v_scale, ks_new, vs_new, flat_slots):
    """Write [T, KV] per-row quant scales into the [NBLK, bs, KV] scale
    pools at flat slot ids [T] — the scale half of a quantized
    paged_kv_write. Rides the SAME RMW kernel through a
    [NBLK, bs, 1, KV] view (the KV axis lands on the lane dim, so the
    block tile stays lane-aligned and dtype-generic)."""
    NBLK, bs, KV = k_scale.shape
    ck, cv = paged_kv_write(
        k_scale.reshape(NBLK, bs, 1, KV), v_scale.reshape(NBLK, bs, 1, KV),
        ks_new[:, None, :], vs_new[:, None, :], flat_slots)
    return ck.reshape(NBLK, bs, KV), cv.reshape(NBLK, bs, KV)
