"""Collective-traffic accounting from compiled HLO.

The comms-logging redesign (ref: deepspeed/utils/comms_logging.py
CommsLogger:67 + comm/comm.py timed_op:101). The reference wraps every
eager collective call in a timing decorator; on TPU the engine issues NO
collectives from Python — XLA's SPMD partitioner inserts them — so the
per-op volume story must come from the compiled program itself. This
module parses the post-partitioning HLO of a compiled step and returns
exact per-collective byte counts: ground truth, not invocation-side
bookkeeping (fixes VERDICT r1 W6: the facade logger observed nothing).
"""

import re
from collections import defaultdict
from typing import Dict, List

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_COLLECTIVES = (
    "all-gather-start", "all-gather",
    "all-reduce-start", "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-permute-start", "collective-permute",
    "collective-broadcast",
)

# e.g. "  %x = bf16[4,128]{1,0} all-gather(...)" or tuple results
_INSTR_RE = re.compile(
    r"=\s*(?P<result>\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")\("
)
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z0-9]+)\[(?P<dims>[0-9,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def parse_hlo_collectives(hlo_text: str) -> List[Dict]:
    """Every collective instruction in the HLO with its payload bytes.

    Async `-start` ops return a tuple carrying the input operand alongside
    the output (e.g. `(bf16[4,128], bf16[16,128]) all-gather-start`); the
    payload is the OUTPUT — the largest member — so tuples from -start
    forms take max, plain (possibly multi-result all-to-all) forms sum."""
    out = []
    for m in _INSTR_RE.finditer(hlo_text):
        is_start = m.group("op").endswith("-start")
        op = m.group("op").replace("-start", "")
        result = m.group("result")
        sizes = [
            _shape_bytes(s.group("dtype"), s.group("dims"))
            for s in _SHAPE_RE.finditer(result)
        ]
        if not sizes:
            continue
        nbytes = max(sizes) if is_start else sum(sizes)
        dtypes = sorted({s.group("dtype") for s in _SHAPE_RE.finditer(result)})
        out.append({"op": op, "bytes": nbytes, "dtypes": dtypes})
    return out


def collective_volumes(compiled) -> Dict[str, Dict[str, float]]:
    """Per-collective-kind totals for one compiled step.

    Returns {op: {count, bytes}} — e.g. how many bytes of all-gather one
    train step moves (the reference's comms summary table, per op kind,
    ref: comms_logging.py log_summary)."""
    text = compiled.as_text()
    agg: Dict[str, Dict[str, float]] = defaultdict(lambda: {"count": 0, "bytes": 0})
    for rec in parse_hlo_collectives(text):
        agg[rec["op"]]["count"] += 1
        agg[rec["op"]]["bytes"] += rec["bytes"]
    return dict(agg)
