"""Checkpoint save/load of sharded state.

TPU-native analog of the reference checkpoint layer
(ref: runtime/checkpoint_engine/checkpoint_engine.py CheckpointEngine
ABC, engine.py save_checkpoint:3064 / load_checkpoint:2700, and the
Nebula async engine). Backed by orbax: every process writes only its
addressable shards, restore re-shards to whatever mesh the new run uses
— which is why the reference's "universal checkpoint" reshape tooling
(deepspeed/checkpoint/ds_to_universal.py) is mostly free here: saved
arrays are logical/global, not per-rank shards.

Layout mirrors the reference's tag scheme:
  <save_dir>/<tag>/state/...   (orbax tree)
  <save_dir>/<tag>/meta.json
  <save_dir>/latest            (text file holding the newest tag)
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist


class CheckpointEngine:
    def __init__(self, async_save: bool = False):
        self.async_save = async_save
        self._ckptr = None
        self._pending = None
        if async_save:
            # the final save of a run must still commit + publish 'latest'
            # even if the script never saves again (ref: nebula engine's
            # implicit finalization on teardown)
            import atexit

            atexit.register(self.wait)

    def _checkpointer(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            if self.async_save:
                self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            else:
                self._ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        return self._ckptr

    def save(self, save_dir: str, tag: str, state: Any, meta: Dict) -> None:
        save_dir = os.path.abspath(save_dir)
        path = os.path.join(save_dir, tag, "state")
        os.makedirs(os.path.join(save_dir, tag), exist_ok=True)
        self.wait()  # one in-flight async save at a time (ref: nebula engine semantics)
        ckptr = self._checkpointer()
        ckptr.save(path, state, force=True)
        if jax.process_index() == 0:
            with open(os.path.join(save_dir, tag, "meta.json"), "w") as f:
                json.dump(meta, f)
        if self.async_save:
            # 'latest' must only point at committed data: defer the pointer
            # update until the background commit finishes (wait()).
            self._pending = (ckptr, save_dir, tag)
        else:
            self._write_latest(save_dir, tag)
        log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])

    @staticmethod
    def _write_latest(save_dir: str, tag: str) -> None:
        if jax.process_index() == 0:
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(tag)

    def wait(self) -> None:
        if self._pending is not None:
            ckptr, save_dir, tag = self._pending
            ckptr.wait_until_finished()
            self._write_latest(save_dir, tag)
            self._pending = None

    def resolve_tag(self, load_dir: str, tag: Optional[str]) -> str:
        load_dir = os.path.abspath(load_dir)
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                raise FileNotFoundError(f"no 'latest' file in {load_dir}")
            with open(latest) as f:
                tag = f.read().strip()
        return tag

    def peek_meta(self, load_dir: str, tag: Optional[str]) -> Dict:
        """Read meta.json without touching tensor data (used to reconcile
        structure differences before restore)."""
        self.wait()  # an in-flight async save must commit before any read
        load_dir = os.path.abspath(load_dir)
        tag = self.resolve_tag(load_dir, tag)
        meta_path = os.path.join(load_dir, tag, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}

    def load(
        self, load_dir: str, tag: Optional[str], template_state: Any
    ) -> Tuple[Any, Dict, str]:
        import orbax.checkpoint as ocp

        self.wait()
        load_dir = os.path.abspath(load_dir)
        tag = self.resolve_tag(load_dir, tag)
        path = os.path.join(load_dir, tag, "state")
        restore_args = ocp.checkpoint_utils.construct_restore_args(template_state)
        state = self._checkpointer().restore(
            path, args=ocp.args.PyTreeRestore(
                item=template_state,
                restore_args=restore_args,
            ),
        )
        meta_path = os.path.join(load_dir, tag, "meta.json")
        meta: Dict = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
        return state, meta, tag
