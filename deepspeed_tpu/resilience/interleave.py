"""Deterministic interleaving harness: seeded cooperative scheduling
for the serving control plane's real threads.

The concurrency analyzer (analysis/concurrency.py) proves lockset
properties statically; this module is its dynamic twin. It replays
PERMUTED thread schedules of real code — the scheduler step vs the
router pump vs the autoscaler tick vs offload-store I/O threads — under
a seeded scheduler, so a race that depends on a particular interleaving
is reproduced on demand instead of once a month in production. CHESS
(Musuvathi et al.) is the lineage: enumerate/sample schedules at
synchronization points, one task runnable at a time, and the schedule
is a pure function of the seed.

Design
  - Tasks are REAL `threading.Thread`s, but all of them are gated by a
    single `threading.Condition`: exactly one task holds the baton at
    any moment, so every shared-memory access is sequentially
    consistent and the interleaving is exactly the recorded trace.
  - A task hands the baton back at `yield_point(op)` calls. Instrumented
    locks call `yield_point` on every acquire/release, so lock-ordering
    bugs surface without hand-sprinkled yields; code under test can add
    explicit `sched.yield_point("tag")` choke points for finer slicing.
  - The next runnable task is `random.Random(seed).choice(sorted(...))`
    — same seed, same schedule, byte-identical trace, every run.
  - `InstrumentedLock` tracks owner + waiters. When every live task is
    blocked on a lock, the harness raises `DeadlockError` carrying the
    full held/waiting map — the dynamic confirmation of a C002 cycle.
    Acquiring a non-reentrant instrumented lock twice from the same
    task raises immediately (a real `threading.Lock` would self-
    deadlock silently).
  - `instrument(obj, attrs)` swaps named `threading.Lock` attributes on
    a live object for instrumented ones, so production classes run
    unmodified under the harness.
  - `trace_digest()` is a blake2b over the `task:op` lines — the
    ds_race gate pins these digests per (lane, seed) in
    CONCURRENCY.json, so a schedule change is a reviewed diff.

Usage
    sched = CooperativeScheduler(seed=7)
    sched.instrument(store, ["_lock"])
    sched.spawn("writer", lambda: store.put(k, v))
    sched.spawn("reader", lambda: store.get(k))
    sched.run()                      # raises the first task exception
    sched.trace_digest()             # stable for a given seed

See docs/concurrency.md for the lane catalog the gate replays.
"""

import hashlib
import random
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CooperativeScheduler",
    "DeadlockError",
    "InstrumentedLock",
    "ScheduleError",
]


class ScheduleError(RuntimeError):
    """Harness misuse or runaway schedule (max_switches exceeded)."""


class _Aborted(BaseException):
    """Internal: unparks victim tasks after a fatal schedule error so
    their threads exit instead of hanging to the join timeout. Never
    surfaces from run() when a real cause exists."""


class DeadlockError(RuntimeError):
    """Every live task is blocked on an instrumented lock.

    `held` maps task -> locks it owns; `waiting` maps task -> the lock
    it is blocked on. Together they spell out the cycle (the dynamic
    face of a C002 finding)."""

    def __init__(self, held: Dict[str, List[str]],
                 waiting: Dict[str, str]) -> None:
        self.held = held
        self.waiting = waiting
        parts = [
            f"{t} holds {sorted(held.get(t, []))} wants {waiting[t]}"
            for t in sorted(waiting)
        ]
        super().__init__("deadlock: all live tasks blocked — "
                         + "; ".join(parts))


# task lifecycle states
_READY = "ready"       # runnable, waiting for the baton
_RUNNING = "running"   # holds the baton
_BLOCKED = "blocked"   # parked on an instrumented lock
_DONE = "done"


class _Task:
    def __init__(self, name: str, fn: Callable[[], None]) -> None:
        self.name = name
        self.fn = fn
        self.state = _READY
        self.thread: Optional[threading.Thread] = None
        self.exc: Optional[BaseException] = None
        self.waiting_on: Optional[str] = None
        self.held: List[str] = []


class InstrumentedLock:
    """A lock whose acquire/release are scheduler yield points.

    Context-manager compatible with `threading.Lock`/`RLock`, so it can
    be swapped onto a live object via `CooperativeScheduler.instrument`.
    No real lock is needed underneath: the scheduler's baton already
    serializes all tasks, so this object only has to model BLOCKING —
    who owns it, who waits, and when a waiter may proceed."""

    def __init__(self, sched: "CooperativeScheduler", name: str,
                 reentrant: bool = False) -> None:
        self._sched = sched
        self.name = name
        self.reentrant = reentrant
        self.owner: Optional[str] = None
        self._depth = 0

    # -- threading.Lock surface -------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._sched._lock_acquire(self, blocking)

    def release(self) -> None:
        self._sched._lock_release(self)

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CooperativeScheduler:
    """Seeded cooperative scheduler over real threads.

    `spawn` registers tasks; `run` starts them and drives the baton
    until every task finishes, re-raising the first task exception
    (after letting remaining tasks run to completion where possible).
    The schedule is a pure function of `seed` and the tasks' yield
    structure: identical seeds produce byte-identical traces."""

    def __init__(self, seed: int = 0, max_switches: int = 100_000) -> None:
        self.seed = seed
        self.max_switches = max_switches
        self._rng = random.Random(seed)
        self._cond = threading.Condition()
        self._tasks: Dict[str, _Task] = {}
        self._order: List[str] = []
        self._current: Optional[str] = None
        self._started = False
        self.trace: List[Tuple[str, str]] = []
        self._switches = 0
        self._abort = False
        self._local = threading.local()

    # ------------------------------------------------------------------
    # task registration / instrumentation
    # ------------------------------------------------------------------
    def spawn(self, name: str, fn: Callable[..., None], *args,
              **kwargs) -> None:
        if self._started:
            raise ScheduleError("spawn() after run()")
        if name in self._tasks:
            raise ScheduleError(f"duplicate task name {name!r}")
        # spawn() precedes run() (the _started guard above), and
        # Thread.start() publishes _tasks with a happens-before edge
        # the lockset model cannot see (Eraser's init-state gap):
        # ds-lint: ok C001 init-before-share, published by Thread.start
        self._tasks[name] = _Task(
            name, (lambda: fn(*args, **kwargs)) if (args or kwargs) else fn)
        self._order.append(name)

    def make_lock(self, name: str, reentrant: bool = False) -> InstrumentedLock:
        return InstrumentedLock(self, name, reentrant=reentrant)

    def instrument(self, obj: object,
                   attrs: Sequence[str] = ("_lock",)) -> object:
        """Swap `threading.Lock`-like attributes on a live object for
        instrumented ones. Lock names are `ClassName.attr`, matching the
        analyzer's C002 node spelling, so a dynamic DeadlockError names
        the same edges the static cycle report does."""
        for a in attrs:
            cur = getattr(obj, a)
            reentrant = "RLock" in type(cur).__name__
            setattr(obj, a, self.make_lock(
                f"{type(obj).__name__}.{a}", reentrant=reentrant))
        return obj

    # ------------------------------------------------------------------
    # the baton
    # ------------------------------------------------------------------
    def _task_name(self) -> str:
        name = getattr(self._local, "task", None)
        if name is None:
            raise ScheduleError(
                "yield_point()/instrumented lock used outside a "
                "scheduler task")
        return name

    def _record(self, task: str, op: str) -> None:
        # every _record call site holds _cond; main reads trace only
        # after run() has joined every task (join-after-fini, the dual
        # of the init-before-share gap):
        # ds-lint: ok C001 guarded by _cond at all call sites, read post-join
        self.trace.append((task, op))

    def _pick_next(self) -> Optional[str]:
        ready = sorted(n for n, t in self._tasks.items()
                       if t.state == _READY)
        if ready:
            return self._rng.choice(ready)
        return None

    def _live(self) -> List[_Task]:
        return [t for t in self._tasks.values() if t.state != _DONE]

    def _dispatch_locked(self) -> None:
        """Pick the next READY task and hand it the baton. Caller holds
        self._cond. Raises DeadlockError when live tasks exist but none
        are runnable."""
        self._switches += 1
        if self._switches > self.max_switches:
            raise ScheduleError(
                f"schedule exceeded max_switches={self.max_switches} "
                "(livelock or missing termination)")
        nxt = self._pick_next()
        if nxt is None:
            live = self._live()
            if live:  # all blocked on locks — a realized deadlock
                raise DeadlockError(
                    held={t.name: list(t.held) for t in live},
                    waiting={t.name: t.waiting_on or "?" for t in live
                             if t.waiting_on},
                )
            self._current = None  # everything finished
        else:
            self._tasks[nxt].state = _RUNNING
            self._current = nxt
        self._cond.notify_all()

    def yield_point(self, op: str = "yield") -> None:
        """Record `op` and hand the baton to a (seeded-)random READY
        task. Instrumented locks call this on every acquire/release;
        tasks may also call it directly to expose extra interleavings."""
        me = self._task_name()
        with self._cond:
            self._record(me, op)
            self._tasks[me].state = _READY
            self._dispatch_locked()
            while self._current != me:
                if self._abort:
                    raise _Aborted()
                self._cond.wait()

    # ------------------------------------------------------------------
    # instrumented-lock protocol (called from task threads)
    # ------------------------------------------------------------------
    def _outside_idle(self) -> bool:
        """True when no schedule is live — before run() or after every
        task finished. Instrumented locks touched then (e.g. a post-run
        assertion reading through a guarded property) degrade to
        trivial single-threaded acquire/release instead of erroring."""
        return (not self._started
                or all(t.state == _DONE for t in self._tasks.values()))

    def _lock_acquire(self, lock: InstrumentedLock, blocking: bool) -> bool:
        if getattr(self._local, "task", None) is None \
                and self._outside_idle():
            return True
        me = self._task_name()
        task = self._tasks[me]
        with self._cond:
            if lock.owner == me:
                if lock.reentrant:
                    lock._depth += 1
                    self._record(me, f"reacquire:{lock.name}")
                    return True
                raise ScheduleError(
                    f"{me} re-acquired non-reentrant lock {lock.name} "
                    "(self-deadlock on a real threading.Lock)")
            # yield BEFORE taking the lock: this is the interleaving
            # point where another task may slip in between check and
            # acquisition — the schedule permutes exactly here
            self._record(me, f"acquire:{lock.name}")
            task.state = _READY
            self._dispatch_locked()
            while True:
                if self._current == me and lock.owner is None:
                    lock.owner = me
                    lock._depth = 1
                    task.held.append(lock.name)
                    task.waiting_on = None
                    task.state = _RUNNING
                    return True
                if self._current == me and lock.owner is not None:
                    if not blocking:
                        task.state = _RUNNING
                        self._record(me, f"tryfail:{lock.name}")
                        return False
                    # park: give the baton away until the owner releases
                    task.state = _BLOCKED
                    task.waiting_on = lock.name
                    self._record(me, f"block:{lock.name}")
                    self._dispatch_locked()
                if self._abort:
                    raise _Aborted()
                self._cond.wait()

    def _lock_release(self, lock: InstrumentedLock) -> None:
        if getattr(self._local, "task", None) is None \
                and self._outside_idle():
            return
        me = self._task_name()
        task = self._tasks[me]
        with self._cond:
            if lock.owner != me:
                raise ScheduleError(
                    f"{me} released {lock.name} owned by {lock.owner}")
            lock._depth -= 1
            if lock._depth > 0:  # reentrant inner release
                self._record(me, f"rerelease:{lock.name}")
                return
            lock.owner = None
            task.held.remove(lock.name)
            self._record(me, f"release:{lock.name}")
            # wake lock waiters: they become READY and re-contend
            for t in self._tasks.values():
                if t.state == _BLOCKED and t.waiting_on == lock.name:
                    t.state = _READY
                    t.waiting_on = None
            task.state = _READY
            self._dispatch_locked()
            while self._current != me:
                if self._abort:
                    raise _Aborted()
                self._cond.wait()

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------
    def _task_main(self, task: _Task) -> None:
        self._local.task = task.name
        with self._cond:
            # wait for the baton before the first user instruction runs
            while self._current != task.name:
                if self._abort:
                    task.state = _DONE
                    self._cond.notify_all()
                    return
                self._cond.wait()
        try:
            task.fn()
        except _Aborted:
            pass  # unparked by a fatal error elsewhere; run() reports it
        except BaseException as e:  # noqa: BLE001 — surfaced in run()
            task.exc = e
        finally:
            with self._cond:
                # drop any locks an excepting task still holds, so the
                # remaining tasks aren't wedged by the failure itself
                for t_lock_name in list(task.held):
                    for other in self._tasks.values():
                        if other.state == _BLOCKED and \
                                other.waiting_on == t_lock_name:
                            other.state = _READY
                            other.waiting_on = None
                task.held.clear()
                task.state = _DONE
                self._record(task.name, "exit")
                if task.exc is not None:
                    # a task raised: abort survivors rather than let
                    # them run against half-mutated state
                    self._abort = True
                    self._current = None
                    self._cond.notify_all()
                elif not self._abort:
                    try:
                        self._dispatch_locked()
                    except BaseException as e:  # deadlock among survivors
                        task.exc = e
                        self._abort = True
                        self._current = None
                        self._cond.notify_all()
                else:
                    self._cond.notify_all()

    def run(self) -> None:
        if self._started:
            raise ScheduleError("run() called twice")
        if not self._tasks:
            return
        self._started = True
        for name in self._order:
            t = self._tasks[name]
            t.thread = threading.Thread(
                target=self._task_main, args=(t,),
                name=f"interleave-{name}", daemon=True)
            t.thread.start()
        with self._cond:
            self._dispatch_locked()
        for name in self._order:
            th = self._tasks[name].thread
            assert th is not None
            th.join(timeout=60)
            if th.is_alive():
                raise ScheduleError(
                    f"task {name!r} failed to finish (wedged schedule)")
        for name in self._order:
            exc = self._tasks[name].exc
            if exc is not None:
                raise exc

    # ------------------------------------------------------------------
    # reporting
    # ------------------------------------------------------------------
    def trace_lines(self) -> List[str]:
        return [f"{t}:{op}" for t, op in self.trace]

    def trace_digest(self) -> str:
        h = hashlib.blake2b(digest_size=16)
        for line in self.trace_lines():
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()


def run_interleaved(seed: int, tasks: Sequence[Tuple[str, Callable[[], None]]],
                    instrument: Sequence[Tuple[object, Sequence[str]]] = (),
                    max_switches: int = 100_000) -> CooperativeScheduler:
    """One-call wrapper: build a scheduler, instrument objects, spawn
    the named tasks, run, and return the scheduler (trace + digest)."""
    sched = CooperativeScheduler(seed=seed, max_switches=max_switches)
    for obj, attrs in instrument:
        sched.instrument(obj, attrs)
    for name, fn in tasks:
        sched.spawn(name, fn)
    sched.run()
    return sched
