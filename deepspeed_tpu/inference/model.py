"""Inference-side transformer forward over a paged KV cache.

TPU-native redesign of the FastGen model layer
(ref: inference/v2/model_implementations/inference_model_base.py:45
DSInferenceModelBase + inference_transformer_base.py — there, per-layer
CUDA kernels write QKV into the paged cache (linear_blocked_kv_rotary)
and run blocked flash; here the same dataflow is a fused Pallas
write+attend kernel over the paged arena).

Weights are the SAME pytree as models/transformer, passed through
`prepare()` into the SERVING layout (one model family, two execution
modes — the reference needs a separate inference module zoo because its
training and inference kernels differ; here both consume the functional
params dict):

- layers are UNSTACKED into a python list of per-layer dicts. The
  training layout stacks layers [L, ...] for `lax.scan`; serving decode
  unrolls layers, and XLA materializes a per-step HBM copy of every
  static slice of a stacked array inside the decode loop (measured
  0.36 ms/step of pure slice copies on the 350M flagship — 16% of the
  step). Separate per-layer arrays stream straight into their GEMMs.
- Q/K/V projections fuse into one [E, H+2KV, D] GEMM and the llama
  gate/up pair into one [E, 2F] GEMM (decode is launch-bound at small
  batch; fewer, fatter MXU ops). Under a TP mesh weights stay UNFUSED:
  splitting a 'model'-sharded fused output would insert collectives.
- weights may be per-channel int8 (quantization.ChannelQuantWeight):
  the matmul consumes the codes directly (XLA fuses the dequant convert
  into the dot — int8 bytes from HBM) and scales the output.

Cache: per layer, k and v as [num_blocks, block_size, KV_heads,
head_dim] — one cache page is a contiguous (block_size, KV, D) tile
(single large DMA in the kernels); TP shards the KV dim. All cache
mutation goes through Pallas RMW kernels on donated buffers so the
arena is updated in place.
"""

from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as T
from ..ops.attention import causal_attention
from ..ops.pallas.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
    paged_decode_fused,
    paged_kv_write,
    paged_scale_write,
    quantize_kv_rows,
    supports_fused_v2,
)
from .quantization import ChannelQuantWeight, channel_quantize


# ---------------------------------------------------------------------------
# serving weight layout
# ---------------------------------------------------------------------------

def is_prepared(params) -> bool:
    return isinstance(params.get("layers"), (list, tuple))


def prepare(params: Dict[str, Any], cfg: T.TransformerConfig,
            fuse: bool = True) -> Dict[str, Any]:
    """Training layout -> serving layout (see module docstring).

    fuse=False keeps wq/wk/wv and w_gate/w_in separate — required under
    a TP mesh where the fused output dim would be 'model'-sharded and
    the split would reshard. Call once (e.g. under jit at
    refresh_params time), NOT inside a per-token compiled step: the
    concats copy the weight tree."""
    if is_prepared(params):
        return params
    out = {k: v for k, v in params.items() if k != "layers"}
    st = params["layers"]
    lead = jax.tree.leaves(st)[0]
    L = cfg.n_layers
    if lead.shape[0] != L:
        raise ValueError(
            f"serving expects flat [n_layers, ...] stacked layers "
            f"(got leading dim {lead.shape[0]} != {L}; merge pipeline "
            "partitions before serving)"
        )
    out["layers"] = [
        prepare_layer({name: w[l] for name, w in st.items()}, cfg, fuse)
        for l in range(L)
    ]
    return out


def prepare_layer(lp: Dict[str, Any], cfg: T.TransformerConfig,
                  fuse: bool = True) -> Dict[str, Any]:
    """One layer's training-layout dict -> serving layout (the per-layer
    body of prepare(); offload serving stages layers through this one at
    a time so a bigger-than-HBM model never materializes whole)."""
    lp = dict(lp)
    if fuse and "wq" in lp:
        lp["w_qkv"] = jnp.concatenate(
            [lp.pop("wq"), lp.pop("wk"), lp.pop("wv")], axis=1)
        if "bq" in lp:
            lp["b_qkv"] = jnp.concatenate(
                [lp.pop("bq"), lp.pop("bk"), lp.pop("bv")], axis=0)
        if cfg.n_experts == 0 and cfg.is_gated and "w_gate" in lp:
            lp["w_gi"] = jnp.concatenate(
                [lp.pop("w_gate"), lp.pop("w_in")], axis=1)
    return lp


# per-layer serving weight name -> (contract_ndim, logical axes) for
# per-channel quantization and TP sharding of the PREPARED layout
_SERVING_SPECS = {
    "w_qkv": (1, ("embed", "heads", "head_dim")),
    "wq": (1, ("embed", "heads", "head_dim")),
    "wk": (1, ("embed", "heads", "head_dim")),
    "wv": (1, ("embed", "heads", "head_dim")),
    "wo": (2, ("heads", "head_dim", "embed")),
    "w_gi": (1, ("embed", "mlp")),
    "w_gate": (1, ("embed", "mlp")),
    "w_in": (1, ("embed", "mlp")),
    "w_out": (1, ("mlp", "embed")),
    "b_qkv": (None, ("heads", "head_dim")),
    "bq": (None, ("heads", "head_dim")),
    "bk": (None, ("heads", "head_dim")),
    "bv": (None, ("heads", "head_dim")),
    "bo": (None, ("embed",)),
    "b_in": (None, ("mlp",)),
    "b_out": (None, ("embed",)),
    "ln1_scale": (None, ("embed",)),
    "ln1_bias": (None, ("embed",)),
    "ln2_scale": (None, ("embed",)),
    "ln2_bias": (None, ("embed",)),
    # MoE expert stacks (never per-channel-quantized; X leading dim)
    "w_router": (None, ("embed", None)),
    # PR-MoE residual dense expert + mixing coefficient
    "wr_in": (1, ("embed", "mlp")),
    "wr_gate": (1, ("embed", "mlp")),
    "wr_out": (1, ("mlp", "embed")),
    "br_in": (None, ("mlp",)),
    "br_out": (None, ("embed",)),
    "w_coef": (None, ("embed", None)),
    "b_coef": (None, (None,)),
}
_MOE_SPECS = {
    "w_in": ("expert", "embed", "expert_mlp"),
    "w_out": ("expert", "expert_mlp", "embed"),
    "w_gate": ("expert", "embed", "expert_mlp"),
    "b_in": ("expert", "expert_mlp"),
    "b_out": ("expert", "embed"),
}


def quantize_prepared(prepared: Dict[str, Any],
                      cfg: T.TransformerConfig) -> Dict[str, Any]:
    """Per-channel int8 over the prepared tree (the decode SPEED path;
    see ChannelQuantWeight). Embedding quantizes per ROW so one scale
    serves both the lookup and the tied-logits contraction. Norm
    scales, biases, the position table, and MoE expert stacks stay full
    precision."""
    out = dict(prepared)
    out["embed"] = channel_quantize(prepared["embed"], 1, scale_first=True)
    if "lm_head" in prepared:
        out["lm_head"] = channel_quantize(prepared["lm_head"], 1)
    out["layers"] = [quantize_layer(lp, cfg) for lp in prepared["layers"]]
    return out


def quantize_layer(lp: Dict[str, Any],
                   cfg: T.TransformerConfig) -> Dict[str, Any]:
    """Per-channel int8 for one prepared layer (see quantize_prepared).

    MoE expert stacks [X, ...] ride the GROUPWISE int8 path instead
    (QuantizedWeight — the N004 machinery): a per-output-channel scale
    does not survive the expert-stacked leading dim, but group scales
    do, so the stacks park as int8 codes (w/ the offload tiers) and
    dequantize transiently where the grouped GEMM consumes them
    (_mlp). Resident expert bytes halve; the router stays fp32."""
    moe = cfg.n_experts > 0
    nlp = dict(lp)
    for name, w in lp.items():
        spec = _SERVING_SPECS.get(name)
        if moe and name in ("w_gate", "w_in", "w_out"):
            from ..ops.quantization import quantize_groupwise
            from .quantization import QuantizedWeight

            q, s = quantize_groupwise(w, 128, 8)
            nlp[name] = QuantizedWeight(q=q, scale=s, bits=8,
                                        dtype_name=str(w.dtype))
            continue
        if spec is None or spec[0] is None:
            continue
        nlp[name] = channel_quantize(w, spec[0])
    return nlp


def _wmm(eq: str, x, w):
    """einsum with a weight that may be per-channel int8: codes feed the
    dot (convert fuses into the MXU operand stream — int8 HBM bytes),
    the per-output-channel scale is an elementwise epilogue."""
    if isinstance(w, ChannelQuantWeight):
        y = jnp.einsum(eq, x, w.q.astype(x.dtype))
        return y * w.scale.astype(x.dtype)
    return jnp.einsum(eq, x, w.astype(x.dtype))


def _embed_rows(embed, tokens):
    if isinstance(embed, ChannelQuantWeight):
        dt = jnp.dtype(embed.dtype_name)
        return (embed.q[tokens].astype(dt)
                * embed.scale[tokens][..., None].astype(dt))
    return embed[tokens]


def _lm_logits(x, params, cfg: T.TransformerConfig):
    """Final-norm'd activations [.., E] -> f32 logits [.., V]. Tied
    embeddings contract WITHOUT materializing embed.T (ref r3 profile:
    the transpose showed up as per-step HBM copies)."""
    if cfg.tie_embeddings:
        emb = params["embed"]
        if isinstance(emb, ChannelQuantWeight):
            y = jnp.einsum("...e,ve->...v", x, emb.q.astype(x.dtype))
            return y.astype(jnp.float32) * emb.scale
        return jnp.einsum("...e,ve->...v", x, emb.astype(x.dtype)
                          ).astype(jnp.float32)
    head = params["lm_head"]
    if isinstance(head, ChannelQuantWeight):
        y = jnp.einsum("...e,ev->...v", x, head.q.astype(x.dtype))
        y = y.astype(jnp.float32) * head.scale
    else:
        y = jnp.einsum("...e,ev->...v", x, head.astype(x.dtype)
                       ).astype(jnp.float32)
    if "lm_head_b" in params:
        y = y + params["lm_head_b"].astype(jnp.float32)
    return y


# ---------------------------------------------------------------------------
# tensor-parallel serving helpers
#
# The reference's inference engine is TP-first: it builds an mp group and
# row/col-slices every Linear (ref: inference/engine.py:254
# _create_model_parallel_group; v2 sharding helpers
# inference/v2/model_implementations/sharding/qkv.py). TPU-native, TP is
# a mesh 'model' axis: weights carry the SAME logical specs as training
# (models/transformer.logical_specs + parallel/sharding rules), the paged
# KV cache shards over its KV-head dim, and XLA inserts the Megatron
# collectives (psum after the row-parallel wo/w_out matmuls). The only
# ops XLA cannot partition are the Pallas custom calls — those run under
# shard_map over the head dims, which the cache layout was designed for
# ("TP shards the KV dim", ops/pallas/paged_attention.py:15).
# ---------------------------------------------------------------------------


def _tp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("model", 1))


def _heads_shardable(mesh: Optional[Mesh], cfg: T.TransformerConfig) -> bool:
    """Pallas kernels may run per-shard only when Q and KV heads both
    split evenly over 'model' (contiguous-block GQA grouping then stays
    device-local: local q group g pairs with local kv head g)."""
    tp = _tp_size(mesh)
    return tp > 1 and cfg.n_heads % tp == 0 and cfg.kv_heads % tp == 0


def _cons(x, mesh: Optional[Mesh], *spec):
    """with_sharding_constraint, shape-guarded: any dim whose mesh-axis
    product does not divide it falls back to replicated."""
    if mesh is None:
        return x
    out = []
    for i, ax in enumerate(spec):
        if ax is None:
            out.append(None)
            continue
        size = mesh.shape.get(ax, 1)
        out.append(ax if size > 1 and x.shape[i] % size == 0 else None)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*out)))


def cache_pspec(mesh: Optional[Mesh], kv_heads: int) -> P:
    """PartitionSpec for one [NBLK, bs, KV, D] cache arena."""
    tp = _tp_size(mesh)
    if tp > 1 and kv_heads % tp == 0:
        return P(None, None, "model", None)
    return P()


def _shard_map_kernel(fn, mesh: Mesh, in_specs, out_specs):
    # fully-manual map (every mesh axis), via the version-portable shim
    from ..platform.mesh import shard_map_partial

    return shard_map_partial(fn, mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             manual_axes=mesh.axis_names)


class PagedCache(NamedTuple):
    """Per-layer lists (length n_layers) of [NBLK, bs, KV, D] arrays.

    int8-quantized caches (kv_quant) additionally carry per-layer
    [NBLK, bs, KV] f32 scale-tile pools: block i's codes dequantize by
    k_scale[i] — the scales are part of the page, so every path that
    moves pages (COW, export/import, spill) moves them together."""

    k: List[jnp.ndarray]
    v: List[jnp.ndarray]
    k_scale: Optional[List[jnp.ndarray]] = None
    v_scale: Optional[List[jnp.ndarray]] = None

    @property
    def block_size(self) -> int:
        return self.k[0].shape[1]

    @property
    def num_blocks(self) -> int:
        return self.k[0].shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(
    cfg: T.TransformerConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
    mesh: Optional[Mesh] = None, kv_quant: bool = False,
) -> PagedCache:
    """kv_quant=True allocates int8 code pools + f32 per-block scale
    tiles instead of `dtype` pools — half (vs bf16) or a quarter (vs
    f32) the resident KV bytes plus KV*8 scale bytes per token."""
    KV, D, L = cfg.kv_heads, cfg.head_dim, cfg.n_layers
    shape = (num_blocks, block_size, KV, D)
    if kv_quant:
        dtype = jnp.int8
    if mesh is not None:
        sharding = NamedSharding(mesh, cache_pspec(mesh, KV))
        mk = lambda: jax.device_put(jnp.zeros(shape, dtype), sharding)
        sc_sharding = NamedSharding(
            mesh, P(*cache_pspec(mesh, KV)[:3]))  # scales shard with KV
        mks = lambda: jax.device_put(
            jnp.ones(shape[:3], jnp.float32), sc_sharding)
    else:
        mk = lambda: jnp.zeros(shape, dtype)
        mks = lambda: jnp.ones(shape[:3], jnp.float32)
    if not kv_quant:
        return PagedCache(k=[mk() for _ in range(L)],
                          v=[mk() for _ in range(L)])
    return PagedCache(
        k=[mk() for _ in range(L)], v=[mk() for _ in range(L)],
        k_scale=[mks() for _ in range(L)], v_scale=[mks() for _ in range(L)])


def _rope_at(x, positions, cfg: T.TransformerConfig):
    """Rotary embedding at per-token positions [T] (decode needs a
    different position per row, unlike training's contiguous offset).
    Frequencies come from T.rope_inv_freq so long-context scaling
    (linear / llama3) and partial rotary (Phi) match the training
    forward exactly."""
    freqs = T.rope_inv_freq(cfg)
    R = T.rope_dim(cfg)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [T, R/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    xr, xp = x[..., :R], x[..., R:]
    c, s = cos[:, None, :], sin[:, None, :]
    if cfg.rope_interleaved:
        # GPT-J rotate_every_two pairing — must match T._rope exactly
        xf = xr.astype(jnp.float32).reshape(*xr.shape[:-1], R // 2, 2)
        x1, x2 = xf[..., 0], xf[..., 1]
        out = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s],
                        axis=-1).reshape(xr.shape)
    else:
        x1, x2 = jnp.split(xr.astype(jnp.float32), 2, axis=-1)  # [T, H, R/2]
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return jnp.concatenate([out.astype(x.dtype), xp], axis=-1)


def _flat_slot_index(positions, block_table, block_size):
    """Token position → flat slot in the [KV, NBLK*bs, D] cache view.

    positions: [T] int32 absolute positions of one sequence (prefill) or
    per-row positions with per-row tables (decode handled by caller)."""
    return block_table[positions // block_size] * block_size + positions % block_size


def _write_kv(cache_k, cache_v, k_new, v_new, flat_idx, mesh=None):
    """Write [T, KV, D] new KV into [NBLK, bs, KV, D] caches at flat
    slots [T] via the Pallas RMW kernel — XLA scatter costs a fixed ~3ms
    per call on TPU (docs/PROFILE_r02.md), which at 2/layer dominated
    the decode step. Under a TP mesh with the KV dim sharded, each device
    RMWs its own KV slice (shard_map; slots are replicated)."""
    KV = cache_k.shape[2]
    tp = _tp_size(mesh)
    if tp > 1 and KV % tp == 0:
        kv = P(None, None, "model", None)
        new = P(None, "model", None)
        return _shard_map_kernel(
            paged_kv_write, mesh,
            in_specs=(kv, kv, new, new, P(None)),
            out_specs=(kv, kv),
        )(cache_k, cache_v, k_new, v_new, flat_idx)
    if tp > 1:
        # KV not divisible: cache/k/v are replicated, but a raw
        # pallas_call cannot run under the multi-device program — use the
        # XLA scatter (SPMD partitions it; the ~3ms scatter cost returns
        # only on this degenerate kv_heads % tp != 0 layout)
        return _write_kv_xla(cache_k, cache_v, k_new, v_new, flat_idx)
    return paged_kv_write(cache_k, cache_v, k_new, v_new, flat_idx)


def _write_kv_xla(cache_k, cache_v, k_new, v_new, flat_idx):
    """jnp scatter oracle for paged_kv_write (tests + CPU/TP fallback).

    -1 slots must be DROPPED: jax wraps negative indices even under
    mode="drop" (only out-of-bounds drops), so map them past the arena
    first — otherwise pad rows would overwrite the last cache slot."""
    NBLK, bs, KV, D = cache_k.shape
    idx = jnp.where(flat_idx < 0, NBLK * bs, flat_idx)
    ck = cache_k.reshape(NBLK * bs, KV, D).at[idx].set(k_new, mode="drop")
    cv = cache_v.reshape(NBLK * bs, KV, D).at[idx].set(v_new, mode="drop")
    return ck.reshape(NBLK, bs, KV, D), cv.reshape(NBLK, bs, KV, D)


def _write_scales_xla(k_scale, v_scale, ks_new, vs_new, flat_idx):
    """jnp scatter of [T, KV] per-row quant scales into the
    [NBLK, bs, KV] scale pools (oracle + TP-degenerate fallback for
    paged_scale_write; same -1-drops contract as _write_kv_xla)."""
    NBLK, bs, KV = k_scale.shape
    idx = jnp.where(flat_idx < 0, NBLK * bs, flat_idx)
    ks = k_scale.reshape(NBLK * bs, KV).at[idx].set(ks_new, mode="drop")
    vs = v_scale.reshape(NBLK * bs, KV).at[idx].set(vs_new, mode="drop")
    return ks.reshape(NBLK, bs, KV), vs.reshape(NBLK, bs, KV)


def _write_kv_quant(cache_k, cache_v, k_scale, v_scale, k_new, v_new,
                    flat_idx, mesh=None):
    """Quantize [T, KV, D] new rows (quantize_kv_rows — THE rounding
    authority, shared with the fused kernel) and write codes + scale
    rows into the int8 pools. Codes ride the same Pallas RMW path as
    bf16 (_write_kv is dtype-generic); scales ride paged_scale_write
    (or the XLA scatter on the degenerate TP layout)."""
    qk, ks, qv, vs = quantize_kv_rows(k_new, v_new)
    ck, cv = _write_kv(cache_k, cache_v, qk, qv, flat_idx, mesh)
    KV = cache_k.shape[2]
    tp = _tp_size(mesh)
    if tp > 1 and KV % tp == 0:
        sp = P(None, None, "model")
        new = P(None, "model")
        cks, cvs = _shard_map_kernel(
            paged_scale_write, mesh,
            in_specs=(sp, sp, new, new, P(None)),
            out_specs=(sp, sp),
        )(k_scale, v_scale, ks, vs, flat_idx)
    elif tp > 1:
        cks, cvs = _write_scales_xla(k_scale, v_scale, ks, vs, flat_idx)
    else:
        cks, cvs = paged_scale_write(k_scale, v_scale, ks, vs, flat_idx)
    return ck, cv, cks, cvs


def _sparsity(cfg: T.TransformerConfig):
    """SparsityConfig for a sparse-trained model, else None. Layouts are
    deterministic (seeded), so serving reproduces the train-time block
    mask exactly — including bigbird/variable random blocks."""
    if cfg.attention_impl != "sparse":
        return None
    return cfg.sparsity_config()


def _sparse_prefill_mask(scfg, Tp: int) -> jnp.ndarray:
    """Static [Tp, Tp] bool token mask from the block layout (causality
    included). Tp is a compiled-shape constant, so this is trace-time
    numpy, not device work."""
    import numpy as np

    nb = -(-Tp // scfg.block)
    lay = scfg.layout(nb * scfg.block)  # [nb, nb]
    blk = np.arange(Tp) // scfg.block
    mask = lay[np.ix_(blk, blk)] & (np.arange(Tp)[None, :] <= np.arange(Tp)[:, None])
    return jnp.asarray(mask)


def _masked_causal_attention(q, k, v, mask):
    """[B,S,H,D] attention under an explicit [S,S] token mask — the
    serving path for sparse-trained models (same masked-softmax math as
    ops/sparse_attention.sparse_causal_attention, without the gather)."""
    from ..ops.attention import _repeat_kv

    B, S, H, D = q.shape
    rep = q.shape[2] // k.shape[2]  # GQA
    k = _repeat_kv(k, rep)
    v = _repeat_kv(v, rep)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D**0.5)
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _sparse_decode_allowed(scfg, positions, n_slots: int) -> jnp.ndarray:
    """[S, n_slots] bool: which absolute kv positions each decode row may
    attend to under its layout row (block of the row's own position).
    Layout rows are prefix-stable, so the table built for the cache span
    matches the train-time layout of any shorter sequence."""
    import numpy as np

    sblk = scfg.block
    nb = -(-n_slots // sblk)
    lay = jnp.asarray(scfg.layout(nb * sblk))  # [nb, nb] (trace-time numpy)
    q_blk = positions // sblk  # [S] traced
    rows = lay[q_blk]  # [S, nb]
    kv_blk = jnp.arange(n_slots) // sblk  # [n_slots]
    return rows[:, kv_blk]


def _sparse_decode_allowed_slots(scfg, positions, n_blocks: int,
                                 bs: int) -> jnp.ndarray:
    """[S, NB] bool at CACHE-BLOCK granularity for the Pallas decode
    kernel's layout mask (scalar prefetch). Valid only when
    scfg.block % bs == 0 — then every cache block lies inside exactly
    one layout block, so the block-granular skip is exact."""
    sblk = scfg.block
    nb_sparse = -(-(n_blocks * bs) // sblk)
    lay = jnp.asarray(scfg.layout(nb_sparse * sblk))
    rows = lay[positions // sblk]  # [S, nb_sparse]
    slot_sparse = (jnp.arange(n_blocks) * bs) // sblk  # [NB]
    return rows[:, slot_sparse]


def _mlp(h, lp, cfg: T.TransformerConfig, census_cb=None):
    """FFN over [T, E] tokens — dense or MoE (Mixtral-class serving).

    Dense llama uses the fused [E, 2F] gate|up GEMM when the prepared
    layout carries it (see prepare()).

    MoE serving is CAPACITY-FREE exact top-k for ANY k: every token
    gets its full expert mix — no train-time capacity drops (those are
    a training-throughput artifact; ref: sharded_moe.py topk_gating
    keeps the drops only because the fixed [X, C] buffers feed the
    all-to-all). Gate weights reproduce the training combine weights
    exactly (top-1: the softmax gate; k>=2: renormalized), so serving
    matches the training forward wherever training dropped nothing.

    Two expert paths share the gating authority
    (moe.dropless.dropless_topk_gating):
    - cfg.moe_dropless: per-expert token batching — the ragged batch's
      rows stable-sort by expert id and run as ONE grouped (ragged)
      GEMM per projection inside this same compiled program
      (moe/dropless.py dropless_apply), FLOPs proportional to T*k.
    - default: a `lax.scan` over the stacked expert weights with a
      per-expert combine column — X-times the dense FFN FLOPs, no
      [T,X,C] dispatch tensor; fine for decode widths.

    Expert stacks may arrive as groupwise-int8 QuantizedWeight (the
    N004 machinery; quantize_layer): codes dequantize transiently here,
    so resident HBM holds int8 codes + group scales.

    census_cb: when set, per-expert routed-token counts [X] of this
    application stream out via jax.debug.callback — the scheduler's
    expert-utilization/imbalance counters (scheduler.metrics())."""
    act = T._act_fn(cfg)  # one dispatch table for train + serve
    if cfg.n_experts == 0:
        if cfg.is_gated:
            if "w_gi" in lp:
                gi = _wmm("te,ef->tf", h, lp["w_gi"])
                F = gi.shape[-1] // 2
                inner = act(gi[:, :F]) * gi[:, F:]
            else:
                inner = act(_wmm("te,ef->tf", h, lp["w_gate"])) \
                    * _wmm("te,ef->tf", h, lp["w_in"])
        else:
            inner = _wmm("te,ef->tf", h, lp["w_in"])
            if "b_in" in lp:
                inner = inner + lp["b_in"].astype(h.dtype)
            inner = act(inner)
        out = _wmm("tf,fe->te", inner, lp["w_out"])
        if "b_out" in lp:
            out = out + lp["b_out"].astype(h.dtype)
        return out

    from ..moe.dropless import (
        dropless_apply,
        dropless_topk_gating,
        expert_counts,
    )
    from .quantization import QuantizedWeight

    def deq(w):
        # groupwise-int8 expert stacks (N004 machinery) dequantize
        # transiently at use; plain arrays pass through
        return w.dequantize() if isinstance(w, QuantizedWeight) else w

    X = cfg.n_experts
    T_ = h.shape[0]
    logits = h.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32)
    # eval gate: no noise; one authority with the training paths
    idx, wts, _, _ = dropless_topk_gating(logits, cfg.moe_top_k)
    if census_cb is not None:
        jax.debug.callback(census_cb, expert_counts(idx, X))

    has_gate = cfg.is_gated
    has_bias = "b_in" in lp
    if cfg.moe_dropless:
        # per-expert token batching across the ragged batch: ONE
        # grouped GEMM per projection in this same compiled program
        out = dropless_apply(
            h, idx, wts, expert_counts(idx, X),
            deq(lp["w_in"]), deq(lp["w_out"]),
            w_gate=deq(lp["w_gate"]) if has_gate else None,
            b_in=lp.get("b_in"), b_out=lp.get("b_out"), act=act)
        return _moe_residual(out, h, lp, cfg, act)

    # combine-weight matrix [T, X] from the top-k decisions
    weights = jnp.zeros((T_, X), jnp.float32).at[
        jnp.arange(T_)[:, None], idx].add(wts)
    xs = [deq(lp["w_in"]), deq(lp["w_out"]), weights.T.astype(h.dtype)]
    if has_gate:
        xs.append(deq(lp["w_gate"]))
    if has_bias:
        xs += [lp["b_in"], lp["b_out"]]

    def expert(acc, ws):
        if has_gate:
            w_in, w_out, wcol, w_gate = ws[:4]
            inner = act(h @ w_gate.astype(h.dtype)) * (
                h @ w_in.astype(h.dtype)
            )
            y = inner @ w_out.astype(h.dtype)
        else:
            w_in, w_out, wcol = ws[:3]
            b_in, b_out = ws[3:] if has_bias else (None, None)
            inner = h @ w_in.astype(h.dtype)
            if b_in is not None:
                inner = inner + b_in.astype(h.dtype)
            y = act(inner) @ w_out.astype(h.dtype)
            if b_out is not None:
                y = y + b_out.astype(h.dtype)
        return acc + wcol[:, None] * y, None

    out, _ = jax.lax.scan(expert, jnp.zeros_like(h), tuple(xs))
    return _moe_residual(out, h, lp, cfg, act)


def _moe_residual(out, h, lp, cfg: T.TransformerConfig, act):
    """PR-MoE serving tail: dense residual expert + learned mix,
    matching the training combine exactly (ref: moe/layer.py
    use_residual). No-op unless cfg.moe_use_residual."""
    if not cfg.moe_use_residual:
        return out
    if cfg.is_gated:
        inner = act(_wmm("te,ef->tf", h, lp["wr_gate"])) \
            * _wmm("te,ef->tf", h, lp["wr_in"])
    else:
        inner = _wmm("te,ef->tf", h, lp["wr_in"])
        if "br_in" in lp:
            inner = inner + lp["br_in"].astype(h.dtype)
        inner = act(inner)
    dense = _wmm("tf,fe->te", inner, lp["wr_out"])
    if "br_out" in lp:
        dense = dense + lp["br_out"].astype(h.dtype)
    coef = jax.nn.softmax(
        h.astype(jnp.float32) @ lp["w_coef"].astype(jnp.float32)
        + lp["b_coef"].astype(jnp.float32), axis=-1)
    return (out * coef[:, 0:1].astype(h.dtype)
            + dense * coef[:, 1:2].astype(h.dtype))


def _decode_attention(q, ck, cv, table, ctx, use_kernel: bool, allowed=None,
                      allowed_slots=None, window: int = 0, mesh=None,
                      k_new=None, v_new=None, slots=None, alibi=None,
                      k_scale=None, v_scale=None):
    """k_new/v_new/slots non-None selects the FUSED write+attend kernel
    (single-token decode rows; ck/cv are the PRE-write arenas and the
    returned (att, ck, cv) includes the in-kernel RMW).

    alibi: optional [H] per-head slopes (Bloom-class) — every path below
    biases scores by slope_h * key_pos (exact per single query row).

    k_scale/v_scale non-None selects the int8-KV paths: ck/cv hold int8
    codes, the per-block scale tiles ride every branch next to their
    code pools, and fused mode additionally returns the updated scale
    pools (att, ck, cv, cks, cvs). The quantized fused path runs the
    (S, NB)-grid kernel — the v2 manual-DMA kernel stays bf16-only."""
    fused = k_new is not None
    quant = k_scale is not None
    if allowed_slots is not None and use_kernel and _tp_size(mesh) <= 1:
        # block-sparse serving on the Pallas kernels: the layout rides
        # in as a per-slot bitmap. Fused+v2 skips pruned slots' DMA
        # entirely; the (S, NB)-grid kernel clamps them to a resident
        # tile (still no fresh DMA, but a grid step each).
        if fused and not quant and supports_fused_v2(q.shape[-1]):
            return paged_decode_fused(q, ck, cv, table, ctx,
                                      k_new, v_new, slots, window=window,
                                      allowed_slots=allowed_slots,
                                      alibi_slopes=alibi)
        return paged_decode_attention(q, ck, cv, table, ctx, window=window,
                                      allowed_slots=allowed_slots,
                                      k_new=k_new, v_new=v_new, slots=slots,
                                      alibi_slopes=alibi,
                                      k_scale=k_scale, v_scale=v_scale)
    if allowed is not None:
        # layout finer than the cache blocks (or TP mesh): XLA path with
        # the per-position mask. (window is passed through for
        # completeness — the config forbids sparse+sliding_window, so
        # both masks never actually combine today.)
        assert not fused
        return paged_decode_attention_xla(q, ck, cv, table, ctx,
                                          allowed=allowed, window=window,
                                          alibi_slopes=alibi,
                                          k_scale=k_scale, v_scale=v_scale)
    tp = _tp_size(mesh)
    H, KV = q.shape[1], ck.shape[2]
    if tp > 1 and H % tp == 0 and KV % tp == 0:
        # heads are device-local: run the kernel (or its oracle) per shard
        assert not fused
        fn = partial(paged_decode_attention if use_kernel
                     else paged_decode_attention_xla, window=window)
        qs = P(None, "model", None)
        kv = P(None, None, "model", None)
        sp = P(None, None, "model")  # scale tiles shard with the heads
        if quant:
            if alibi is not None:
                wrapped = (lambda q_, k_, v_, t_, c_, ks_, vs_, ab_:
                           fn(q_, k_, v_, t_, c_, k_scale=ks_, v_scale=vs_,
                              alibi_slopes=ab_))
                return _shard_map_kernel(
                    wrapped, mesh,
                    in_specs=(qs, kv, kv, P(None, None), P(None), sp, sp,
                              P("model")),
                    out_specs=qs,
                )(q, ck, cv, table, ctx, k_scale, v_scale,
                  jnp.asarray(alibi, jnp.float32))
            wrapped = (lambda q_, k_, v_, t_, c_, ks_, vs_:
                       fn(q_, k_, v_, t_, c_, k_scale=ks_, v_scale=vs_))
            return _shard_map_kernel(
                wrapped, mesh,
                in_specs=(qs, kv, kv, P(None, None), P(None), sp, sp),
                out_specs=qs,
            )(q, ck, cv, table, ctx, k_scale, v_scale)
        if alibi is not None:
            # slopes shard with the heads (each device biases its own)
            wrapped = (lambda q_, k_, v_, t_, c_, ab_:
                       fn(q_, k_, v_, t_, c_, alibi_slopes=ab_))
            return _shard_map_kernel(
                wrapped, mesh,
                in_specs=(qs, kv, kv, P(None, None), P(None), P("model")),
                out_specs=qs,
            )(q, ck, cv, table, ctx, jnp.asarray(alibi, jnp.float32))
        return _shard_map_kernel(
            fn, mesh,
            in_specs=(qs, kv, kv, P(None, None), P(None)),
            out_specs=qs,
        )(q, ck, cv, table, ctx)
    if use_kernel and tp <= 1:
        if fused and not quant and supports_fused_v2(q.shape[-1]):
            # per-sequence grid + manual block DMA: the dense decode hot
            # path (live blocks only, 2KB row writes instead of 256KB
            # block RMW through the output pipeline)
            return paged_decode_fused(q, ck, cv, table, ctx,
                                      k_new, v_new, slots, window=window,
                                      alibi_slopes=alibi)
        return paged_decode_attention(q, ck, cv, table, ctx, window=window,
                                      k_new=k_new, v_new=v_new, slots=slots,
                                      alibi_slopes=alibi,
                                      k_scale=k_scale, v_scale=v_scale)
    # under a TP mesh with non-divisible heads, the XLA path lets SPMD
    # partition freely (a raw pallas_call over sharded operands cannot)
    assert not fused
    return paged_decode_attention_xla(q, ck, cv, table, ctx, window=window,
                                      alibi_slopes=alibi,
                                      k_scale=k_scale, v_scale=v_scale)


# ---------------------------------------------------------------------------
# decode: a batch of sequences, one new token each
# ---------------------------------------------------------------------------

def decode_step(
    params, cache: PagedCache, tokens, tables, ctx_lens, cfg: T.TransformerConfig,
    use_kernel: bool = True, mesh: Optional[Mesh] = None,
    unique_rows: bool = False, fetch_layer=None, census_cb=None,
):
    """tokens [S] int32, tables [S, NB] int32, ctx_lens [S] int32 (context
    length INCLUDING the new token) → (logits [S, V], new cache).

    ref: engine_v2.py put→model.forward decode path; one compiled program
    per (S, NB) shape. mesh: TP serving — params/cache arrive sharded
    over 'model' and constraints keep activations head-sharded between
    the column-parallel QKV and row-parallel output projections.

    unique_rows=True asserts every row is a distinct sequence (no
    chunked-continuation rows sharing a block table) — this enables the
    fused write+attend kernel, halving Pallas launches per layer. The
    caller must also guarantee padding rows' tables point at a reserved
    scratch block (engine: pad_block), since the fused kernel's
    write-back touches each row's target block.

    fetch_layer: ZeRO-Inference offload serving — a per-layer transform
    (in-jit pinned_host→HBM device_put) applied as each layer's weights
    are consumed, so HBM holds O(one layer) of weights instead of the
    model (ref: docs/_posts/2022-09-10-zero-inference.md full-offload
    mode; the engine builds it)."""
    S = tokens.shape[0]
    if not is_prepared(params):
        params = prepare(params, cfg, fuse=mesh is None)
    H, KV, D, bs = cfg.n_heads, cfg.kv_heads, cfg.head_dim, cache.block_size
    # rows with ctx_lens == 0 are batch padding: their KV write is dropped
    # and their (garbage) logits are sliced off by the engine
    valid = ctx_lens > 0
    positions = jnp.maximum(ctx_lens - 1, 0)  # [S] this token's position
    scfg = _sparsity(cfg)
    allowed = allowed_slots = None
    if scfg is not None:
        if (use_kernel and _tp_size(mesh) <= 1
                and scfg.block % cache.block_size == 0):
            # cache blocks nest inside layout blocks → exact block-
            # granular skip inside the Pallas kernel
            allowed_slots = _sparse_decode_allowed_slots(
                scfg, positions, tables.shape[1], cache.block_size)
        else:
            allowed = _sparse_decode_allowed(
                scfg, positions, tables.shape[1] * cache.block_size)
    x = _embed_rows(params["embed"], tokens)  # [S, E]
    if cfg.use_learned_pos:
        x = x + params["pos_embed"][positions].astype(x.dtype)
    if cfg.embedding_layernorm:
        x = T._norm(x, params["embed_ln_scale"],
                    params.get("embed_ln_bias"), cfg)
    alibi = (jnp.asarray(T.model_alibi_slopes(cfg)) if cfg.alibi
             else None)

    # fused write+attend only on the single-device kernel path (the
    # shard_map TP path and the XLA fallbacks keep the separate write)
    fuse_write = (
        unique_rows and use_kernel and _tp_size(mesh) <= 1
        and allowed is None
    )
    quant = cache.quantized

    # per-row flat slot: each row has its own table; padding rows
    # scatter to -1 which mode="drop" discards
    flat_idx = (
        jnp.take_along_axis(tables, (positions // bs)[:, None], axis=1)[:, 0]
        * bs + positions % bs
    )
    flat_idx = jnp.where(valid, flat_idx, jnp.int32(-1))

    new_k, new_v = [], []
    new_ks, new_vs = [], []  # quantized caches: per-block scale pools
    x_hist = []  # layer outputs; fetch l is barriered on output l-2
    for li, lp in enumerate(params["layers"]):
        if fetch_layer is not None:
            lp = fetch_layer(lp, x_hist[-2] if len(x_hist) >= 2 else None,
                             li)
        h1 = T._act_quant(T._norm(x, lp["ln1_scale"], lp.get("ln1_bias"), cfg), cfg)
        if "w_qkv" in lp:
            qkv = _wmm("se,ehd->shd", h1, lp["w_qkv"])
            if "b_qkv" in lp:
                qkv = qkv + lp["b_qkv"].astype(x.dtype)
            q, k, v = jnp.split(qkv, [H, H + KV], axis=1)
        else:
            q = _wmm("se,ehd->shd", h1, lp["wq"])
            k = _wmm("se,ehd->shd", h1, lp["wk"])
            v = _wmm("se,ehd->shd", h1, lp["wv"])
            if "bq" in lp:
                q = q + lp["bq"].astype(x.dtype)
                k = k + lp["bk"].astype(x.dtype)
                v = v + lp["bv"].astype(x.dtype)
        if cfg.use_rope:
            q = _rope_at(q, positions, cfg)
            k = _rope_at(k, positions, cfg)
        q = _cons(q, mesh, None, "model", None)
        k = _cons(k, mesh, None, "model", None)
        v = _cons(v, mesh, None, "model", None)

        li_c = len(new_k)
        ck_in, cv_in = cache.k[li_c], cache.v[li_c]
        cks = cvs = None
        if fuse_write:
            if quant:
                att, ck, cv, cks, cvs = _decode_attention(
                    q, ck_in, cv_in, tables, ctx_lens, use_kernel,
                    allowed_slots=allowed_slots,
                    window=cfg.window_for_layer(li),
                    mesh=mesh, k_new=k, v_new=v, slots=flat_idx,
                    alibi=alibi, k_scale=cache.k_scale[li_c],
                    v_scale=cache.v_scale[li_c],
                )
            else:
                att, ck, cv = _decode_attention(
                    q, ck_in, cv_in, tables, ctx_lens, use_kernel,
                    allowed_slots=allowed_slots,
                    window=cfg.window_for_layer(li),
                    mesh=mesh, k_new=k, v_new=v, slots=flat_idx,
                    alibi=alibi,
                )
        else:
            if quant:
                ck, cv, cks, cvs = _write_kv_quant(
                    ck_in, cv_in, cache.k_scale[li_c], cache.v_scale[li_c],
                    k, v, flat_idx, mesh)
                cks = _cons(cks, mesh, None, None, "model")
                cvs = _cons(cvs, mesh, None, None, "model")
            else:
                ck, cv = _write_kv(ck_in, cv_in, k, v, flat_idx, mesh)
            ck = _cons(ck, mesh, None, None, "model", None)
            cv = _cons(cv, mesh, None, None, "model", None)
            att = _decode_attention(q, ck, cv, tables, ctx_lens, use_kernel,
                                    allowed=allowed,
                                    allowed_slots=allowed_slots,
                                    window=cfg.window_for_layer(li),
                                    mesh=mesh, alibi=alibi,
                                    k_scale=cks, v_scale=cvs)
        new_k.append(ck)
        new_v.append(cv)
        if quant:
            new_ks.append(cks)
            new_vs.append(cvs)
        out = _wmm("shd,hde->se", att, lp["wo"])
        if "bo" in lp:
            out = out + lp["bo"].astype(x.dtype)

        if cfg.parallel_residual:
            h2 = h1 if cfg.shared_ln else T._act_quant(
                T._norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg), cfg)
            x = x + out + _mlp(h2, lp, cfg, census_cb=census_cb)
        else:
            x = x + out
            h2 = T._act_quant(
                T._norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg), cfg)
            x = x + _mlp(h2, lp, cfg, census_cb=census_cb)
        x_hist.append(x)

    x = T._norm(x, params["ln_f_scale"], params.get("ln_f_bias"), cfg)
    logits = _lm_logits(x, params, cfg)
    logits = _cons(logits, mesh, None, None)
    if quant:
        return logits, PagedCache(k=new_k, v=new_v,
                                  k_scale=new_ks, v_scale=new_vs)
    return logits, PagedCache(k=new_k, v=new_v)


def decode_multi(
    params, cache: PagedCache, tokens, tables, ctx_lens,
    cfg: T.TransformerConfig, n_steps: int, use_kernel: bool = True,
    mesh: Optional[Mesh] = None, unique_rows: bool = True,
    sampling=None, keys=None, step0=None, presence=None,
    fetch_layer=None, census_cb=None,
):
    """Fused decode: n_steps tokens per compiled program.

    One `lax.scan` over decode_step with the next token fed back — the
    host dispatches once per n_steps instead of per token, amortizing
    dispatch/scheduling latency (the SplitFuse-era "fixed work per
    forward" idea applied along time). Block tables must already cover
    ctx_lens + n_steps positions. Rows are by construction distinct
    sequences (each advances its own context), so the fused
    write+attend kernel applies (see decode_step unique_rows).

    sampling: optional sampling.SamplingConfig — the full on-device
    chain (penalty/temperature/top-k/top-p + gumbel-max draw); None =
    greedy argmax. keys [S] per-sequence PRNG keys and step0 [S] int32
    draw counters feed the per-(sequence, step) streams; presence
    [S, V] uint8 rides the carry for the repetition penalty (pass only
    when the config needs it — it is 2 MB at batch 64).

    Returns (generated [n_steps, S] int32, final logits [S, V], cache,
    final presence or None).
    """
    from .sampling import sample_tokens, update_presence

    S = tokens.shape[0]
    V = cfg.vocab_size
    if not is_prepared(params):
        params = prepare(params, cfg, fuse=mesh is None)
    with_presence = presence is not None

    def body(carry, i):
        toks, ctx, _, cache, pres = carry
        logits, cache = decode_step(params, cache, toks, tables, ctx, cfg,
                                    use_kernel, mesh=mesh,
                                    unique_rows=unique_rows,
                                    fetch_layer=fetch_layer,
                                    census_cb=census_cb)
        if sampling is None:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            nxt = sample_tokens(logits, sampling, keys,
                                None if step0 is None else step0 + i,
                                presence=pres)
        if with_presence:
            pres = update_presence(pres, nxt)
        # logits ride the CARRY (overwritten per step): stacking them in ys
        # would keep a dead [n_steps, S, V] accumulator live in HBM
        return (nxt, ctx + 1, logits, cache, pres), nxt

    init = (tokens, ctx_lens, jnp.zeros((S, V), jnp.float32), cache,
            presence)
    (_, _, last_logits, cache, presence), gen = jax.lax.scan(
        body, init, jnp.arange(n_steps, dtype=jnp.int32)
    )
    return gen, last_logits, cache, presence


# ---------------------------------------------------------------------------
# prefill: one sequence's whole prompt
# ---------------------------------------------------------------------------

def prefill_step(
    params, cache: PagedCache, tokens, n_real, table, cfg: T.TransformerConfig,
    use_kernel: bool = True, mesh: Optional[Mesh] = None,
):
    """tokens [Tp] int32 (padded), n_real scalar int32, table [NB] int32 →
    (last-token logits [V], new cache) — single-prompt prefill (the B=1
    view of prefill_batch)."""
    n_real = jnp.asarray(n_real, jnp.int32).reshape(1)
    logits, cache = prefill_batch(
        params, cache, tokens[None], n_real, table[None], cfg, use_kernel,
        mesh=mesh,
    )
    return logits[0], cache


def prefill_batch(
    params, cache: PagedCache, tokens, n_real, tables,
    cfg: T.TransformerConfig, use_kernel: bool = True,
    mesh: Optional[Mesh] = None, fetch_layer=None, census_cb=None,
):
    """Cross-prompt batched prefill: tokens [B, Tp] int32 (padded),
    n_real [B] int32, tables [B, NB] int32 → (last-real-token logits
    [B, V], new cache).

    ONE compiled program runs B concurrent prompts — the ragged-batch
    idea of SplitFuse applied to prefill (ref: inference/v2/kernels/
    ragged_ops/ mixed prefill batches; VERDICT r2 W4: per-prompt calls
    made TTFT degrade linearly under concurrent arrivals). Attention
    over each prompt is plain causal flash (batch dim is natural); new
    KV rows from every prompt scatter into the paged cache in one RMW
    call. Rows with n_real == 0 are batch padding (garbage logits,
    sliced by the caller; their KV writes drop)."""
    B, Tp = tokens.shape
    if not is_prepared(params):
        params = prepare(params, cfg, fuse=mesh is None)
    H, KV = cfg.n_heads, cfg.kv_heads
    bs = cache.block_size
    positions = jnp.arange(Tp, dtype=jnp.int32)
    scfg = _sparsity(cfg)
    sparse_mask = (
        _sparse_prefill_mask(scfg, Tp)
        if scfg is not None and Tp % scfg.block != 0 else None
    )
    x = _embed_rows(params["embed"], tokens)  # [B, Tp, E]
    if cfg.use_learned_pos:
        x = x + params["pos_embed"][:Tp].astype(x.dtype)[None]
    if cfg.embedding_layernorm:
        x = T._norm(x, params["embed_ln_scale"],
                    params.get("embed_ln_bias"), cfg)
    alibi = (jnp.asarray(T.model_alibi_slopes(cfg)) if cfg.alibi
             else None)

    # per-row flat cache slots for the real tokens; -1 rows drop
    flat_idx = jnp.where(
        positions[None, :] < n_real[:, None],
        jnp.take_along_axis(
            tables, positions[None, :] // bs, axis=1
        ) * bs + positions[None, :] % bs,
        jnp.int32(-1),
    ).reshape(B * Tp)

    quant = cache.quantized
    new_k, new_v = [], []
    new_ks, new_vs = [], []  # quantized caches: per-block scale pools
    x_hist = []  # layer outputs; fetch l is barriered on output l-2
    for li, lp in enumerate(params["layers"]):
        if fetch_layer is not None:
            lp = fetch_layer(lp, x_hist[-2] if len(x_hist) >= 2 else None,
                             li)
        h1 = T._act_quant(T._norm(x, lp["ln1_scale"], lp.get("ln1_bias"), cfg), cfg)
        if "w_qkv" in lp:
            qkv = _wmm("bse,ehd->bshd", h1, lp["w_qkv"])
            if "b_qkv" in lp:
                qkv = qkv + lp["b_qkv"].astype(x.dtype)
            q, k, v = jnp.split(qkv, [H, H + KV], axis=2)
        else:
            q = _wmm("bse,ehd->bshd", h1, lp["wq"])
            k = _wmm("bse,ehd->bshd", h1, lp["wk"])
            v = _wmm("bse,ehd->bshd", h1, lp["wv"])
            if "bq" in lp:
                q = q + lp["bq"].astype(x.dtype)
                k = k + lp["bk"].astype(x.dtype)
                v = v + lp["bv"].astype(x.dtype)
        if cfg.use_rope:
            rot = jax.vmap(_rope_at, in_axes=(0, None, None))
            q = rot(q, positions, cfg)
            k = rot(k, positions, cfg)
        q = _cons(q, mesh, None, None, "model", None)
        k = _cons(k, mesh, None, None, "model", None)
        v = _cons(v, mesh, None, None, "model", None)

        KVh, Dh = k.shape[2], k.shape[3]
        l = len(new_k)
        if quant:
            # the prompt's in-flight attention below stays full
            # precision (it never reads the cache); only the RESIDENT
            # copy quantizes — later decode steps read these codes
            ck, cv, cks, cvs = _write_kv_quant(
                cache.k[l], cache.v[l], cache.k_scale[l], cache.v_scale[l],
                k.reshape(B * Tp, KVh, Dh),
                v.reshape(B * Tp, KVh, Dh), flat_idx, mesh)
            new_ks.append(_cons(cks, mesh, None, None, "model"))
            new_vs.append(_cons(cvs, mesh, None, None, "model"))
        else:
            ck, cv = _write_kv(cache.k[l], cache.v[l],
                               k.reshape(B * Tp, KVh, Dh),
                               v.reshape(B * Tp, KVh, Dh), flat_idx, mesh)
        ck = _cons(ck, mesh, None, None, "model", None)
        cv = _cons(cv, mesh, None, None, "model", None)
        new_k.append(ck)
        new_v.append(cv)

        if scfg is not None and Tp % scfg.block == 0:
            # block-gather path: FLOPs/memory scale with layout density,
            # not Tp^2 (same computation the training forward runs)
            from ..ops.attention import _repeat_kv
            from ..ops.sparse_attention import sparse_causal_attention

            rep = q.shape[2] // k.shape[2]  # GQA repeat, as in training
            att = sparse_causal_attention(
                q, _repeat_kv(k, rep), _repeat_kv(v, rep), scfg
            )
        elif sparse_mask is not None:
            # bucket shorter than a layout block: dense-with-mask fallback
            att = _masked_causal_attention(q, k, v, sparse_mask)
        elif _heads_shardable(mesh, cfg):
            # flash kernel per head-shard; GQA grouping stays device-local
            hs = P(None, None, "model", None)
            if alibi is not None:
                att = _shard_map_kernel(
                    lambda q_, k_, v_, ab_: causal_attention(
                        q_, k_, v_, use_flash=use_kernel and cfg.use_flash,
                        window=cfg.window_for_layer(li), alibi=ab_),
                    mesh, in_specs=(hs, hs, hs, P("model")), out_specs=hs,
                )(q, k, v, alibi)
            else:
                att = _shard_map_kernel(
                    partial(causal_attention,
                            use_flash=use_kernel and cfg.use_flash,
                            window=cfg.window_for_layer(li)),
                    mesh, in_specs=(hs, hs, hs), out_specs=hs,
                )(q, k, v)
        else:
            att = causal_attention(
                q, k, v,
                # a raw pallas_call cannot consume TP-sharded operands
                use_flash=use_kernel and cfg.use_flash and _tp_size(mesh) <= 1,
                window=cfg.window_for_layer(li), alibi=alibi)
        out = _wmm("bshd,hde->bse", att, lp["wo"])
        if "bo" in lp:
            out = out + lp["bo"].astype(x.dtype)

        E = x.shape[-1]
        if cfg.parallel_residual:
            h2 = h1 if cfg.shared_ln else T._act_quant(
                T._norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg), cfg)
            x = x + out + _mlp(h2.reshape(B * Tp, E), lp, cfg,
                               census_cb=census_cb).reshape(B, Tp, E)
        else:
            x = x + out
            h2 = T._act_quant(
                T._norm(x, lp["ln2_scale"], lp.get("ln2_bias"), cfg), cfg)
            x = x + _mlp(h2.reshape(B * Tp, E), lp, cfg,
                         census_cb=census_cb).reshape(B, Tp, E)
        x_hist.append(x)

    # logits for each prompt's last REAL token only (logits_gather):
    # gather before the vocab matmul so the head runs on B tokens, not B*Tp
    last = jnp.maximum(n_real - 1, 0)  # [B]; padding rows read pos 0
    x_last = jnp.take_along_axis(x, last[:, None, None].astype(jnp.int32)
                                 .repeat(x.shape[-1], axis=2), axis=1)[:, 0]
    x_last = T._norm(x_last, params["ln_f_scale"], params.get("ln_f_bias"), cfg)
    logits = _lm_logits(x_last, params, cfg)
    logits = _cons(logits, mesh, None, None)
    if quant:
        return logits, PagedCache(k=new_k, v=new_v,
                                  k_scale=new_ks, v_scale=new_vs)
    return logits, PagedCache(k=new_k, v=new_v)
