"""ZeRO-Offload tests: optimizer tier in host DRAM.

Ref model: tests/unit/runtime/zero offload lanes + tests/unit/ops/adam
cpu_adam numerics — the invariant is the offloaded engine reproduces the
in-HBM engine's trajectory exactly while keeping master/moments off the
mesh.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

import deepspeed_tpu as ds
from deepspeed_tpu.models import transformer as T

# interpreter-/compile-heavy: excluded from the fast lane (-m 'not slow')
pytestmark = pytest.mark.slow

VOCAB = 128


def model_cfg(**kw):
    base = dict(vocab_size=VOCAB, n_layers=2, n_heads=4, d_model=64, max_seq=32,
                variant="llama", use_flash=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def ds_config(**kw):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "seed": 7,
        "steps_per_print": 1000,
    }
    base.update(kw)
    return base


def build_engine(**cfg_kw):
    mcfg = model_cfg()
    return ds.initialize(
        ds_config(**cfg_kw),
        loss_fn=T.make_loss_fn(mcfg),
        param_init_fn=lambda k: T.init(mcfg, k),
        param_logical_specs=T.logical_specs(mcfg),
    )


def data(n=3, batch=16, seq=33, seed=0):
    r = np.random.default_rng(seed)
    return [{"tokens": r.integers(0, VOCAB, (batch, seq)).astype(np.int32)} for _ in range(n)]


def losses(engine, batches):
    return [engine.train_batch(b)["loss"] for b in batches]


OFFLOAD = {"offload_optimizer": {"device": "cpu"}}


class TestOffloadEquivalence:
    @pytest.fixture(scope="class")
    def baseline(self):
        return losses(build_engine(), data())

    def test_cpu_offload_matches_hbm(self, baseline):
        engine = build_engine(zero_optimization={"stage": 0, **OFFLOAD})
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_cpu_offload_zero2(self, baseline):
        engine = build_engine(zero_optimization={"stage": 2, **OFFLOAD})
        np.testing.assert_allclose(losses(engine, data()), baseline, rtol=2e-4)

    def test_cpu_offload_bf16(self):
        base = build_engine(bf16={"enabled": True})
        off = build_engine(bf16={"enabled": True},
                           zero_optimization={"stage": 0, **OFFLOAD})
        np.testing.assert_allclose(losses(off, data()), losses(base, data()), rtol=2e-4)


class TestOffloadPlacement:
    def test_state_lives_on_host(self):
        engine = build_engine(zero_optimization={"stage": 1, **OFFLOAD})
        # master + moments: single host device, NOT mesh-sharded
        m = engine.state.master["embed"]
        assert not isinstance(m.sharding, NamedSharding)
        assert m.sharding.device_set == {engine.host_optimizer and
                                         jax.local_devices(backend="cpu")[0]}
        for moment in engine.state.opt.values():
            leaf = moment["embed"] if isinstance(moment, dict) else moment
            if hasattr(leaf, "sharding"):
                assert not isinstance(leaf.sharding, NamedSharding)
        # params stay on the mesh
        assert isinstance(engine.state.params["embed"].sharding, NamedSharding)

    def test_fp16_offload_raises(self):
        with pytest.raises(NotImplementedError, match="fp16"):
            build_engine(fp16={"enabled": True},
                         zero_optimization={"stage": 0, **OFFLOAD})

    def test_nvme_requires_path(self):
        with pytest.raises(ValueError, match="nvme_path"):
            build_engine(zero_optimization={
                "stage": 0, "offload_optimizer": {"device": "nvme"}})


class TestNVMeTier:
    """ZeRO-Infinity NVMe swap: csrc/aio-backed optimizer-state files."""

    def test_nvme_matches_hbm(self, tmp_path):
        base = build_engine()
        off = build_engine(zero_optimization={
            "stage": 0,
            "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)},
        })
        np.testing.assert_allclose(losses(off, data()), losses(base, data()),
                                   rtol=2e-4)
        # swap files exist and TrainState holds no optimizer tier
        import os
        swap_dir = os.path.join(str(tmp_path), "ds_tpu_swap")
        assert os.listdir(swap_dir)
        assert off.state.master is None and off.state.opt is None

    def test_nvme_bf16(self, tmp_path):
        base = build_engine(bf16={"enabled": True})
        off = build_engine(
            bf16={"enabled": True},
            zero_optimization={
                "stage": 0,
                "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)},
            })
        np.testing.assert_allclose(losses(off, data()), losses(base, data()),
                                   rtol=2e-4)


class TestOffloadCheckpoint:
    def test_roundtrip_resume(self, tmp_path):
        cfg = dict(zero_optimization={"stage": 0, **OFFLOAD})
        batches = data(6)
        a = build_engine(**cfg)
        losses(a, batches[:3])
        a.save_checkpoint(str(tmp_path))
        rest_a = losses(a, batches[3:])

        b = build_engine(**cfg)
        b.load_checkpoint(str(tmp_path))
        rest_b = losses(b, batches[3:])
        np.testing.assert_allclose(rest_b, rest_a, rtol=2e-4)
        # restored state back on host
        assert not isinstance(b.state.master["embed"].sharding, NamedSharding)

    def test_nvme_roundtrip_resume(self, tmp_path):
        """Moments travel through the checkpoint, not the scratch swap
        files: the resumed engine must continue the SAME trajectory even
        with a fresh swap dir."""
        def build(swap_dir):
            return build_engine(zero_optimization={
                "stage": 0,
                "offload_optimizer": {"device": "nvme", "nvme_path": str(swap_dir)},
            })

        batches = data(6)
        ckpt = tmp_path / "ckpt"
        a = build(tmp_path / "swap_a")
        losses(a, batches[:3])
        a.save_checkpoint(str(ckpt))
        rest_a = losses(a, batches[3:])

        b = build(tmp_path / "swap_b")
        b.load_checkpoint(str(ckpt))
        rest_b = losses(b, batches[3:])
        np.testing.assert_allclose(rest_b, rest_a, rtol=2e-4)


class TestParamOffload:
    """ZeRO-Infinity param tier: compute-dtype params parked in host DRAM
    (memory_kind='pinned_host') between steps, streamed into HBM inside
    the compiled step (ref: runtime/zero/partitioned_param_coordinator.py
    fetch/release + partitioned_param_swapper.py — the host half)."""

    PARAM_OFF = {"stage": 3, "offload_param": {"device": "cpu"}}

    def test_requires_stage3(self):
        with pytest.raises(ValueError, match="stage 3"):
            build_engine(zero_optimization={
                "stage": 1, "offload_param": {"device": "cpu"}})

    def test_nvme_param_offload_raises(self):
        with pytest.raises(NotImplementedError, match="offload_param"):
            build_engine(zero_optimization={
                "stage": 3, "offload_param": {"device": "nvme",
                                              "nvme_path": "/tmp/x"}})

    def test_params_parked_on_host(self):
        engine = build_engine(zero_optimization=dict(self.PARAM_OFF))
        for leaf in jax.tree.leaves(engine.state.params):
            assert leaf.sharding.memory_kind == "pinned_host"
        # master stays in HBM (offload_param alone moves only the params)
        for leaf in jax.tree.leaves(engine.state.master):
            assert leaf.sharding.memory_kind == "device"

    def test_matches_hbm_trajectory(self):
        base = build_engine(zero_optimization={"stage": 3})
        off = build_engine(zero_optimization=dict(self.PARAM_OFF))
        np.testing.assert_allclose(losses(off, data()), losses(base, data()),
                                   rtol=2e-4)
        for leaf in jax.tree.leaves(off.state.params):
            assert leaf.sharding.memory_kind == "pinned_host"

    def test_full_infinity_tiering(self):
        """offload_param + offload_optimizer: HBM holds neither params nor
        optimizer state between steps — the '13B on one device' class."""
        base = build_engine(zero_optimization={"stage": 3})
        off = build_engine(zero_optimization={
            **self.PARAM_OFF, "offload_optimizer": {"device": "cpu"}})
        np.testing.assert_allclose(losses(off, data()), losses(base, data()),
                                   rtol=2e-4)
        for leaf in jax.tree.leaves(off.state.params):
            assert leaf.sharding.memory_kind == "pinned_host"
        # master/moments on the host device, not the mesh
        assert not isinstance(off.state.master["embed"].sharding, NamedSharding)

    def test_bf16_and_eval(self):
        base = build_engine(bf16={"enabled": True},
                            zero_optimization={"stage": 3})
        off = build_engine(bf16={"enabled": True},
                           zero_optimization=dict(self.PARAM_OFF))
        batches = data()
        np.testing.assert_allclose(losses(off, batches), losses(base, batches),
                                   rtol=2e-4)
        np.testing.assert_allclose(off.eval_batch(batches[0]),
                                   base.eval_batch(batches[0]), rtol=2e-4)

    def test_checkpoint_roundtrip(self, tmp_path):
        cfg = dict(zero_optimization=dict(self.PARAM_OFF))
        batches = data(6)
        a = build_engine(**cfg)
        losses(a, batches[:3])
        a.save_checkpoint(str(tmp_path))
        rest_a = losses(a, batches[3:])

        b = build_engine(**cfg)
        b.load_checkpoint(str(tmp_path))
        rest_b = losses(b, batches[3:])
        np.testing.assert_allclose(rest_b, rest_a, rtol=2e-4)
        for leaf in jax.tree.leaves(b.state.params):
            assert leaf.sharding.memory_kind == "pinned_host"


class TestParamOffloadNVMe:
    """Full ZeRO-Infinity: optimizer AND param tiers on NVMe — params are
    resident nowhere between steps, re-materialized from the swap files'
    master sections each step."""

    def _cfg(self, tmp_path):
        return dict(zero_optimization={
            "stage": 3,
            "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)},
            "offload_param": {"device": "nvme", "nvme_path": str(tmp_path)},
        })

    def test_requires_optimizer_nvme(self, tmp_path):
        with pytest.raises(NotImplementedError, match="offload_optimizer"):
            build_engine(zero_optimization={
                "stage": 3,
                "offload_param": {"device": "nvme", "nvme_path": str(tmp_path)},
            })

    def test_matches_hbm_trajectory(self, tmp_path):
        base = build_engine(zero_optimization={"stage": 3})
        off = build_engine(**self._cfg(tmp_path))
        batches = data()
        np.testing.assert_allclose(losses(off, batches), losses(base, batches),
                                   rtol=2e-4)
        assert off.state.params is None  # no resident copy between steps
        # eval + params property materialize on demand from the swap files
        np.testing.assert_allclose(off.eval_batch(batches[0]),
                                   base.eval_batch(batches[0]), rtol=2e-4)
        assert off.params is not None

    def test_checkpoint_roundtrip(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        batches = data(6)
        a = build_engine(**self._cfg(tmp_path / "swap_a"))
        losses(a, batches[:3])
        a.save_checkpoint(str(ckpt))
        rest_a = losses(a, batches[3:])

        b = build_engine(**self._cfg(tmp_path / "swap_b"))
        b.load_checkpoint(str(ckpt))
        rest_b = losses(b, batches[3:])
        np.testing.assert_allclose(rest_b, rest_a, rtol=2e-4)
        assert b.state.params is None

    def test_cross_layout_checkpoint_interop(self, tmp_path):
        """nvme-param checkpoints must load into ANY engine layout and
        vice versa (the load-under-any-layout property)."""
        batches = data(5)
        ck_a = tmp_path / "a"
        a = build_engine(**self._cfg(tmp_path / "swap_a"))
        losses(a, batches[:2])
        a.save_checkpoint(str(ck_a))
        rest_a = losses(a, batches[2:])

        plain = build_engine(zero_optimization={"stage": 3})
        plain.load_checkpoint(str(ck_a))
        np.testing.assert_allclose(losses(plain, batches[2:]), rest_a,
                                   rtol=2e-4)

        ck_b = tmp_path / "b"
        p2 = build_engine(zero_optimization={"stage": 3})
        losses(p2, batches[:2])
        p2.save_checkpoint(str(ck_b))
        rest_b = losses(p2, batches[2:])
        nv = build_engine(**self._cfg(tmp_path / "swap_c"))
        nv.load_checkpoint(str(ck_b))
        np.testing.assert_allclose(losses(nv, batches[2:]), rest_b,
                                   rtol=2e-4)
