"""Inference engine: continuous batching over a paged KV cache.

TPU-native redesign of FastGen's InferenceEngineV2
(ref: inference/v2/engine_v2.py:30 — put:107, query:158, flush:242;
config ref: inference/v2/ragged/manager_configs.py
RaggedInferenceEngineConfig:137). Differences driven by XLA:

- static shapes: prompts and decode batches are padded to power-of-two
  buckets; each bucket is one compiled program, cached (the reference
  re-runs eager CUDA kernels on exact ragged sizes; here the SplitFuse
  "fixed token budget per step" idea becomes "fixed compiled buckets").
- the ragged batch never exists as a device-side struct: the device sees
  dense padded token buffers + block tables + context lengths; all
  raggedness lives in the host-side StateManager (inference/ragged.py).
- one forward pass per put() for the decode set (all sequences advance
  one token in a single compiled program); concurrent prefills run as
  compiled WAVES — one program per (batch-bucket, token-bucket), capped
  at max_batch_size prompts (the SplitFuse mixed-batch idea).

v1-engine parity (ref: deepspeed/inference/engine.py:39): init_inference
constructs this engine; greedy `generate` is provided for parity with
the wrapped-module generate path.
"""

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pydantic import Field

from ..config.config import ConfigModel, PrefixCacheConfig
from ..resilience.faults import fault_point
from ..resilience.integrity import (
    HandoffIntegrityError,
    corrupt_payload,
    payload_digest,
)
from ..models import transformer as T
from ..utils.logging import log_dist
from ..utils.sync import serving_readback
from . import model as M
from .ragged import StateManager


class InferenceConfig(ConfigModel):
    """ref: inference/v2/ragged/manager_configs.py DSStateManagerConfig +
    RaggedInferenceEngineConfig (max_tracked_sequences,
    max_ragged_batch_size, KVCacheConfig) — flattened to what the TPU
    engine needs. tp_size: tensor-parallel degree (the v1 engine's
    tensor_parallel.tp_size, ref: inference/config.py DeepSpeedTPConfig) —
    weights shard by the training rules table, the KV cache shards over
    its KV-head dim."""

    max_tracked_sequences: int = 256
    max_batch_size: int = 64          # decode sequences per step
    max_seq_len: int = 4096           # per-sequence context cap
    kv_block_size: int = 128
    num_kv_blocks: int = 512          # total paged-cache blocks
    min_prefill_bucket: int = 64
    tp_size: int = 1                  # tensor-parallel degree
    # KV-cache residency dtype: 'auto' = the engine compute dtype;
    # 'int8' = per-block quantized pools (int8 codes + [bs, KV] f32
    # scale tiles per block; docs/paged_attention.md) — ~2x (bf16) /
    # ~4x (f32) more resident tokens per HBM byte, and export/spill
    # payloads shrink by the same factor
    kv_cache_dtype: str = "auto"
    # decode attention implementation: 'auto' = Pallas kernels on TPU,
    # the XLA gather oracle elsewhere; 'pallas' forces the fused
    # kernels (interpret mode off-TPU — the CPU test/gate lane);
    # 'xla' forces the oracle
    decode_impl: str = "auto"
    # MoE expert-utilization census: every compiled decode/prefill
    # application streams its per-expert routed-token counts to the
    # engine (jax.debug.callback — one tiny [X] host transfer per
    # layer), surfaced as engine.moe_expert_census() and the
    # scheduler's moe_expert_* / moe_imbalance metrics. Off by default
    # (a per-layer callback is not free); no effect on dense models.
    moe_census: bool = False
    # automatic prefix caching (config/config.py PrefixCacheConfig):
    # hash-matched block reuse + COW tails in the ragged control plane
    prefix_cache: PrefixCacheConfig = Field(default_factory=PrefixCacheConfig)

    @property
    def blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.kv_block_size)


class KvCacheDtypeError(ValueError):
    """KV pages cannot move between engines whose cache dtypes differ:
    an int8 payload's codes+scales mean nothing to a bf16 pool and vice
    versa, and silently dequantizing would break the token-identity
    contract of the recompute fallback. Typed (a ValueError subclass)
    so the router's fleet-construction check and direct import_kv
    callers can reject mixed-dtype fleets explicitly — mirroring the
    heterogeneous-fleet geometry rejection."""


def _bucket(n: int, lo: int) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _layer_logical_specs(lp: Any, cfg: T.TransformerConfig) -> Dict[str, Any]:
    """Logical-axis specs for ONE prepared layer dict (the single
    source for both park-time and fetch-time sharding)."""
    moe = cfg.n_experts > 0
    return {
        name: (M._MOE_SPECS[name] if moe and name in M._MOE_SPECS
               else M._SERVING_SPECS[name][1])
        for name in lp
    }


def _leaf_sharding(pspec, leaf, mesh: Mesh, memory_kind: str = "device"):
    """Sharding(s) for one prepared leaf: plain leaves take the rules-
    table spec; quantized leaves shard their int codes by that spec and
    replicate the scales (small, and a sharded-scale/packed-codes
    pairing is not worth the bookkeeping)."""
    from .quantization import ChannelQuantWeight, QuantizedWeight

    try:
        mk = NamedSharding(mesh, pspec, memory_kind=memory_kind)
        repl = NamedSharding(mesh, P(), memory_kind=memory_kind)
    except ValueError:
        # backend without distinct memory spaces (CPU, jax 0.4.x): the
        # default memory already IS host memory, so the tier placement
        # collapses to a plain sharding
        mk = NamedSharding(mesh, pspec)
        repl = NamedSharding(mesh, P())
    if isinstance(leaf, QuantizedWeight):
        return QuantizedWeight(q=mk, scale=repl, bits=leaf.bits,
                               dtype_name=leaf.dtype_name)
    if isinstance(leaf, ChannelQuantWeight):
        return ChannelQuantWeight(q=mk, scale=repl,
                                  dtype_name=leaf.dtype_name)
    return mk


def _prepared_specs(prepared: Any, cfg: T.TransformerConfig) -> Any:
    """Logical-axis tree matching a PREPARED serving tree (M.prepare
    layout: per-layer list, unfused under TP)."""
    # top-level entries come from the training spec table (one source of
    # truth; prepare() leaves them untouched)
    top = {k: v for k, v in T.logical_specs(cfg).items() if k != "layers"}
    specs: Dict[str, Any] = {k: top[k] for k in prepared if k != "layers"}
    specs["layers"] = [_layer_logical_specs(lp, cfg)
                       for lp in prepared["layers"]]
    return specs


def _shard_serving_params(params: Any, cfg: T.TransformerConfig,
                          mesh: Mesh) -> Any:
    """device_put the PREPARED weight tree with the training rules table
    (parallel/sharding.py — heads/mlp/vocab over 'model'), shape-guarded
    per leaf so e.g. 2 GQA kv-heads under tp=8 replicate instead of
    failing. Quantized leaves shard their int codes by the same logical
    spec (scales replicate — they are small and the pairing of a sharded
    scale dim with packed codes is not worth the bookkeeping).
    ref: inference/engine.py:331 sharded checkpoint load + AutoTP slicing
    — here sharding is a placement, not a tensor-surgery pass."""
    from ..parallel import sharding as Sh
    from .quantization import ChannelQuantWeight, QuantizedWeight

    is_q = lambda x: isinstance(x, (QuantizedWeight, ChannelQuantWeight))
    specs = _prepared_specs(params, cfg)
    # shape-guard against the ARRAY actually placed (int4 codes pack the
    # last dim 2-per-byte, so the guard must see the packed shape)
    shapes = jax.tree.map(
        lambda leaf: leaf.q.shape if is_q(leaf) else leaf.shape,
        params, is_leaf=is_q,
    )
    pspecs = Sh.tree_logical_to_mesh(specs, Sh.make_rules(), mesh,
                                     shapes=shapes)
    shardings = jax.tree.map(
        lambda ps, leaf: _leaf_sharding(ps, leaf, mesh),
        pspecs, params,
        is_leaf=lambda x: isinstance(x, P))
    return jax.tree.map(jax.device_put, params, shardings)


class InferenceEngine:
    """put/query/flush over (params, TransformerConfig)."""

    def __init__(
        self,
        model_config: T.TransformerConfig,
        params: Any,
        config: Optional[InferenceConfig] = None,
        dtype=jnp.bfloat16,
        quantization: Optional[Dict[str, Any]] = None,
        mesh: Optional[Mesh] = None,
        offload: Optional[Dict[str, Any]] = None,
    ):
        """quantization: ZeRO-Inference weight-only PTQ, e.g.
        {"bits": 8, "group_size": 128} — weights stay int8/int4 in HBM
        and dequantize transiently inside each compiled step
        (ref: deepspeed/inference/quantization/).

        offload: ZeRO-Inference FULL-offload serving — {"device": "cpu"}
        parks every LAYER's weights in host DRAM (pinned_host) and
        streams them into HBM inside the compiled step, one layer at a
        time, so models larger than a chip's HBM serve on one chip
        (ref: docs/_posts/2022-09-10-zero-inference.md:52 — the 43 tok/s
        OPT-30B full-offload case; batch-size-first policy applies: the
        per-step cost is dominated by the fixed weight stream, so
        throughput scales with batch until HBM/compute bind).
        Embeddings / lm_head / final norm stay HBM-resident (they are
        the hot constant set). Composes with per-channel int8
        quantization (halves the streamed bytes) AND with a TP mesh
        (each device parks + streams its own weight SHARD — per-device
        stream shrinks by 1/tp). {"device": "nvme", "path": ...} parks
        layers in per-leaf NVMe files instead (bigger-than-DRAM models;
        ref partitioned_param_swapper.py:36): each step's layer fetch
        is an in-program io_callback over the aio read-ahead window
        (inference/offload_store.py); single-chip only.

        mesh: explicit serving mesh; when absent and config.tp_size > 1,
        a {'model': tp_size} mesh is built over the first tp_size devices
        (ref: inference/engine.py:254 _create_model_parallel_group)."""
        self.cfg = model_config
        self.config = config or InferenceConfig()
        if mesh is not None and self.config.tp_size > 1 and \
                int(mesh.shape.get("model", 1)) != self.config.tp_size:
            raise ValueError(
                f"explicit mesh has model={mesh.shape.get('model', 1)} but "
                f"config.tp_size={self.config.tp_size}; drop one of the two"
            )
        if mesh is None and self.config.tp_size > 1:
            from ..platform.mesh import build_mesh

            devs = jax.devices()
            if len(devs) < self.config.tp_size:
                raise ValueError(
                    f"tp_size {self.config.tp_size} > {len(devs)} devices"
                )
            mesh = build_mesh({"model": self.config.tp_size},
                              devices=devs[: self.config.tp_size])
        # a mesh whose axes are all size 1 is the single-device path
        self.mesh = (
            mesh if mesh is not None and any(s > 1 for s in mesh.shape.values())
            else None
        )
        if self.mesh is not None:
            tp = int(self.mesh.shape.get("model", 1))
            if model_config.n_heads % tp != 0:
                raise ValueError(
                    f"n_heads {model_config.n_heads} not divisible by "
                    f"tp_size {tp} (ref AutoTP requires head divisibility, "
                    "module_inject/auto_tp.py)"
                )
        if model_config.attention_impl == "sparse":
            # sparse-trained models serve with the train-time block layout
            # reproduced exactly (inference/model.py _sparsity). Decode
            # runs the Pallas kernel with a per-slot layout bitmap when
            # cache blocks nest inside layout blocks (and no TP mesh);
            # otherwise the XLA paged path carries the per-position mask.
            kernel_ok = (
                jax.default_backend() == "tpu"
                and model_config.sparse_block % self.config.kv_block_size == 0
                and self.mesh is None
            )
            log_dist(
                "serving block-sparse attention "
                f"(mode={model_config.sparse_mode}); decode uses the "
                f"{'Pallas layout-masked' if kernel_ok else 'XLA'} paged "
                "path",
                ranks=[0],
            )
        if model_config.use_learned_pos:
            # prefill pads prompts up to a power-of-two bucket, and every
            # padded position indexes the learned position table — so the
            # largest BUCKET (not just max_seq_len) must fit
            worst = _bucket(self.config.max_seq_len, self.config.min_prefill_bucket)
            if worst > model_config.max_seq:
                raise ValueError(
                    f"gpt2 learned positions ({model_config.max_seq}) are "
                    f"shorter than the largest prefill bucket ({worst}); "
                    "lower max_seq_len so its bucket fits"
                )
        self._offload = None
        self._nvme_store = None
        if offload is not None:  # {} is a config error, not "disabled"
            dev = offload.get("device")
            if dev not in ("cpu", "nvme"):
                raise ValueError(
                    f"offload.device must be 'cpu' or 'nvme' (got {dev!r})")
            if dev == "nvme":
                # bigger-than-DRAM tier (ref: partitioned_param_swapper
                # :36 + the 30 tok/s OPT-30B NVMe case, zero-inference
                # post:52): layers live in per-leaf NVMe files and each
                # step's layer fetch is an in-program io_callback over
                # the aio read-ahead window (inference/offload_store.py)
                if self.mesh is not None:
                    raise NotImplementedError(
                        "nvme offload serving under a TP mesh: the "
                        "io_callback fetch is single-process; use the "
                        "cpu tier with TP, or nvme single-chip"
                    )
                if not offload.get("path"):
                    raise ValueError(
                        "offload={'device': 'nvme'} requires 'path' "
                        "(an NVMe-backed directory)")
                self._offload = {
                    "device": "nvme",
                    "path": offload["path"],
                    "n_threads": int(offload.get("n_threads", 4)),
                    "block_size": int(offload.get("block_size", 1 << 20)),
                    "read_ahead": int(offload.get("read_ahead", 2)),
                }
            else:
                # cpu tier composes with a TP mesh: each device's weight
                # SHARD parks in its pinned_host and streams to its own
                # HBM inside the step (the per-device stream shrinks by
                # 1/tp, so offload TP scales the weight-stream roofline)
                self._offload = {"device": "cpu"}
        self._dtype = dtype
        self._quantization = dict(quantization) if quantization else None
        self._per_channel = bool(self._quantization
                                 and self._quantization.pop("per_channel",
                                                            False))
        if self._quantization is not None:
            unknown = set(self._quantization) - {"bits", "group_size",
                                                 "min_ndim"}
            if unknown:
                raise TypeError(
                    f"unknown quantization keys {sorted(unknown)}; expected "
                    "bits / group_size / min_ndim / per_channel"
                )
        if self._per_channel and int(quantization.get("bits", 8)) != 8:
            raise ValueError(
                "per_channel quantization is int8-only (int4 uses the "
                "groupwise memory path)"
            )
        if quantization and not self._per_channel:
            from .quantization import dequantize_tree

            self._dequant = dequantize_tree
        else:
            # per-channel codes feed the matmuls directly (M._wmm); no
            # step-entry dequant pass
            self._dequant = lambda p: p
        self._prepare_fn = None
        self._layer_xform = None
        self._top_xform = None
        self.refresh_params(params)
        self.state = StateManager(
            num_blocks=self.config.num_kv_blocks,
            block_size=self.config.kv_block_size,
            max_tracked=self.config.max_tracked_sequences,
            enable_prefix_cache=self.config.prefix_cache.enabled,
            cache_pool_blocks=self.config.prefix_cache.pool_blocks,
        )
        self._cow_fn = None  # compiled (cache, src, dst) -> cache page copy
        # compiled block-table transfer pair (disaggregated serving):
        # gather a sequence's KV pages out / scatter them into another
        # engine's cache. Fixed [blocks_per_seq] index width, so ONE
        # program each regardless of sequence length.
        self._kv_gather = None
        self._kv_scatter = None
        # one RESERVED scratch block past the allocator's range: fused
        # write+attend RMWs every decode row's newest block, so padding
        # rows need a target that can never alias a live sequence
        self.pad_block = self.config.num_kv_blocks
        if self.config.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'auto' or 'int8' "
                f"(got {self.config.kv_cache_dtype!r})")
        if self.config.decode_impl not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"decode_impl must be 'auto', 'pallas' or 'xla' "
                f"(got {self.config.decode_impl!r})")
        self.kv_quant = self.config.kv_cache_dtype == "int8"
        self.cache = M.init_cache(
            model_config, self.config.num_kv_blocks + 1,
            self.config.kv_block_size, dtype, mesh=self.mesh,
            kv_quant=self.kv_quant,
        )
        self._use_kernel = (
            self.config.decode_impl == "pallas"
            or (self.config.decode_impl == "auto"
                and jax.default_backend() == "tpu"))
        self._prefill_batch_fns: Dict[Tuple[int, int], Any] = {}
        # keyed (batch_width, unique_rows)
        self._decode_fns: Dict[Tuple[int, bool], Any] = {}
        # always-on S003 tracker (analysis/sanitizer.py): the serving
        # scheduler and warmup() record each dispatch's operand
        # signature per compiled-program name — a finding after warmup
        # means steady-state serving is recompiling (weak-type drift /
        # shape churn), the exact hazard AOT warmup exists to kill
        from ..analysis.sanitizer import RecompileTracker

        self.recompile_tracker = RecompileTracker()
        # MoE expert-utilization census (config.moe_census): per-expert
        # routed-token counts accumulated from every compiled MoE FFN
        # application. debug.callback fires on runtime threads, so the
        # accumulator is lock-guarded (the R003 race class).
        self._census_enabled = (self.config.moe_census
                                and model_config.n_experts > 0)
        self._census = np.zeros((max(model_config.n_experts, 1),),
                                np.int64)
        self._census_lock = threading.Lock()
        # per-bucket static footprints captured by warmup(footprint=True)
        # ({width: {peak_hbm_bytes, ...}} — analysis/costmodel.py)
        self.warmup_footprints: Dict[int, Dict[str, float]] = {}
        kv_bytes = sum(x.nbytes for x in self.cache.k + self.cache.v)
        if self.kv_quant:
            kv_bytes += sum(x.nbytes
                            for x in self.cache.k_scale + self.cache.v_scale)
        log_dist(
            f"inference engine: {self.config.num_kv_blocks} KV blocks x "
            f"{self.config.kv_block_size} tokens ({kv_bytes/2**30:.2f} GiB "
            f"{'int8' if self.kv_quant else str(dtype.__name__ if hasattr(dtype, '__name__') else dtype)} cache), "
            f"max_batch {self.config.max_batch_size}",
            ranks=[0],
        )

    def refresh_params(self, params: Any) -> None:
        """(Re)point the served weight tree — the hybrid-engine shared-
        weights path (ref: runtime/hybrid_engine.py): after training
        steps, generation serves the updated arrays (quantized engines
        re-quantize). The tree is cast and converted to the SERVING
        layout (M.prepare: per-layer unstacked, fused GEMMs — see
        inference/model.py docstring) in one compiled transform.

        Offload engines stage LAYER BY LAYER instead: a bigger-than-HBM
        model must never materialize whole on device, so each layer is
        cast/fused/quantized in its own compiled transform whose outputs
        land directly in pinned_host (device HBM holds one layer
        transiently)."""
        if self._offload is not None:
            self.params = self._refresh_offload(params)
            return
        if self._prepare_fn is None:
            cfg, dtype = self.cfg, self._dtype
            fuse = self.mesh is None
            per_channel = self._per_channel
            qz = self._quantization

            def xform(p):
                cast = jax.tree.map(
                    lambda x: x.astype(dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    p,
                )
                prep = M.prepare(cast, cfg, fuse=fuse)
                if per_channel:
                    prep = M.quantize_prepared(prep, cfg)
                elif qz:
                    from .quantization import quantize_for_inference

                    prep = quantize_for_inference(prep, **qz)
                return prep

            self._prepare_fn = jax.jit(xform)
        prepared = self._prepare_fn(params)
        if self.mesh is not None:
            prepared = _shard_serving_params(prepared, self.cfg, self.mesh)
        self.params = prepared

    def _layer_pspec_sharding(self, lp: Any, memory_kind: str):
        """Per-leaf NamedShardings for ONE prepared layer under the TP
        mesh — the same rules/packing _shard_serving_params uses
        (_layer_logical_specs + _leaf_sharding), restricted to a layer
        subtree, in the given memory kind."""
        from ..parallel import sharding as Sh
        from .quantization import ChannelQuantWeight, QuantizedWeight

        is_q = lambda x: isinstance(x, (QuantizedWeight, ChannelQuantWeight))
        specs = _layer_logical_specs(lp, self.cfg)
        shapes = jax.tree.map(
            lambda leaf: leaf.q.shape if is_q(leaf) else leaf.shape,
            lp, is_leaf=is_q)
        pspecs = Sh.tree_logical_to_mesh(specs, Sh.make_rules(), self.mesh,
                                         shapes=shapes)
        return jax.tree.map(
            lambda ps, leaf: _leaf_sharding(ps, leaf, self.mesh,
                                            memory_kind),
            pspecs, lp, is_leaf=lambda x: isinstance(x, P))

    def _refresh_offload(self, params: Any) -> Any:
        """Layer-at-a-time staging into the offload tier: pinned_host
        (cpu — per-device SHARDS under a TP mesh) or per-leaf NVMe files
        (nvme — inference/offload_store.py)."""
        cfg, dtype = self.cfg, self._dtype
        if self._quantization and not self._per_channel:
            raise NotImplementedError(
                "offload serving with GROUPWISE quantization would "
                "dequantize the whole tree on device each step; use "
                "per_channel int8 (streams codes, scales on output)"
            )
        nvme = self._offload["device"] == "nvme"
        try:
            host = jax.sharding.SingleDeviceSharding(
                jax.devices()[0], memory_kind="pinned_host")
        except ValueError:
            # backend without a pinned_host space (CPU, jax 0.4.x): the
            # default memory already IS host memory
            host = jax.sharding.SingleDeviceSharding(jax.devices()[0])

        from .quantization import ChannelQuantWeight

        is_cq = lambda x: isinstance(x, ChannelQuantWeight)

        def cast(p):
            # quantized leaves pass through whole (their f32 scales must
            # NOT cast to the serving dtype)
            return jax.tree.map(
                lambda x: x if is_cq(x) else (
                    x.astype(dtype)
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                    else jnp.asarray(x)),
                p, is_leaf=is_cq)

        per_channel = self._per_channel
        fuse = self.mesh is None  # TP keeps QKV/gate-up unfused

        def layer_xform(lp):
            lp = M.prepare_layer(cast(lp), cfg, fuse=fuse)
            if per_channel and not any(is_cq(v) for v in lp.values()):
                lp = M.quantize_layer(lp, cfg)
            return lp

        if self._layer_xform is None:
            # one compiled transform per layer; the result is parked to
            # pinned_host eagerly (in-jit host out_shardings is not
            # lowered on every backend), so HBM holds a single layer
            # transiently
            self._layer_xform = jax.jit(layer_xform)
            self._top_xform = jax.jit(
                lambda t: M.quantize_prepared(
                    {**cast(t), "layers": []}, cfg)
                if per_channel else cast(t))
        st = params["layers"]
        if isinstance(st, dict):  # training layout: stacked [L, ...]
            layer_dicts = ({name: w[l] for name, w in st.items()}
                           for l in range(cfg.n_layers))
        else:
            # per-layer list, or the lazy HF import's single-use
            # generator (import_external(lazy_layers=True))
            layer_dicts = st

        if nvme:
            from .offload_store import NvmeLayerStore

            if self._nvme_store is not None:
                # params refresh: reclaim the previous model's NVMe
                # footprint before staging the new one
                self._nvme_store.close()
            self._nvme_store = NvmeLayerStore(
                self._offload["path"], cfg.n_layers,
                n_threads=self._offload["n_threads"],
                block_size=self._offload["block_size"],
                read_ahead=self._offload["read_ahead"])

            def park(l, lp):
                # pull to host and release the device copy immediately
                lp_host = jax.tree.map(
                    lambda w: np.asarray(jax.device_get(w)), lp)
                self._nvme_store.stage_layer(l, lp_host)
                # the served tree carries NO arrays for this layer —
                # the step's io_callback materializes them per use,
                # selected by the static loop index
                return {}
        elif self.mesh is not None:
            def park(l, lp):
                sh = self._layer_pspec_sharding(lp, "pinned_host")
                return jax.tree.map(jax.device_put, lp, sh)
        else:
            def park(l, lp):
                return jax.tree.map(lambda w: jax.device_put(w, host), lp)

        layers = [park(l, self._layer_xform(lp))
                  for l, lp in enumerate(layer_dicts)]
        if len(layers) != cfg.n_layers:
            raise ValueError(
                f"offload staging got {len(layers)} layers for a "
                f"{cfg.n_layers}-layer model — an exhausted single-use "
                "lazy import generator (re-import for a second engine) "
                "or a pipeline-partitioned stack (merge partitions first)"
            )
        if nvme:
            self._nvme_store.finish_staging()
        top_in = {k: v for k, v in params.items() if k != "layers"}
        top = self._top_xform(top_in)
        if self.mesh is not None:
            top = _shard_serving_params({**top, "layers": []}, cfg,
                                        self.mesh)
        top.pop("layers", None)
        top["layers"] = layers
        return top

    def _fetch_layer(self):
        """In-jit offload-tier→HBM fetch for one layer's weights (None
        when weights are HBM-resident).

        The fetch is scheduling-barriered on the activations from TWO
        layers back: without the barrier XLA's scheduler hoists every
        layer's host stream (or NVMe callback) to the program start —
        for a bigger-than-HBM model that is an immediate OOM (observed
        on the 19 GiB 70B-width slice). The 2-layer window still
        overlaps layer l+1's stream with layer l's compute."""
        if self._offload is None:
            return None

        def barrier(lp, dep):
            if dep is None:
                return lp
            return jax.tree.map(
                lambda w: jax.lax.optimization_barrier((w, dep))[0], lp)

        if self._offload["device"] == "nvme":
            from jax.experimental import io_callback

            store = self._nvme_store

            def fetch(lp, dep=None, idx=None):
                # the layer entry carries no arrays; the STATIC loop
                # index selects the manifest row at trace time
                specs = store.layer_specs(idx)
                l = idx
                # the dep rides as a callback ARGUMENT: the runtime may
                # not start the read before the activations two layers
                # back exist, so reads stay inside the rolling window
                # (read_ahead submits the NEXT layers on each wait)
                token = (jnp.zeros((), jnp.int32) if dep is None
                         else jnp.sum(jnp.ravel(dep)[:1]).astype(jnp.int32))
                return io_callback(
                    lambda _tok, _l=l: store.read_layer(int(_l)),
                    specs, token)

            return fetch

        if self.mesh is not None:
            def fetch(lp, dep=None, idx=None):
                lp = barrier(lp, dep)
                # shardings recomputed from leaf names+shapes at trace
                # time: the same rules table that parked the shards
                sh = self._layer_pspec_sharding(lp, "device")
                return jax.tree.map(jax.device_put, lp, sh)

            return fetch

        try:
            dev_s = jax.sharding.SingleDeviceSharding(
                jax.devices()[0], memory_kind="device")
        except ValueError:
            # backend without distinct memory spaces (CPU, jax 0.4.x):
            # the default memory IS the only tier, so the in-jit fetch
            # collapses to a plain placement (same fallback as
            # _leaf_sharding / _refresh_offload)
            dev_s = jax.sharding.SingleDeviceSharding(jax.devices()[0])

        def fetch(lp, dep=None, idx=None):
            lp = barrier(lp, dep)
            return jax.tree.map(lambda w: jax.device_put(w, dev_s), lp)

        return fetch

    # -- compiled-step caches -------------------------------------------
    def _prefill_batch_fn(self, bp: int, tp: int):
        """Compiled cross-prompt prefill for batch bucket bp x token
        bucket tp — ONE program runs all concurrent prompts (ref:
        inference/v2 ragged mixed-prefill batches; fixes the per-prompt
        TTFT pile-up under concurrent arrivals)."""
        key = (bp, tp)
        if key not in self._prefill_batch_fns:
            cfg, use_kernel, deq = self.cfg, self._use_kernel, self._dequant
            mesh = self.mesh
            fetch = self._fetch_layer()

            census = self._census_cb()

            def step(params, cache, tokens, n_real, tables):
                return M.prefill_batch(
                    deq(params), cache, tokens, n_real, tables, cfg,
                    use_kernel, mesh=mesh, fetch_layer=fetch,
                    census_cb=census,
                )

            # donated: the paged KV cache aliases the returned cache
            # (same PagedCache layout in and out); compile caches below
            # are only ever touched by the host dispatch thread
            self._prefill_batch_fns[key] = jax.jit(step, donate_argnums=(1,))
        return self._prefill_batch_fns[key]

    def _census_cb(self):
        """The per-application expert-census sink compiled into MoE
        programs (None when disabled — the compiled program then
        carries no callback at all)."""
        if not self._census_enabled:
            return None

        def add(counts):
            with self._census_lock:
                self._census += np.asarray(counts, np.int64)

        return add

    def moe_expert_census(self) -> np.ndarray:
        """[X] int64 cumulative per-expert routed-token counts (counts
        accumulate over layers and steps; config.moe_census)."""
        with self._census_lock:
            return self._census.copy()

    def _decode_fn(self, s: int, unique_rows: bool = False):
        key = (s, unique_rows)
        if key not in self._decode_fns:
            cfg, use_kernel, deq = self.cfg, self._use_kernel, self._dequant
            mesh = self.mesh
            fetch = self._fetch_layer()

            census = self._census_cb()

            def step(params, cache, tokens, tables, ctx):
                return M.decode_step(
                    deq(params), cache, tokens, tables, ctx, cfg, use_kernel,
                    mesh=mesh, unique_rows=unique_rows, fetch_layer=fetch,
                    census_cb=census,
                )

            # donated: the KV cache aliases the returned cache in-place
            self._decode_fns[key] = jax.jit(step, donate_argnums=(1,))
        return self._decode_fns[key]

    def decode_multi_fn(self, s: int, n_steps: int, sampling=None,
                        with_presence: bool = False):
        """Compiled fused decode (model.decode_multi) for batch width
        `s` — the one construction site that applies the engine's
        dequant wrapper, mirroring _decode_fn. sampling: a
        sampling.SamplingConfig compiled into the program (None =
        greedy); with_presence adds the [s, vocab] repetition-penalty
        bitmap to the carried state."""
        key = (s, n_steps, None if sampling is None else sampling.key(),
               with_presence)
        if not hasattr(self, "_decode_multi_fns"):
            self._decode_multi_fns = {}
        if key not in self._decode_multi_fns:
            cfg, use_kernel, deq = self.cfg, self._use_kernel, self._dequant
            mesh = self.mesh
            fetch = self._fetch_layer()
            census = self._census_cb()

            if sampling is None:
                def step(params, cache, tokens, tables, ctx):
                    return M.decode_multi(
                        deq(params), cache, tokens, tables, ctx, cfg,
                        n_steps=n_steps, use_kernel=use_kernel, mesh=mesh,
                        fetch_layer=fetch, census_cb=census,
                    )
            elif with_presence:
                def step(params, cache, tokens, tables, ctx, keys, step0,
                         presence):
                    return M.decode_multi(
                        deq(params), cache, tokens, tables, ctx, cfg,
                        n_steps=n_steps, use_kernel=use_kernel, mesh=mesh,
                        sampling=sampling, keys=keys, step0=step0,
                        presence=presence, fetch_layer=fetch,
                        census_cb=census,
                    )
            else:
                def step(params, cache, tokens, tables, ctx, keys, step0):
                    return M.decode_multi(
                        deq(params), cache, tokens, tables, ctx, cfg,
                        n_steps=n_steps, use_kernel=use_kernel, mesh=mesh,
                        sampling=sampling, keys=keys, step0=step0,
                        fetch_layer=fetch, census_cb=census,
                    )

            # donated: the KV cache aliases the carried cache output
            self._decode_multi_fns[key] = jax.jit(step, donate_argnums=(1,))
        return self._decode_multi_fns[key]

    def _sample_fn(self, scfg, with_presence: bool):
        """Compiled sampling epilogue over a [n, V] logits batch (the
        put()/prefill token-return path)."""
        from .sampling import sample_tokens

        key = (scfg.key(), with_presence)
        if not hasattr(self, "_sample_fns"):
            self._sample_fns = {}
        if key not in self._sample_fns:
            if with_presence:
                fn = lambda lg, keys, steps, pres: sample_tokens(
                    lg, scfg, keys, steps, presence=pres)
            else:
                fn = lambda lg, keys, steps: sample_tokens(
                    lg, scfg, keys, steps)
            self._sample_fns[key] = jax.jit(fn)
        return self._sample_fns[key]

    def _dev(self, x):
        """Host array → device, replicated over the serving mesh (so the
        compiled step's non-weight operands carry a committed sharding)."""
        if self.mesh is None:
            return jnp.asarray(x)
        return jax.device_put(jnp.asarray(x), NamedSharding(self.mesh, P()))

    def _copy_block(self, src: int, dst: int) -> None:
        """Host-issued cache-page copy (the COW half of prefix caching):
        clone block src's K/V rows into block dst across every layer, in
        ONE compiled program reused for all copies (src/dst are traced
        scalars, so the first copy pays the only compile)."""
        if self._cow_fn is None:
            def cp(cache, s, d):
                # scale tiles are part of the page: a quantized COW
                # clones them with their codes
                return M.PagedCache(
                    k=[ck.at[d].set(ck[s]) for ck in cache.k],
                    v=[cv.at[d].set(cv[s]) for cv in cache.v],
                    k_scale=(None if cache.k_scale is None else
                             [ks.at[d].set(ks[s]) for ks in cache.k_scale]),
                    v_scale=(None if cache.v_scale is None else
                             [vs.at[d].set(vs[s]) for vs in cache.v_scale]),
                )

            # donated: cache aliases the returned PagedCache (in-place
            # page write, no second cache allocation)
            self._cow_fn = jax.jit(cp, donate_argnums=(0,))
        self.cache = self._cow_fn(self.cache, jnp.int32(src),
                                  jnp.int32(dst))

    def kv_bytes_per_token(self) -> int:
        """Resident KV bytes one token costs across all layers — codes
        (+ per-block scale tiles when quantized). The capacity number
        the ds_budget gate pins the int8/bf16 ratio on (>= 1.8x)."""
        per_tok = 0
        for l in range(self.cfg.n_layers):
            # one token slot of one block: [KV, D] in the pool dtype
            per_tok += 2 * self.cache.k[l][0, 0].nbytes
            if self.cache.quantized:
                per_tok += 2 * self.cache.k_scale[l][0, 0].nbytes
        return per_tok

    def prefix_cache_stats(self) -> Dict[str, float]:
        """Per-engine prefix-cache counters: lookup hits/misses,
        cached-token ratio, LRU evictions, COW copies (ragged.py
        StateManager.cache_stats) — plus the KV-pool residency
        numbers: kv_bytes_per_token (codes + scale tiles),
        kv_pool_bytes (whole resident pool incl. the scratch block),
        and kv_quantized (1.0 on the int8 pools)."""
        s = self.state.cache_stats()
        pool = sum(x.nbytes for x in self.cache.k + self.cache.v)
        if self.cache.quantized:
            pool += sum(x.nbytes
                        for x in self.cache.k_scale + self.cache.v_scale)
        s["kv_bytes_per_token"] = float(self.kv_bytes_per_token())
        s["kv_pool_bytes"] = float(pool)
        s["kv_quantized"] = 1.0 if self.cache.quantized else 0.0
        return s

    # -- paged-KV block transfer (prefill/decode disaggregation) ---------
    def _kv_gather_fn(self):
        """Compiled gather of [blocks_per_seq] cache pages across every
        layer: (cache, idx) -> ([L, B, bs, KV, D] k, same v). Pad slots
        index the reserved scratch block, so one program serves every
        sequence length."""
        if self._kv_gather is None:
            def gather(cache, idx):
                out = (jnp.stack([ck[idx] for ck in cache.k]),
                       jnp.stack([cv[idx] for cv in cache.v]))
                if cache.k_scale is not None:
                    # quantized pages travel with their scale tiles
                    out += (jnp.stack([ks[idx] for ks in cache.k_scale]),
                            jnp.stack([vs[idx] for vs in cache.v_scale]))
                return out

            self._kv_gather = jax.jit(gather)
        return self._kv_gather

    def _kv_scatter_fn(self):
        """Compiled scatter of transferred pages into this cache:
        (cache, idx, k, v) -> cache with rows idx overwritten. Pad rows
        land on the reserved scratch block (never a live page)."""
        if self._kv_scatter is None:
            if self.kv_quant:
                def scatter(cache, idx, k, v, ks, vs):
                    return M.PagedCache(
                        k=[ck.at[idx].set(k[l])
                           for l, ck in enumerate(cache.k)],
                        v=[cv.at[idx].set(v[l])
                           for l, cv in enumerate(cache.v)],
                        k_scale=[p.at[idx].set(ks[l])
                                 for l, p in enumerate(cache.k_scale)],
                        v_scale=[p.at[idx].set(vs[l])
                                 for l, p in enumerate(cache.v_scale)],
                    )
            else:
                def scatter(cache, idx, k, v):
                    return M.PagedCache(
                        k=[ck.at[idx].set(k[l])
                           for l, ck in enumerate(cache.k)],
                        v=[cv.at[idx].set(v[l])
                           for l, cv in enumerate(cache.v)],
                    )

            # donated: the live cache aliases the returned one (an
            # in-place page write, no second cache allocation)
            self._kv_scatter = jax.jit(scatter, donate_argnums=(0,))
        return self._kv_scatter

    def _pad_block_idx(self, blocks: List[int]) -> np.ndarray:
        idx = np.full((self.config.blocks_per_seq,), self.pad_block,
                      np.int32)
        idx[:len(blocks)] = blocks
        return idx

    def kv_payload_nbytes(self, n_blocks: int) -> int:
        """Size in bytes of an export_kv payload's K+V page stacks —
        codes plus, for quantized pools, the per-block scale tiles —
        for a sequence holding `n_blocks` blocks: the spill tier's
        budget pre-check (scheduler._try_spill), computed WITHOUT
        paying the compiled gather + readback. A quantized pool's
        payload is ~2x (bf16) / ~4x (f32) smaller, so the same
        pinned-host spill budget parks that many more victims."""
        per_page = int(self.cache.k[0][0].nbytes)
        if self.cache.quantized:
            per_page += int(self.cache.k_scale[0][0].nbytes)
        return 2 * self.cfg.n_layers * n_blocks * per_page

    def export_kv(self, uid: int) -> Dict[str, Any]:
        """Serialize one sequence's paged KV for a cross-engine handoff
        (the DistServe/Splitwise prefill->decode transfer): gather its
        block pages in ONE compiled program and read them back as host
        numpy. The payload is self-describing — seen_tokens, the token
        record (for the receiver's prefix index), and the [L, n_blocks,
        bs, KV, D] K/V page stacks — and import_kv() on any
        geometry-identical engine reconstructs the sequence exactly.
        The readback routes through utils.sync.serving_readback: it is
        a deliberate transfer-boundary sync, sized in KV pages (never
        logits), and the only host crossing in the handoff path."""
        act = fault_point("engine.export_kv", uid=uid)
        if act is not None and act.kind == "delay":
            time.sleep(act.value)  # a hung transfer (timeout-guard tests)
        seq = self.state.get(uid)
        if seq is None:
            raise KeyError(f"unknown sequence uid {uid}")
        # export only the blocks holding WRITTEN KV: a preemption
        # victim (spill path) reserves blocks for its full recompute
        # target ahead of writing them, and import_kv's extend
        # allocates by seen_tokens — the unwritten reservation tail
        # carries no data and must not ride the payload
        nb = min(len(seq.blocks),
                 -(-seq.seen_tokens // self.state.block_size))
        idx = self._pad_block_idx(seq.blocks[:nb])
        self.recompile_tracker.record("kv_transfer_gather", (idx,))
        gathered = self._kv_gather_fn()(self.cache, self._dev(idx))
        k, v = gathered[0], gathered[1]
        payload = {
            "seen_tokens": int(seq.seen_tokens),
            "n_blocks": nb,
            # the receiver must lay the pages into a dtype-identical
            # pool (import_kv rejects mixed-dtype fleets typed)
            "kv_dtype": str(self.cache.k[0].dtype),
            "token_ids": (list(seq.tokens[:seq.seen_tokens])
                          if seq.tokens_valid else None),
            "k": serving_readback(k)[:, :nb],
            "v": serving_readback(v)[:, :nb],
        }
        if self.cache.quantized:
            # per-block scale tiles ship WITH their code pages — and
            # under the digest below, so a flipped scale byte is caught
            # exactly like a flipped code byte
            ks, vs = gathered[2], gathered[3]
            payload["k_scale"] = serving_readback(ks)[:, :nb]
            payload["v_scale"] = serving_readback(vs)[:, :nb]
        # integrity envelope (resilience/integrity.py): blake2b over
        # every field's bytes+dtype+shape (sorted keys — the quantized
        # payload's scale tensors are covered too), attached at the
        # sender — import_kv verifies it before a single page is
        # scattered, so a bit flipped in transit or in the receiver's
        # DRAM falls back to the token-identical recompute path
        # instead of serving corrupted KV
        payload["digest"] = payload_digest(payload)
        return payload

    def import_kv(self, uid: int, payload: Dict[str, Any]) -> None:
        """Adopt a sequence whose KV pages arrive from export_kv() on a
        peer engine: allocate blocks, scatter the pages in ONE compiled
        program, and commit the token record (which also registers the
        transferred prefix in THIS engine's hash-chain index, so later
        prompts sharing it route here for free). Raises RuntimeError
        when the pool cannot fit the sequence — callers fall back to
        recompute (token-identical: draws key on seed/stream/position,
        not on which replica runs them). Raises HandoffIntegrityError
        BEFORE any allocation when the payload's digest envelope does
        not verify (an in-transit/DRAM bit flip) — same fallback."""
        fault_point("engine.import_kv", uid=uid)
        # chaos point 'handoff.payload': kind='corrupt' flips one bit
        # in the K/V page stacks of a COPY of the payload (the
        # in-transit SDC model) — the digest check below must catch it
        act = fault_point("handoff.payload", uid=uid)
        if act is not None and act.kind == "corrupt":
            payload, flips = corrupt_payload(
                payload, act.seed, act.invocation)
            log_dist(f"chaos: corrupted KV handoff payload of uid "
                     f"{uid} ({flips})", ranks=[0])
        if "digest" in payload and \
                payload_digest(payload) != payload["digest"]:
            raise HandoffIntegrityError(
                f"KV handoff payload of uid {uid} failed digest "
                "verification — discarding (recompute fallback)")
        own_dtype = str(self.cache.k[0].dtype)
        sent_dtype = payload.get("kv_dtype", own_dtype)
        if sent_dtype != own_dtype:
            # typed BEFORE any allocation (mirrors the heterogeneous-
            # fleet geometry rejection): a quantized payload cannot
            # land in a full-precision pool — the caller's recompute
            # fallback stays token-identical, silent dequantization
            # would not
            raise KvCacheDtypeError(
                f"KV payload of uid {uid} carries {sent_dtype} pages but "
                f"this engine's pool is {own_dtype} — mixed-kv-dtype "
                "fleets are rejected; recompute the sequence instead")
        n_tok = int(payload["seen_tokens"])
        nb = int(payload["n_blocks"])
        k, v = payload["k"], payload["v"]
        want = self.cache.k[0].shape[1:]  # (bs, KV, D) per page
        if tuple(k.shape[2:]) != want or k.shape[0] != self.cfg.n_layers:
            raise ValueError(
                f"KV payload geometry {k.shape} does not match this "
                f"engine's cache pages {(self.cfg.n_layers, nb) + want} — "
                "disaggregated replicas must be model/geometry-identical")
        if self.kv_quant and ("k_scale" not in payload
                              or "v_scale" not in payload):
            raise KvCacheDtypeError(
                f"int8 KV payload of uid {uid} is missing its per-block "
                "scale tensors — refusing to scatter scaleless codes")
        seq = self.state.extend(uid, n_tok)  # may raise: pool exhausted
        assert len(seq.blocks) == nb, (len(seq.blocks), nb)
        idx = self._pad_block_idx(seq.blocks)
        B = self.config.blocks_per_seq
        dt = self.cache.k[0].dtype
        kp = np.zeros((k.shape[0], B) + tuple(k.shape[2:]), dt)
        vp = np.zeros_like(kp)
        kp[:, :nb], vp[:, :nb] = k, v
        args = [self._dev(kp), self._dev(vp)]
        if self.kv_quant:
            ksp = np.ones((k.shape[0], B) + tuple(k.shape[2:4]), np.float32)
            vsp = np.ones_like(ksp)
            ksp[:, :nb], vsp[:, :nb] = payload["k_scale"], payload["v_scale"]
            args += [self._dev(ksp), self._dev(vsp)]
        self.recompile_tracker.record("kv_transfer_scatter", (idx,))
        self.cache = self._kv_scatter_fn()(
            self.cache, self._dev(idx), *args)
        self.state.commit(uid, n_tok, token_ids=payload["token_ids"])

    def warmup_kv_transfer(self) -> None:
        """Precompile + signature-baseline the handoff gather/scatter
        pair over scratch-only indices, so the first real handoff in
        steady-state serving compiles nothing (the same zero-recompile
        contract warmup() gives the decode grid)."""
        idx = self._pad_block_idx([])
        self.recompile_tracker.record("kv_transfer_gather", (idx,))
        gathered = self._kv_gather_fn()(self.cache, self._dev(idx))
        self.recompile_tracker.record("kv_transfer_scatter", (idx,))
        self.cache = self._kv_scatter_fn()(
            self.cache, self._dev(idx), *gathered)

    def export_parked_kv(self, limit: int) -> List[Dict[str, Any]]:
        """Serialize up to `limit` of this engine's hottest PARKED
        prefix chains (StateManager.parked_chains — MRU-first, full
        token provenance) as export_kv-format payloads, one per chain:
        seen_tokens covers exactly the chain's full blocks, the page
        stacks ride the SAME compiled gather as a live handoff, and
        the blake2b digest envelope is attached. A joining replica
        (inference/router.py add_replica warm boot) import_kv()s each
        payload onto a scratch uid and flushes it, which parks the
        pages AND registers the prefix chain in its own hash index —
        the new replica's first same-prefix prompt scores a cache hit
        before it has served anything. Chains longer than
        blocks_per_seq are truncated to the transfer window (the
        leading blocks still form a valid chain). Read-only on the
        donor: nothing is acquired, flushed, or evicted."""
        payloads: List[Dict[str, Any]] = []
        bs = self.state.block_size
        for tokens, blocks in self.state.parked_chains(limit):
            nb = min(len(blocks), self.config.blocks_per_seq)
            idx = self._pad_block_idx(blocks[:nb])
            self.recompile_tracker.record("kv_transfer_gather", (idx,))
            gathered = self._kv_gather_fn()(self.cache, self._dev(idx))
            payload = {
                "seen_tokens": nb * bs,
                "n_blocks": nb,
                "kv_dtype": str(self.cache.k[0].dtype),
                "token_ids": list(tokens[:nb * bs]),
                "k": serving_readback(gathered[0])[:, :nb],
                "v": serving_readback(gathered[1])[:, :nb],
            }
            if self.cache.quantized:
                payload["k_scale"] = serving_readback(gathered[2])[:, :nb]
                payload["v_scale"] = serving_readback(gathered[3])[:, :nb]
            payload["digest"] = payload_digest(payload)
            payloads.append(payload)
        return payloads

    # -- scheduling queries (ref: engine_v2.py query:158/can_schedule:184)
    def query(self, uid: int) -> Dict[str, Any]:
        seq = self.state.get(uid)
        seen = seq.seen_tokens if seq else 0
        cached_cap = (len(seq.blocks) * self.state.block_size - seen) if seq else 0
        return {
            "seen_tokens": seen,
            "free_blocks": self.state.free_blocks,
            "max_new_tokens": min(
                cached_cap + self.state.free_blocks * self.state.block_size,
                self.config.max_seq_len - seen,
            ),
            "prefix_cache": self.state.cache_stats(),
        }

    def can_schedule(self, uids: Iterable[int], lengths: Iterable[int]) -> bool:
        need = 0
        for uid, n in zip(uids, lengths):
            seq = self.state.get(uid)
            seen = seq.seen_tokens if seq else 0
            if seen + n > self.config.max_seq_len:
                return False
            have = len(seq.blocks) if seq else 0
            need += max(0, -(-(seen + n) // self.state.block_size) - have)
        return need <= self.state.free_blocks

    # -- per-row PRNG streams: key = fold_in(base(seed), uid), draw
    # -- counter = the sampled token's POSITION (seen_tokens at draw
    # -- time) — batch composition never affects a sequence's stream
    def _row_keys(self, seed: int, uids_arr: np.ndarray):
        if not hasattr(self, "_key_fn"):
            self._key_fn = jax.jit(
                lambda base, u: jax.vmap(
                    jax.random.fold_in, in_axes=(None, 0))(base, u)
            )
        return self._key_fn(jax.random.PRNGKey(seed),
                            jnp.asarray(uids_arr, jnp.uint32))

    # -- the engine step (ref: engine_v2.py put:107) ---------------------
    def put(
        self, uids: Sequence[int], tokens: Sequence[np.ndarray],
        return_tokens: bool = False,
        sampling: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        presence: Optional[np.ndarray] = None,
        strict: bool = True,
        sampling_streams: Optional[Sequence[int]] = None,
    ) -> Any:
        """Run one engine step over a ragged batch.

        New uids carry their whole prompt; known uids carry exactly one
        continuation token. Returns next-token logits [len(uids), vocab]
        in input order — or, with return_tokens=True, SAMPLED token ids
        [len(uids)] int32: the sampling chain runs on device and only
        the ids cross to the host (the reference gathers logits /
        samples device-side too: inference/v2 logits_gather + the MII
        sampling contract; round 3 shipped [batch, vocab] fp32 per step).

        sampling: SamplingConfig kwargs (do_sample/temperature/top_k/
        top_p/repetition_penalty); greedy when omitted. seed + stream +
        position define the draw (deterministic, batch-independent);
        the stream id defaults to the uid, overridable per input row
        via sampling_streams (generate() passes its slot indices so a
        fixed seed reproduces regardless of which uids were free).
        presence: optional [len(uids), vocab] uint8 seen-token bitmap,
        required when repetition_penalty != 1 (the engine tracks counts,
        not token sets — generate() builds it from its own history).

        strict=True (default) raises BEFORE any state mutation when the
        batch's new prompts don't fit the KV pool (decode rows in the
        same call are not run either — re-issue after freeing).
        strict=False instead admits prompts per-uid while capacity
        lasts (the v2 scheduler's defer-individual-prompts behavior,
        ref: inference/v2/scheduling_utils.py) and returns
        (results, rejected_uids); rejected prompts' rows are zeros and
        their sequences untouched."""
        uids = list(uids)
        tokens = [np.atleast_1d(np.asarray(t, np.int32)) for t in tokens]
        if len(uids) != len(set(uids)):
            raise ValueError("duplicate uids in one put()")
        if len(uids) != len(tokens):
            raise ValueError("uids and tokens length mismatch")

        prefills: List[Tuple[int, int, np.ndarray]] = []  # (pos, uid, toks)
        # chunked continuation (SplitFuse/ragged analog): an in-flight
        # sequence's multi-token chunk becomes len(chunk) "virtual decode
        # rows" sharing one block table with per-row increasing context —
        # the same compiled decode program serves single-token decodes and
        # continuation prefills (only the last row's logits are surfaced)
        decodes: List[Tuple[int, int, np.ndarray]] = []  # (pos, uid, chunk)
        n_rows = 0
        for i, (uid, toks) in enumerate(zip(uids, tokens)):
            if len(toks) == 0:
                raise ValueError(f"uid {uid}: empty token array")
            seq = self.state.get(uid)
            if seq is not None and seq.seen_tokens > 0:
                if seq.seen_tokens + len(toks) > self.config.max_seq_len:
                    raise ValueError(
                        f"uid {uid}: {seq.seen_tokens}+{len(toks)} tokens "
                        "> max_seq_len"
                    )
                decodes.append((i, uid, toks))
                n_rows += len(toks)
            else:
                if len(toks) > self.config.max_seq_len:
                    raise ValueError(f"prompt of {len(toks)} > max_seq_len")
                prefills.append((i, uid, toks))
        if n_rows > self.config.max_batch_size:
            raise RuntimeError(
                f"{n_rows} decode rows > max_batch_size "
                f"{self.config.max_batch_size}; split the put()"
            )

        scfg = None
        if return_tokens:
            from .sampling import SamplingConfig

            scfg = SamplingConfig(**(sampling or {}))
            if scfg.needs_presence and presence is None:
                raise ValueError(
                    "repetition_penalty needs the seen-token bitmap: pass "
                    "presence=[len(uids), vocab] uint8 (generate() builds "
                    "it from its own history)"
                )
            tok_out = np.zeros((len(uids),), np.int32)
            stream_of = {u: (sampling_streams[i]
                             if sampling_streams is not None else u)
                         for i, u in enumerate(uids)}

        def sample_rows(logits_all, rows, row_uids, row_steps, row_pos):
            """Sample the bucketed logits [bucket, V] in place: real
            rows listed in `rows`; pad rows sample garbage that is
            never read. Working on the BUCKET keeps one compiled
            epilogue per bucket width instead of one per exact row
            count (r4 review finding)."""
            bucket = logits_all.shape[0]
            streams = np.zeros((bucket,), np.uint32)
            steps = np.zeros((bucket,), np.int32)
            streams[np.asarray(rows)] = [stream_of[u] for u in row_uids]
            steps[np.asarray(rows)] = row_steps
            keys = self._row_keys(seed, streams)
            if presence is not None and scfg.needs_presence:
                pres = np.zeros((bucket, presence.shape[1]), presence.dtype)
                pres[np.asarray(rows)] = presence[np.asarray(row_pos)]
                toks = self._sample_fn(scfg, True)(
                    logits_all, keys, self._dev(steps), self._dev(pres))
            else:
                toks = self._sample_fn(scfg, False)(logits_all, keys,
                                                    self._dev(steps))
            tok_out[np.asarray(row_pos)] = np.asarray(toks)[np.asarray(rows)]

        out = np.zeros((len(uids), self.cfg.vocab_size), np.float32)

        rejected: List[int] = []
        if prefills:
            if not self.can_schedule([u for _, u, _ in prefills],
                                     [len(t) for _, _, t in prefills]):
                if strict:
                    # nothing has been mutated yet (decodes run after) —
                    # the caller can free sequences and re-issue the put
                    raise RuntimeError(
                        "insufficient KV blocks for this prefill wave; "
                        "free sequences, split the put(), or use "
                        "strict=False for per-prompt admission"
                    )
                # per-prompt admission (ref: the v2 scheduler defers
                # individual prompts rather than failing the batch):
                # admit in arrival order while capacity lasts
                admitted = []
                for pos, uid, toks in prefills:
                    if self.can_schedule(
                        [u for _, u, _ in admitted] + [uid],
                        [len(t) for _, _, t in admitted] + [len(toks)],
                    ):
                        admitted.append((pos, uid, toks))
                    else:
                        rejected.append(uid)
                prefills = admitted
        if prefills and self.state.enable_prefix_cache:
            # prefix-cache admission: a prompt whose leading full blocks
            # match the content-addressed index SHARES those blocks and
            # prefills only the suffix — routed through the chunked-
            # continuation decode path (it already handles arbitrary
            # start positions against the paged cache), bounded by the
            # decode-row budget. Capacity was checked above WITHOUT
            # cache credit, so a degraded match always still fits.
            missed: List[Tuple[int, int, np.ndarray]] = []
            for pos, uid, toks in prefills:
                budget = self.config.max_batch_size - n_rows
                _, match = self.state.extend(
                    uid, len(toks), token_ids=toks, max_suffix_rows=budget)
                if match.n_cached > 0:
                    if match.cow is not None:
                        # shared full-match tail: clone the page before
                        # the recomputed last token writes into it
                        self._copy_block(*match.cow)
                    suffix = toks[match.n_cached:]
                    decodes.append((pos, uid, suffix))
                    n_rows += len(suffix)
                else:
                    missed.append((pos, uid, toks))
            prefills = missed
        if prefills:
            # prompts run as compiled WAVES (a solo prompt is a bp=1
            # wave — one code path, one compile cache), bucketed in both
            # tokens (max prompt in the wave) and batch (power of 2) and
            # capped so one put() cannot compile an unbounded (bp, tp)
            # activation footprint. Waves are GROUPED BY TOKEN BUCKET
            # (length-sorted): prompts sharing a power-of-two bucket run
            # together, so one long straggler no longer inflates every
            # short prompt's padding to its bucket (r3 advisor finding —
            # the compute cost of a wave is bp * bucket(max member)).
            prefills.sort(key=lambda pu: len(pu[2]))
            groups: Dict[int, List[Tuple[int, int, np.ndarray]]] = {}
            for pu in prefills:
                groups.setdefault(
                    _bucket(len(pu[2]), self.config.min_prefill_bucket), []
                ).append(pu)
            # largest power of two <= max_batch_size, so the bp bucket
            # can never exceed the configured ceiling
            cap = 1 << (self.config.max_batch_size.bit_length() - 1)
            waves = [g[w0:w0 + cap] for _, g in sorted(groups.items())
                     for w0 in range(0, len(g), cap)]
            for wave in waves:
                tp = _bucket(max(len(t) for _, _, t in wave),
                             self.config.min_prefill_bucket)
                bp = _bucket(len(wave), 1)
                toks_b = np.zeros((bp, tp), np.int32)
                n_real = np.zeros((bp,), np.int32)
                tables = np.zeros((bp, self.config.blocks_per_seq), np.int32)
                for row, (pos, uid, toks) in enumerate(wave):
                    n = len(toks)
                    self.state.extend(uid, n)
                    toks_b[row, :n] = toks
                    n_real[row] = n
                    tables[row] = self.state.block_table(
                        [uid], self.config.blocks_per_seq)[0]
                logits, self.cache = self._prefill_batch_fn(bp, tp)(
                    self.params, self.cache, self._dev(toks_b),
                    self._dev(n_real), self._dev(tables),
                )
                for row, (pos, uid, toks) in enumerate(wave):
                    self.state.commit(uid, len(toks), token_ids=toks)
                if return_tokens:
                    sample_rows(
                        logits,
                        list(range(len(wave))),
                        [uid for _, uid, _ in wave],
                        [len(toks) for _, _, toks in wave],
                        [pos for pos, _, _ in wave],
                    )
                else:
                    logits = np.asarray(logits)
                    for row, (pos, uid, toks) in enumerate(wave):
                        out[pos] = logits[row]

        if decodes:
            sp = _bucket(n_rows, 8)
            toks = np.zeros((sp,), np.int32)
            ctx = np.zeros((sp,), np.int32)  # pad rows: ctx 0 = inert
            tables = np.full((sp, self.config.blocks_per_seq),
                             self.pad_block, np.int32)
            last_row: List[int] = []  # each chunk's final row index
            row = 0
            for pos, uid, chunk in decodes:
                base = self.state.get(uid).seen_tokens
                self.state.extend(uid, len(chunk))
                table = self.state.block_table(
                    [uid], self.config.blocks_per_seq, self.pad_block,
                )[0]
                for j, tok in enumerate(chunk):
                    toks[row] = int(tok)
                    ctx[row] = base + j + 1
                    tables[row] = table
                    row += 1
                last_row.append(row - 1)
            # single-token rows are all DISTINCT sequences → the fused
            # write+attend kernel applies; multi-token chunks share a
            # table across rows and keep the separate write kernel
            unique = all(len(c) == 1 for _, _, c in decodes)
            logits, self.cache = self._decode_fn(sp, unique)(
                self.params, self.cache, self._dev(toks),
                self._dev(tables), self._dev(ctx),
            )
            for (pos, uid, chunk), lr in zip(decodes, last_row):
                self.state.commit(uid, len(chunk), token_ids=chunk)
            if return_tokens:
                sample_rows(
                    logits,
                    last_row,
                    [uid for _, uid, _ in decodes],
                    [self.state.get(uid).seen_tokens
                     for _, uid, _ in decodes],
                    [pos for pos, _, _ in decodes],
                )
            else:
                logits_np = np.asarray(logits[:n_rows])
                for (pos, uid, chunk), lr in zip(decodes, last_row):
                    out[pos] = logits_np[lr]
        result = tok_out if return_tokens else out
        if not strict:
            return result, rejected
        return result

    def flush(self, uid: int) -> None:
        """Free a sequence's KV blocks (ref: engine_v2.py flush:242)."""
        self.state.flush(uid)

    # -- AOT warmup: precompile the serving shape-bucket grid ------------
    def warmup(
        self,
        sampling: Optional[Dict[str, Any]] = None,
        widths: Optional[Sequence[int]] = None,
        chunked: bool = True,
        decode_chunks: Sequence[int] = (),
        presence: bool = False,
        footprint: bool = True,
    ) -> Dict[str, Any]:
        """Precompile the (bucket width x chunk) decode/sample grid so
        steady-state serving triggers ZERO recompiles (S003): every
        program a ServingScheduler can dispatch at these widths is
        compiled here, by EXECUTING it once over inert padding rows —
        ctx 0 rows drop their KV writes (XLA path) or write the
        reserved pad_block scratch (fused kernel), so the live cache is
        untouched and the jit call cache (not just an AOT artifact) is
        populated on every jax version.

        widths: decode-row buckets (default: powers of two from 8 up to
        bucket(max_batch_size)). chunked=True additionally compiles the
        shared-table variant mixed prefill chunks need. decode_chunks:
        fused multi-step depths (model.decode_multi) to warm per width.
        sampling/presence select the sampling epilogue variant.
        footprint=True additionally AOT-compiles the per-width decode
        program once more for its static cost report (the jit call
        cache and the AOT artifact are separate compilations), filling
        `self.warmup_footprints[width]` — the per-bucket HBM numbers
        the serving scheduler validates its admission config against
        and feeds to the monitor.

        Logs a one-line compile-time summary and returns
        {programs, seconds, widths, chunks, hbm_per_bucket}."""
        import time as _time
        import warnings as _warnings

        from ..analysis.costmodel import build_cost_report
        from .sampling import SamplingConfig

        scfg = SamplingConfig(**(sampling or {}))
        if widths is None:
            widths, w = [], 8
            top = _bucket(self.config.max_batch_size, 8)
            while w <= top:
                widths.append(w)
                w *= 2
        widths = [int(w) for w in widths]
        t0 = _time.perf_counter()
        n = 0
        rt = self.recompile_tracker
        use_sampler = not (scfg.greedy and not scfg.needs_presence)
        with_pres = bool(presence and scfg.needs_presence)
        V = self.cfg.vocab_size
        for w in widths:
            toks = np.zeros((w,), np.int32)
            ctx = np.zeros((w,), np.int32)
            tables = np.full((w, self.config.blocks_per_seq),
                             self.pad_block, np.int32)
            steps = np.zeros((w,), np.int32)
            keys = self._row_keys(0, np.zeros((w,), np.uint32))
            logits = None
            for uniq in ((True, False) if chunked else (True,)):
                rt.record(f"serving_decode[w{w},u{int(uniq)}]",
                          (toks, tables, ctx))
                logits, self.cache = self._decode_fn(w, uniq)(
                    self.params, self.cache, self._dev(toks),
                    self._dev(tables), self._dev(ctx))
                n += 1
            if with_pres:
                pres = np.zeros((w, V), np.uint8)
                rt.record(f"serving_sample[w{w}]", (steps, pres))
                self._sample_fn(scfg, True)(
                    logits, keys, self._dev(steps), self._dev(pres))
            else:
                rt.record(f"serving_sample[w{w}]", (steps,))
                self._sample_fn(scfg, False)(logits, keys,
                                             self._dev(steps))
            n += 1
            for C in decode_chunks:
                C = int(C)
                if C < 1:
                    continue
                rt.record(f"serving_fused[w{w},c{C}]",
                          (toks, tables, ctx, steps))
                fn = self.decode_multi_fn(
                    w, C, sampling=scfg if use_sampler else None,
                    with_presence=with_pres)
                args = [self.params, self.cache, self._dev(toks),
                        self._dev(tables), self._dev(ctx)]
                if use_sampler:
                    args.append(keys)
                    args.append(self._dev(steps))
                    if with_pres:
                        args.append(self._dev(np.zeros((w, V), np.uint8)))
                _, _, self.cache, _ = fn(*args)
                n += 1
            if footprint:
                # the donated-cache warning is S001 business, not ours
                with _warnings.catch_warnings():
                    _warnings.simplefilter("ignore")
                    compiled = self._decode_fn(w, True).lower(
                        self.params, self.cache, self._dev(toks),
                        self._dev(tables), self._dev(ctx)).compile()
                rep = build_cost_report(compiled,
                                        label=f"serving_decode[w{w}]")
                if rep is not None:
                    self.warmup_footprints[w] = {
                        "peak_hbm_bytes": float(rep.peak_hbm_bytes),
                        "arg_bytes": float(rep.arg_bytes),
                        "temp_bytes": float(rep.temp_bytes),
                        "comm_bytes": float(rep.comm_bytes),
                        # schedule-aware S009 projection per bucket
                        # (analysis/schedule.py): the AOT step-time the
                        # ds_schedule gate pins for the decode buckets
                        "step_time_us": float(rep.step_time_s * 1e6),
                        "exposed_comm_us": float(
                            rep.exposed_comm_s * 1e6),
                    }
        dt = _time.perf_counter() - t0
        fp = self.warmup_footprints
        fp_note = (f", peak {max(f['peak_hbm_bytes'] for f in fp.values()) / 2**20:.0f} MiB"
                   if fp else "")
        log_dist(
            f"serving warmup: {n} compiled programs (decode widths "
            f"{widths}{' +chunked' if chunked else ''}, fused depths "
            f"{[int(c) for c in decode_chunks]}, "
            f"sampling={'on' if use_sampler else 'greedy'}) in {dt:.1f}s"
            f"{fp_note}",
            ranks=[0],
        )
        return {"programs": n, "seconds": dt, "widths": widths,
                "chunks": [int(c) for c in decode_chunks],
                "hbm_per_bucket": {
                    w: f["peak_hbm_bytes"] for w, f in sorted(fp.items())}}

    def sanitize_numerics(self, widths: Optional[Sequence[int]] = None):
        """Numerics sanitizer (analysis/numerics.py) over the serving
        decode buckets: per width, the compiled decode program is
        checked against the engine's serving dtype — accumulation
        downcasts (N001: an additive reduce below fp32 that jax's
        upcast-by-default semantics would never emit means an explicit
        override snuck into the model) — plus the determinism
        analyzer's D001 on the pre-optimization HLO (a mesh-sharded
        threefry draw in a decode bucket would make served tokens a
        function of the TP layout). Compile-time only; defaults to
        the warmed bucket widths (or the smallest bucket before
        warmup). Returns a merged analysis.SanitizerReport."""
        import warnings as _warnings

        from ..analysis.determinism import check_rng_discipline
        from ..analysis.numerics import check_program_numerics
        from ..analysis.report import merge_reports
        from ..profiling.hlo import preopt_hlo_text
        from ..runtime.precision import PrecisionPolicy, hlo_dtype_name

        serving = hlo_dtype_name(self._dtype)
        policy = PrecisionPolicy(
            compute=serving, master=None, grad_accum="f32",
            grad_comm=serving, loss_scaled=False)
        if widths is None:
            widths = sorted(self.warmup_footprints) or [
                min(8, _bucket(self.config.max_batch_size, 8))]
        reports = []
        for w in (int(w) for w in widths):
            toks = np.zeros((w,), np.int32)
            ctx = np.zeros((w,), np.int32)
            tables = np.full((w, self.config.blocks_per_seq),
                             self.pad_block, np.int32)
            # the donated-cache warning is S001 business, not ours
            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore")
                lowered = self._decode_fn(w, True).lower(
                    self.params, self.cache, self._dev(toks),
                    self._dev(tables), self._dev(ctx))
                compiled = lowered.compile()
            reports.append(check_program_numerics(
                compiled, policy, lowered=lowered,
                label=f"serving_decode[w{w}]"))
            pre = preopt_hlo_text(lowered)
            if pre:
                reports.append(check_rng_discipline(
                    pre, label=f"serving_decode[w{w}]"))
        return merge_reports("serving_decode", *reports)

    # -- speculative (multi-token-per-stream) decoding -------------------
    def _verify_chunks(
        self, uids: Sequence[int], chunks: Sequence[np.ndarray],
    ) -> List[np.ndarray]:
        """Run each in-flight uid's candidate chunk through ONE decode
        program and return EVERY row's logits ([len(chunk), V] per uid)
        — the verification half of speculative decoding. KV for all
        candidate rows is written, but seen_tokens is NOT committed:
        the caller commits only the accepted prefix (rejected rows'
        slots are simply overwritten by the next real tokens)."""
        rows = sum(len(c) for c in chunks)
        if rows > self.config.max_batch_size:
            raise RuntimeError(
                f"{rows} verify rows > max_batch_size "
                f"{self.config.max_batch_size}")
        sp = _bucket(rows, 8)
        toks = np.zeros((sp,), np.int32)
        ctx = np.zeros((sp,), np.int32)
        tables = np.full((sp, self.config.blocks_per_seq),
                         self.pad_block, np.int32)
        spans: List[Tuple[int, int]] = []
        row = 0
        for uid, chunk in zip(uids, chunks):
            base = self.state.get(uid).seen_tokens
            self.state.extend(uid, len(chunk))
            table = self.state.block_table(
                [uid], self.config.blocks_per_seq, self.pad_block)[0]
            spans.append((row, row + len(chunk)))
            for j, tok in enumerate(chunk):
                toks[row] = int(tok)
                ctx[row] = base + j + 1
                tables[row] = table
                row += 1
        logits, self.cache = self._decode_fn(sp, False)(
            self.params, self.cache, self._dev(toks),
            self._dev(tables), self._dev(ctx),
        )
        logits_np = np.asarray(logits[:rows])
        return [logits_np[a:b] for a, b in spans]

    @staticmethod
    def _ngram_draft(hist: List[int], ngram: int, k: int) -> List[int]:
        """Prompt-lookup drafting: the most recent earlier occurrence of
        the last `ngram` tokens proposes the k tokens that followed it
        (no draft model — the sequence drafts itself)."""
        if k <= 0 or len(hist) <= ngram:
            return []
        pat = hist[-ngram:]
        for i in range(len(hist) - ngram - 1, -1, -1):
            if hist[i:i + ngram] == pat:
                return hist[i + ngram: i + ngram + k]
        return []

    def generate_speculative(
        self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None, ngram: int = 3,
        draft_len: int = 4, return_stats: bool = False,
    ) -> Any:
        """Greedy generation with prompt-lookup self-speculation.

        Each step feeds [committed_next, draft_1..draft_k] through ONE
        forward and accepts the longest greedy-consistent prefix — so a
        run of k accepted tokens streams the weights ONCE instead of k
        times. For full-offload serving the step cost IS the weight
        stream (docs/PROFILE_r04.md: 88% of the host-link roofline), so
        effective tok/s scales with the mean accepted length — the
        policy lever the r4 profile names for bigger-than-HBM models.
        Exact: the output equals plain greedy decoding token for token
        (worst case accepts 1 token/step = standard decode).
        ref: the reference ecosystem's prompt-lookup/self-speculative
        decoding (MII generation path); arXiv 2304.04487-class
        draft-and-verify with the sequence as its own draft model.

        return_stats=True additionally returns a dict of per-run
        counters: steps, draft/accepted token totals, mean accepted
        length, draft_acceptance_rate (accepted draft tokens over
        proposed draft tokens), and draft_collapsed_steps — steps where the shared
        verify-row budget (max_batch_size // n_live) forced per_seq=1
        so k=0 and speculation degenerated to one-token decode. The
        first such step also logs a warning, so a silently-serial
        "speculative" run is visible to callers.

        Since the serving-scheduler PR the request lifecycle (admission,
        immediate EOS retirement + flush, preemption under KV pressure)
        runs through inference/scheduler.py ServingScheduler in
        speculative mode; verification still dispatches through
        self._verify_chunks. Exactness is unchanged."""
        from .scheduler import ServingScheduler, ServingSchedulerConfig

        if len(prompts) > self.config.max_batch_size:
            raise ValueError(
                f"{len(prompts)} prompts > max_batch_size "
                f"{self.config.max_batch_size} (every live sequence "
                "needs at least one verify row per step)")
        sched = ServingScheduler(
            self,
            ServingSchedulerConfig(prefill_mode="wave", warmup=False),
            seed=0,
            speculative={"ngram": int(ngram),
                         "draft_len": int(draft_len)})
        rids = [sched.submit(list(p), max_new_tokens, eos_token_id,
                             stream=i)
                for i, p in enumerate(prompts)]
        sched.run()
        outs = [sched.finished[r].output for r in rids]
        if return_stats:
            # one authority for the derived rates (mean_accepted,
            # draft_acceptance_rate): the scheduler's spec_summary —
            # the same numbers the router reports per replica
            return outs, sched.spec_summary()
        return outs

    # -- sampling (v1 generate inherits full HF sampling; here the same
    # -- knobs applied host-side over put() logits, ref:
    # -- inference/engine.py:613 generate → HF LogitsProcessor chain)
    @staticmethod
    def sample_token(
        logits: np.ndarray,
        *,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        repetition_penalty: float = 1.0,
        seen_tokens: Sequence[int] = (),
        rng: Optional[np.random.Generator] = None,
    ) -> int:
        """One next-token draw from a [V] float logits row.

        temperature <= 0 is greedy argmax. top_k/top_p filter before the
        softmax draw (both may combine). repetition_penalty follows the
        CTRL rule the reference inherits from HF: a seen token's logit is
        divided by the penalty when positive, multiplied when negative.
        """
        row = np.asarray(logits, np.float64).copy()
        if repetition_penalty != 1.0 and len(seen_tokens):
            idx = np.unique(np.asarray(list(seen_tokens), np.int64))
            pos = row[idx] > 0
            row[idx] = np.where(pos, row[idx] / repetition_penalty,
                                row[idx] * repetition_penalty)
        if temperature <= 0.0:
            return int(np.argmax(row))
        row = row / temperature
        if top_k and 0 < top_k < row.size:
            kth = np.partition(row, -top_k)[-top_k]
            row[row < kth] = -np.inf
        if 0.0 < top_p < 1.0:
            order = np.argsort(row)[::-1]
            probs = np.exp(row[order] - row[order[0]])
            probs /= probs.sum()
            keep = np.cumsum(probs) - probs < top_p  # always keep top-1
            row[order[~keep]] = -np.inf
        probs = np.exp(row - row.max())
        probs /= probs.sum()
        # v1-parity host sampler: callers that want replayable draws
        # pass `rng`; bare calls are explicitly best-effort
        # ds-lint: ok D004 best-effort path, rng param is the replayable route
        gen = rng if rng is not None else np.random.default_rng()
        return int(gen.choice(row.size, p=probs))

    # -- convenience generation (v1 engine.generate parity) --------------
    def generate(
        self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        repetition_penalty: float = 1.0,
        seed: Optional[int] = None,
        chunk: int = 8,
    ) -> List[List[int]]:
        """Continuous-batch generation; returns new tokens per prompt
        (ref: inference/engine.py generate:613).

        Rides FUSED multi-step decode: after the prefill, tokens are
        produced in compiled chunks of `chunk` steps — sampling
        (temperature/top-k/top-p/repetition-penalty, gumbel-max draw)
        runs INSIDE the decode program with per-sequence PRNG streams
        (key = fold_in(seed, uid), counter = token position), so the
        host sees only [chunk, batch] token ids per dispatch — never
        [batch, vocab] logits (round 3's per-step serving tax). The
        draw for a given (seed, uid, position) is independent of batch
        composition; a fixed seed reproduces the sequence exactly
        (tests/test_sampling.py replays it with a host oracle).

        top-p nucleus mass is computed over the top-256 candidates
        (sampling.SamplingConfig.cand_width) — exact whenever the
        nucleus fits, which at serving temperatures it does.

        uids are allocated disjoint from in-flight sequences so calling
        generate() never hijacks another caller's context.

        Since the serving-scheduler PR this is a thin wrapper over
        inference/scheduler.py ServingScheduler (prefill_mode='wave',
        decode_chunk=chunk): one control plane serves batch generation
        and online serving. Observable upgrades over the old loop: a
        sequence hitting EOS/length is FLUSHED at the iteration it
        finishes (its KV blocks rejoin the pool mid-batch instead of
        stranding until the last sequence drains), more prompts than
        max_batch_size queue instead of raising, and KV-block pressure
        preempts the youngest sequence for recompute instead of
        raising RuntimeError. Tokens are unchanged: draws are keyed by
        (seed, stream=slot, position), independent of scheduling."""
        from .scheduler import ServingScheduler, ServingSchedulerConfig

        # seed=None asks for a FRESH session seed; the drawn value then
        # becomes the session's (seed, stream, position) root, so
        # replay-with-the-returned-seed is exact
        # ds-lint: ok D004 fresh-seed request; replay threads the drawn seed
        seed_val = (int(np.random.default_rng().integers(2**31))
                    if seed is None else int(seed))
        sched = ServingScheduler(
            self,
            ServingSchedulerConfig(
                decode_chunk=max(1, int(chunk)),
                prefill_mode="wave",
                max_num_batched_tokens=max(
                    self.config.max_batch_size,
                    ServingSchedulerConfig().max_num_batched_tokens),
                warmup=False),
            sampling=dict(do_sample=do_sample, temperature=temperature,
                          top_k=top_k, top_p=top_p,
                          repetition_penalty=repetition_penalty),
            seed=seed_val)
        rids = [sched.submit(list(p), max_new_tokens, eos_token_id,
                             stream=i)
                for i, p in enumerate(prompts)]
        sched.run()
        return [sched.finished[r].output for r in rids]


def init_inference(
    params: Any,
    model_config: T.TransformerConfig,
    config: Optional[Dict[str, Any]] = None,
    dtype=jnp.bfloat16,
    quantization: Optional[Dict[str, Any]] = None,
    mesh: Optional[Mesh] = None,
    offload: Optional[Dict[str, Any]] = None,
) -> InferenceEngine:
    """Build the inference engine (ref: deepspeed/__init__.py
    init_inference:268 → InferenceEngine; config keys follow
    InferenceConfig). quantization={"bits": 8|4, "group_size": N}
    enables ZeRO-Inference weight-only PTQ.

    Tensor parallelism: pass an explicit mesh, config["tp_size"]=N, or
    the reference's spelling config["tensor_parallel"]={"tp_size": N}
    (ref: inference/config.py DeepSpeedTPConfig).

    Reference v1 config keys (ref: inference/config.py
    DeepSpeedInferenceConfig) are understood: `dtype` maps to the engine
    dtype ('int8' additionally enables weight PTQ), `max_out_tokens` →
    max_seq_len, kernel-injection/CUDA-graph knobs are no-ops on TPU
    (kernels are always the Pallas/XLA path), and `checkpoint` points to
    init_inference_from_hf."""
    cfg = dict(config or {})
    if "checkpoint" in cfg:
        raise NotImplementedError(
            "config['checkpoint']: load external checkpoints with "
            "init_inference_from_hf(path, ...) (HF safetensors/bin), or "
            "pass params restored via the TRAINING engine's "
            "load_checkpoint (runtime/engine.py) into init_inference"
        )
    if "injection_policy" in cfg or "injection_policy_tuple" in cfg:
        raise NotImplementedError(
            "injection_policy: TPU sharding is a rules table, not module "
            "surgery — override parallel/sharding.py rules instead"
        )
    dt = cfg.pop("dtype", None)
    if dt is not None:
        try:
            # dtype OBJECTS (jnp.bfloat16, np.float16, np.dtype(...)) —
            # the natural spellings in a JAX codebase
            name = np.dtype(dt).name
        except TypeError:
            # strings ('fp16') and torch.dtype reprs ('torch.float16')
            name = str(dt).split(".")[-1].lower()
        if name in ("int8",):
            # ZeRO-Inference weight-only PTQ is the int8 serving path
            quantization = quantization or {"bits": 8, "group_size": 128}
            dtype = jnp.bfloat16
        elif name in ("float16", "fp16", "half", "bfloat16", "bf16"):
            # fp16 serving maps to bf16 (TPU's 16-bit matmul format)
            dtype = jnp.bfloat16
        elif name in ("float32", "fp32", "float", "float64", "double"):
            # float64 spellings (np.dtype('float') → 'float64', torch
            # double) clamp to f32 — TPU has no f64 serving path
            dtype = jnp.float32
        else:
            raise ValueError(f"unsupported inference dtype {dt!r}")
    if "max_out_tokens" in cfg:
        mot = int(cfg.pop("max_out_tokens"))
        if "max_seq_len" in cfg and int(cfg["max_seq_len"]) != mot:
            raise ValueError(
                f"conflicting max_out_tokens ({mot}) and max_seq_len "
                f"({cfg['max_seq_len']}) in the inference config; drop one"
            )
        cfg["max_seq_len"] = mot
    for noop in ("replace_with_kernel_inject", "replace_method",
                 "enable_cuda_graph", "triangular_masking",
                 "use_triton", "triton_autotune"):
        if cfg.pop(noop, None):
            log_dist(
                f"inference config '{noop}' is a no-op on TPU (the "
                "Pallas/XLA kernels are always the serving path)",
                ranks=[0],
            )
    tp = cfg.pop("tensor_parallel", None)
    if tp is not None:
        if isinstance(tp, dict):
            size = int(tp.get("tp_size", 1))
            if not tp.get("enabled", True):
                size = 1
        else:
            size = int(tp)
        if "tp_size" in cfg and int(cfg["tp_size"]) != size:
            raise ValueError(
                f"conflicting tensor_parallel ({size}) and tp_size "
                f"({cfg['tp_size']}) in the inference config; drop one"
            )
        cfg["tp_size"] = size
    if "offload" in cfg:
        off = cfg.pop("offload")
        if offload is not None and offload != off:
            raise ValueError("conflicting offload in config and kwarg")
        offload = off
    icfg = InferenceConfig(**cfg)
    return InferenceEngine(model_config, params, icfg, dtype,
                           quantization=quantization, mesh=mesh,
                           offload=offload)


def init_inference_from_hf(
    path: str,
    config: Optional[Dict[str, Any]] = None,
    dtype=jnp.bfloat16,
    quantization: Optional[Dict[str, Any]] = None,
    mesh: Optional[Mesh] = None,
    offload: Optional[Dict[str, Any]] = None,
    **config_overrides,
) -> InferenceEngine:
    """Serve an HF-format checkpoint directory: import + init_inference
    (the build_hf_engine analog, ref: inference/v2/engine_factory.py:67).
    config_overrides adjust the derived TransformerConfig (e.g.
    attention_impl, use_flash).

    With offload={"device": "cpu"} the import is LAZY: layers stream
    from the checkpoint files one at a time straight into the
    pinned_host tier, so a checkpoint larger than free host-RAM
    headroom (let alone HBM) never materializes whole anywhere."""
    from ..utils.hf_checkpoint import import_external

    lazy = offload is not None or bool((config or {}).get("offload"))
    model_cfg, params = import_external(path, lazy_layers=lazy,
                                        **config_overrides)
    return init_inference(params, model_cfg, config, dtype,
                          quantization=quantization, mesh=mesh,
                          offload=offload)
