"""NvmeLayerStore serving-tier tests (inference/offload_store.py):
staging/read roundtrip and the _inflight lock — unordered io_callback
threads must never double-submit a layer (which would leak an unawaited
aio ticket and race two reads into one buffer). Host-side file I/O
only, so these run in the fast tier-1 lane."""

import threading

import numpy as np
import pytest

from deepspeed_tpu.inference.offload_store import NvmeLayerStore


def _store(tmp_path, n_layers=4, read_ahead=2):
    store = NvmeLayerStore(str(tmp_path), n_layers, n_threads=2,
                           read_ahead=read_ahead)
    rng = np.random.default_rng(0)
    layers = []
    for l in range(n_layers):
        lp = {"w": rng.normal(size=(8, 16)).astype(np.float32),
              "b": rng.normal(size=(16,)).astype(np.float32)}
        store.stage_layer(l, lp)
        layers.append(lp)
    store.finish_staging()
    return store, layers


class TestNvmeLayerStore:
    def test_roundtrip_and_prefetch_wraparound(self, tmp_path):
        store, layers = _store(tmp_path)
        try:
            for _ in range(2):  # cyclic decode walk
                for l in range(4):
                    got = store.read_layer(l)
                    np.testing.assert_array_equal(got["w"], layers[l]["w"])
                    np.testing.assert_array_equal(got["b"], layers[l]["b"])
        finally:
            store.close()

    def test_concurrent_unordered_reads_no_double_submit(self, tmp_path):
        """Hammer read_layer from many threads in arbitrary layer order
        — the lock must keep every read correct with no leaked tickets
        (close() drains what remains without error)."""
        store, layers = _store(tmp_path, n_layers=6, read_ahead=3)
        errors = []
        barrier = threading.Barrier(8)

        def worker(seed):
            rng = np.random.default_rng(seed)
            try:
                barrier.wait(timeout=30)
                for _ in range(25):
                    l = int(rng.integers(0, 6))
                    got = store.read_layer(l)
                    if not np.array_equal(got["w"], layers[l]["w"]):
                        raise AssertionError(f"layer {l} read corrupt")
            except Exception as e:  # surface across the thread boundary
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        store.close()

    def test_read_after_close_raises(self, tmp_path):
        store, _ = _store(tmp_path)
        store.close()
        with pytest.raises(RuntimeError, match="closed"):
            store.read_layer(0)
        store.close()  # idempotent
